"""Bounded, priority-ordered, deadline-aware admission queue.

Replaces the raw FIFO between the RPC threads and the
:class:`~karpenter_tpu.service.server.SolvePipeline` dispatcher.  Three
properties the FIFO lacked:

- **Bounded** — per-class and total depth quotas; a full queue rejects the
  arrival (or preempts a strictly lower class) instead of growing latency
  without bound.
- **Priority-ordered** — the dispatcher pops ``(class rank, arrival seq)``,
  so within a megabatch window higher classes fill slots first and FIFO
  order is preserved within a class.
- **Deadline-aware** — every ticket carries an absolute enqueue deadline;
  the dispatcher rejects expired tickets *before* tensorize/dispatch, so
  timed-out work never burns a device round trip.

This module owns only the *mechanism*: it reports rejection reasons and
preempted tickets to the caller and never raises shed errors or touches
metrics itself — the accounting (``karpenter_admission_shed_total``) lives
with :class:`~karpenter_tpu.admission.AdmissionControl`, the single layer
ktlint KT009 audits for uncounted rejections.

Multi-producer (RPC threads) / single-consumer (the pipeline dispatcher);
all state is condition-guarded.  Clocked through the injectable
:class:`~karpenter_tpu.utils.clock.Clock` (KT002).
"""

from __future__ import annotations

import heapq
import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.clock import Clock
from .policy import AdmissionPolicy, rank

_SEQ = itertools.count(1)


@dataclass
class AdmissionTicket:
    """One admitted request as the queue tracks it.  ``item`` is opaque to
    the queue (the pipeline's ``(kwargs, future, ...)`` tuple)."""

    item: object
    pclass: str
    enqueued_at: float
    deadline: Optional[float]           #: absolute queue-clock time, or None
    seq: int = field(default_factory=lambda: next(_SEQ))
    shed: bool = False                  #: set under the queue lock on preempt
    released: bool = False              #: concurrency slot returned (control)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline

    def sort_key(self) -> Tuple[int, int]:
        return (rank(self.pclass), self.seq)


class AdmissionQueue:
    """See module docstring.  ``put`` returns ``(ticket, reason,
    preempted)``: ``ticket`` is None exactly when ``reason`` names the
    rejection (``"queue_full"``); ``preempted`` lists tickets this
    admission evicted (their futures are the caller's to fail)."""

    def __init__(
        self,
        policy: Optional[AdmissionPolicy] = None,
        clock: Optional[Clock] = None,
        on_depth: Optional[Callable[[str, int], None]] = None,
    ) -> None:
        self.policy = policy or AdmissionPolicy()
        self.clock = clock or Clock()
        self._on_depth = on_depth
        self._cond = threading.Condition()
        self._heap: List[Tuple[Tuple[int, int], AdmissionTicket]] = []  # guarded-by: _cond
        self._depths: Dict[str, int] = {}                               # guarded-by: _cond

    def __len__(self) -> int:
        with self._cond:
            return sum(self._depths.values())

    def depth(self, pclass: str) -> int:
        with self._cond:
            return self._depths.get(pclass, 0)

    def _bump(self, pclass: str, delta: int) -> None:
        # Condition wraps an RLock, so re-acquiring under a holding caller
        # is free — and keeps the lock discipline lexical (KT004)
        with self._cond:
            self._depths[pclass] = self._depths.get(pclass, 0) + delta
            if self._on_depth is not None:
                self._on_depth(pclass, self._depths[pclass])

    def put(
        self, item: object, pclass: str, deadline: Optional[float] = None,
        gate=None,
    ) -> Tuple[Optional[AdmissionTicket], Optional[str],
               List[AdmissionTicket]]:
        """Admit or reject one item.  ``gate()`` (optional) is the caller's
        LAST admission check — e.g. the class token bucket — consulted
        inside the critical section only after every capacity check has
        passed, so a request the queue was going to reject anyway never
        spends a token; it returns a rejection reason or None.  A victim
        is preempted only after the gate passes, for the same reason."""
        quota = self.policy.quota(pclass)
        ticket = AdmissionTicket(
            item=item, pclass=pclass, enqueued_at=self.clock.now(),
            deadline=deadline,
        )
        preempted: List[AdmissionTicket] = []
        with self._cond:
            if (quota.max_queue_depth > 0
                    and self._depths.get(pclass, 0) >= quota.max_queue_depth):
                return None, "queue_full", preempted
            victim = None
            if sum(self._depths.values()) >= self.policy.max_queue_total:
                victim = self._victim(rank(pclass))
                if victim is None:
                    return None, "queue_full", preempted
            if gate is not None:
                reason = gate()
                if reason is not None:
                    return None, reason, preempted
            if victim is not None:
                victim.shed = True          # lazily removed from the heap
                self._bump(victim.pclass, -1)
                preempted.append(victim)
            heapq.heappush(self._heap, (ticket.sort_key(), ticket))
            self._bump(pclass, +1)
            self._cond.notify()
        return ticket, None, preempted

    def _victim(self, arriving_rank: int) -> Optional[AdmissionTicket]:
        """Newest queued ticket of the LOWEST class strictly below the
        arrival.  None when nothing outranks."""
        victim: Optional[AdmissionTicket] = None
        with self._cond:
            for _key, t in self._heap:
                if t.shed or rank(t.pclass) <= arriving_rank:
                    continue
                if (victim is None or rank(t.pclass) > rank(victim.pclass)
                        or (t.pclass == victim.pclass and t.seq > victim.seq)):
                    victim = t
        return victim

    def get(self, timeout: Optional[float] = None) -> Optional[AdmissionTicket]:
        """Pop the highest-priority live ticket (FIFO within a class), or
        None after ``timeout``.  Preempted (shed) tickets are skipped —
        their futures were already failed by the preempting ``put``."""
        with self._cond:
            while True:
                while self._heap and self._heap[0][1].shed:
                    heapq.heappop(self._heap)
                if self._heap:
                    _key, ticket = heapq.heappop(self._heap)
                    self._bump(ticket.pclass, -1)
                    return ticket
                if timeout is not None and timeout <= 0:
                    return None
                if not self._cond.wait(timeout):
                    # timed out; one last sweep in case notify raced the wait
                    while self._heap and self._heap[0][1].shed:
                        heapq.heappop(self._heap)
                    if not self._heap:
                        return None

    def drain(self) -> List[AdmissionTicket]:
        """Pop everything still queued (shutdown path) — the caller fails
        each ticket's future so no RPC thread is stranded."""
        out: List[AdmissionTicket] = []
        with self._cond:
            for _key, t in self._heap:
                if not t.shed:
                    out.append(t)
                    self._bump(t.pclass, -1)
            self._heap.clear()
        out.sort(key=AdmissionTicket.sort_key)
        return out
