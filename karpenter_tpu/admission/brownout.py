"""Brownout: load-responsive degradation ladder.

Under overload the service must degrade solve *quality/latency* for low
priority classes instead of failing high ones — CvxCluster's tiered
solve-quality-vs-latency tradeoff, applied at the serving boundary.  The
driving signal is the admission queue-delay EWMA (how long admitted
requests wait before the dispatcher picks them up); as it climbs through
the rung thresholds the controller steps down a ladder of increasingly
lossy mitigations, and steps back up with hysteresis as the delay drains:

====  ==========================================================
rung  mitigation
====  ==========================================================
1     shrink the coalescer max-wait to 0 (stop holding batches
      open for stragglers; flush the moment the queue idles)
2     cap megabatch slots (bound one flush's latency footprint)
3     route ``best_effort`` to the host FFD ``reference`` solver
      (device capacity reserved for critical/batch)
4     shed ``best_effort`` at admission (RESOURCE_EXHAUSTED)
====  ==========================================================

Knobs: ``KT_BROWNOUT_MS`` — rung-1 threshold, milliseconds (default 2000;
rung *n* engages at ``2^(n-1)`` times it; 0 disables the ladder);
``KT_BROWNOUT_ALPHA`` — EWMA smoothing (default 0.2);
``KT_BROWNOUT_SLOT_CAP`` — the rung-2 slot cap (default 2).

Single-writer by contract: the pipeline dispatcher owns ``observe`` (like
``SlotCoalescer``); readers (statusz) see the gauge.  Clocked through the
injectable Clock (KT002).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..metrics import (
    ADMISSION_BROWNOUT_LEVEL,
    Registry,
    registry as default_registry,
)
from ..utils.clock import Clock
from .policy import BEST_EFFORT, _env_float, rank

logger = logging.getLogger(__name__)

#: number of rungs on the ladder
MAX_LEVEL = 4

#: the idle-tick cadence the per-observation ``alpha`` was calibrated to
#: (the dispatcher's 100ms idle poll): :meth:`BrownoutController.idle`
#: decays by elapsed TIME at exactly the rate ``observe(0.0)`` decayed
#: per tick at this cadence, so real-time behavior is unchanged while a
#: stalled or FakeClock'd dispatcher no longer pins the ladder at its
#: last loaded rung (ISSUE 19 satellite bugfix)
IDLE_TICK_REF_S = 0.1


class BrownoutController:
    def __init__(
        self,
        step_s: Optional[float] = None,
        alpha: Optional[float] = None,
        slot_cap: Optional[int] = None,
        registry: Optional[Registry] = None,
        clock: Optional[Clock] = None,
    ) -> None:
        if step_s is None:
            step_s = _env_float("KT_BROWNOUT_MS", 2000.0) / 1000.0
        if alpha is None:
            alpha = _env_float("KT_BROWNOUT_ALPHA", 0.2)
        if slot_cap is None:
            slot_cap = int(_env_float("KT_BROWNOUT_SLOT_CAP", 2))
        self.step_s = step_s
        self.alpha = min(1.0, max(0.01, alpha))
        self._slot_cap = max(1, slot_cap)
        self.registry = registry or default_registry
        self.clock = clock or Clock()
        self.ewma_s = 0.0
        self._level = 0
        #: last observation/decay stamp on the injected clock — the
        #: idle-decay path is TIME-based, not tick-counted
        self._last_at: Optional[float] = None
        self.registry.gauge(ADMISSION_BROWNOUT_LEVEL).set(0)

    @property
    def enabled(self) -> bool:
        return self.step_s > 0

    @property
    def level(self) -> int:
        return self._level

    def threshold(self, level: int) -> float:
        """Queue-delay EWMA at which ``level`` engages."""
        return self.step_s * (2 ** (level - 1))

    def observe(self, wait_s: float) -> int:
        """Fold one queue wait (or an idle tick's 0.0 — the decay path)
        into the EWMA and recompute the rung.  Engaging is immediate at
        the rung threshold; disengaging requires the EWMA to fall below
        HALF the rung's threshold (hysteresis, so the ladder doesn't
        flap at a boundary).  Returns the new level."""
        if not self.enabled:
            return 0
        self._last_at = self.clock.now()
        self.ewma_s += self.alpha * (max(0.0, wait_s) - self.ewma_s)
        return self._reeval()

    def idle(self, now: Optional[float] = None) -> int:
        """Idle-tick decay, by ELAPSED TIME on the injected clock.

        The old path folded a fixed-alpha 0.0 sample per tick, which
        tied the decay rate to the dispatcher's real-time poll cadence:
        a stalled dispatcher (wedged fence, debugger) or a FakeClock
        harness left the ladder stuck at its last loaded rung until the
        next request.  Here the EWMA decays by ``(1-alpha)`` per
        :data:`IDLE_TICK_REF_S` of elapsed clock time — identical to the
        old behavior at the dispatcher's nominal 10Hz idle cadence, and
        cadence-independent everywhere else.  Returns the new level."""
        if not self.enabled:
            return 0
        if now is None:
            now = self.clock.now()
        if self._last_at is None:
            self._last_at = now
            return self._level
        dt = max(0.0, now - self._last_at)
        self._last_at = now
        if dt > 0.0 and self.ewma_s > 0.0:
            self.ewma_s *= (1.0 - self.alpha) ** (dt / IDLE_TICK_REF_S)
        return self._reeval()

    def retune(self, step_s: Optional[float] = None,
               slot_cap: Optional[int] = None) -> None:
        """Live knob application (tuning registry, ISSUE 19): move the
        ladder's threshold scale and/or rung-2 slot cap, then requantize
        the rung against the UNCHANGED EWMA — the dispatcher calls this
        under its scheduler lock, so a mid-evaluation retune can never
        tear a decision."""
        changed = False
        if step_s is not None and step_s != self.step_s:
            self.step_s = step_s
            changed = True
        if slot_cap is not None:
            self._slot_cap = max(1, int(slot_cap))
        if changed and self.enabled:
            self._reeval()

    def _reeval(self) -> int:
        """Requantize the rung from the current EWMA: engage at the rung
        threshold, disengage below HALF of it (hysteresis)."""
        level = self._level
        while level < MAX_LEVEL and self.ewma_s >= self.threshold(level + 1):
            level += 1
        while level > 0 and self.ewma_s < self.threshold(level) / 2.0:
            level -= 1
        if level != self._level:
            logger.warning(
                "brownout %s: level %d -> %d (queue-delay EWMA %.0fms)",
                "escalating" if level > self._level else "recovering",
                self._level, level, self.ewma_s * 1000.0)
            self._level = level
            self.registry.gauge(ADMISSION_BROWNOUT_LEVEL).set(level)
        return self._level

    # ---- ladder effects (read by the pipeline dispatcher) ---------------
    def max_wait(self, base_s: float) -> float:
        """Rung 1+: stop holding partial batches open for stragglers."""
        return 0.0 if self._level >= 1 else base_s

    def slot_cap(self, base_slots: int) -> int:
        """Rung 2+: bound one megabatch flush's latency footprint."""
        if self._level >= 2:
            return max(1, min(base_slots, self._slot_cap))
        return base_slots

    def route_to_host(self, pclass: str) -> bool:
        """Rung 3+: low classes solve on the host FFD tier, reserving
        device capacity for critical/batch."""
        return self._level >= 3 and rank(pclass) >= rank(BEST_EFFORT)

    def shed(self, pclass: str) -> bool:
        """Rung 4: low classes are shed at admission."""
        return self._level >= MAX_LEVEL and rank(pclass) >= rank(BEST_EFFORT)
