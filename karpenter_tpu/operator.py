"""Operator runtime — process bootstrap, controller wiring, run loop.

The cmd/controller/main.go + core operator.NewOperator analog (SURVEY.md
§3.1): builds the cloud provider, wraps it in the metrics decorator, registers
every controller, exposes /metrics and /healthz over HTTP, and drives the
reconcile loops.  Leader election is LEASE-based (the coordination.k8s.io
Lease analog — reference settings.md:23, LEADER_ELECT): replicas contend on
a pluggable LeaseStore, the holder renews every tick, a standby acquires
when the lease expires, and leadership gates cache hydration exactly like
launchtemplate.go:77-88 — hydration re-runs on every (re-)election, which is
the resume-from-cloud-state posture (SURVEY §5 checkpoint/resume).

Run a self-contained simulation:  ``python -m karpenter_tpu.operator --demo``
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from .batcher import Window
from .cache import UnavailableOfferings
from .cloud.base import CloudProvider
from .cloud.fake import FakeCloudProvider
from .controllers.deprovisioning import DeprovisioningController
from .controllers.garbagecollect import GarbageCollectController, LinkController
from .controllers.interruption import InterruptionController, MessageQueue
from .controllers.nodetemplate import NodeTemplateController
from .controllers.provisioning import ProvisioningController
from .controllers.state import ClusterState
from .controllers.termination import TerminationController
from .events import Recorder
from .metrics import Registry, decorate, registry as default_registry
from .models.catalog import generate_catalog
from .models.pod import PodSpec
from .models.provisioner import Provisioner
from .obs import FlightRecorder, Tracer
from .obs import export as obs_export
from .providers.pricing import PricingProvider
from .providers.securitygroup import SecurityGroupProvider
from .providers.subnet import SubnetProvider
from .settings import Settings, SettingsStore
from .solver.scheduler import BatchScheduler
from .utils.clock import Clock


@dataclass
class Lease:
    """One leadership lease record (coordination.k8s.io/Lease analog)."""

    holder: str
    renewed_at: float
    ttl: float

    def expired(self, now: float) -> bool:
        return now >= self.renewed_at + self.ttl


class InMemoryLeaseStore:
    """Pluggable lease store.  Contending Operator replicas share one store;
    a real deployment plugs a kube-API-backed implementation with the same
    two-method surface.  ``try_acquire`` is atomic: it renews for the current
    holder, grants an unheld/expired lease, and refuses a live one."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._leases: dict = {}

    def get(self, name: str) -> Optional[Lease]:
        with self._lock:
            return self._leases.get(name)

    def try_acquire(self, name: str, holder: str, ttl: float, now: float) -> bool:
        with self._lock:
            cur = self._leases.get(name)
            if cur is not None and cur.holder != holder and not cur.expired(now):
                return False
            self._leases[name] = Lease(holder, now, ttl)
            return True

    def release(self, name: str, holder: str) -> None:
        with self._lock:
            cur = self._leases.get(name)
            if cur is not None and cur.holder == holder:
                del self._leases[name]


def _default_identity() -> str:
    """Unique per elector instance ACROSS processes: two replicas sharing a
    real (pluggable) lease store must never collide on a default identity,
    or try_acquire would grant both (holder == holder) and split-brain."""
    import uuid

    return f"operator-{uuid.uuid4().hex[:8]}"


class LeaderElector:
    """Lease-based leadership (operator.Elected() analog, settings.md:23).

    Each tick the elector tries to acquire-or-renew the lease: the holder
    stays elected, a standby takes over once the lease TTL lapses without a
    renewal (leader crashed / partitioned), and a deposed holder steps down.
    ``on_elected`` callbacks fire on every False->True transition — i.e. on
    takeover too, so hydration re-runs and the new leader resumes from cloud
    state.  ``elect`` (optional) is an extra gate retained for tests."""

    DEFAULT_TTL = 15.0

    def __init__(
        self,
        elect: Optional[Callable[[], bool]] = None,
        *,
        identity: Optional[str] = None,
        store: Optional[InMemoryLeaseStore] = None,
        lease_name: str = "karpenter-tpu-leader",
        lease_ttl: float = DEFAULT_TTL,
        clock: Optional[Clock] = None,
    ) -> None:
        self._elect = elect
        self.identity = identity or _default_identity()
        self.store = store or InMemoryLeaseStore()
        self.lease_name = lease_name
        self.lease_ttl = lease_ttl
        self.clock = clock or Clock()
        self.elected = False
        self._on_elected: List[Callable[[], None]] = []

    def on_elected(self, fn: Callable[[], None]) -> None:
        self._on_elected.append(fn)

    def tick(self) -> bool:
        if self._elect is not None and not self._elect():
            # gate closed: step down AND release the lease so a healthy
            # standby takes over immediately instead of waiting out the TTL
            self.resign()
            return False
        won = self.store.try_acquire(
            self.lease_name, self.identity, self.lease_ttl, self.clock.now()
        )
        if won and not self.elected:
            self.elected = True
            for fn in self._on_elected:
                fn()
        elif not won:
            self.elected = False  # deposed: stop reconciling immediately
        return self.elected

    def resign(self) -> None:
        """Release the lease (clean shutdown / gate-down) so a standby takes
        over without waiting out the TTL.  Safe to call when not holding —
        the store only deletes a lease naming this identity."""
        self.store.release(self.lease_name, self.identity)
        self.elected = False


class Operator:
    def __init__(
        self,
        cloud: CloudProvider,
        clock: Optional[Clock] = None,
        settings: Optional[SettingsStore] = None,
        registry: Optional[Registry] = None,
        scheduler_backend: str = "auto",
        metrics_port: int = 0,  # 0 disables the HTTP server
        lease_store: Optional[InMemoryLeaseStore] = None,
        identity: Optional[str] = None,
        solver_address: str = "",  # host:port of a solver sidecar; "" = in-process
    ) -> None:
        self.clock = clock or Clock()
        self.settings = settings or SettingsStore()
        self.registry = registry or default_registry
        # observability spine (docs/OBSERVABILITY.md): one tracer + flight
        # recorder per operator, on the operator's clock/registry; events
        # feed the flight recorder's ring so anomaly dumps carry them
        self.flight = FlightRecorder(clock=self.clock, registry=self.registry)
        self.tracer = Tracer(clock=self.clock, registry=self.registry,
                             flight=self.flight)
        self.recorder = Recorder(sink=self.flight.add_event)
        self.elector = LeaderElector(
            identity=identity, store=lease_store, clock=self.clock
        )
        self.metrics_port = metrics_port

        self.state = ClusterState(clock=self.clock)
        # request coalescing under the metrics decorator, like the
        # reference's pkg/batcher sits inside the provider under
        # core's metrics.Decorate (cmd/controller/main.go:46).
        # idle_seconds=0: the operator tick is single-threaded, so waiting
        # for peers would only add dead latency; coalescing engages for
        # concurrent callers (e.g. the gRPC solver service threads).
        from .cloud.batched import BatchedCloud

        self.cloud = decorate(BatchedCloud(cloud, idle_seconds=0.0), self.registry)
        self.cloud.configure_settings(self.settings.current)
        self.unavailable = UnavailableOfferings(clock=self.clock)
        if solver_address:
            # split topology (deploy/operator.yaml + deploy/solver.yaml): the
            # sidecar owns tensorization + the device mesh; this process only
            # reconciles.  The reference consumes its remote boundary the
            # same way (cmd/controller/main.go:44).  Falls back to a local
            # oracle solve while the sidecar is unreachable.
            from .admission import CRITICAL
            from .service.client import RemoteScheduler

            deadline_ms = float(
                os.environ.get("KT_SOLVER_DEADLINE_MS", "0") or 0.0)
            self.scheduler = RemoteScheduler(
                solver_address,
                backend="" if scheduler_backend == "auto" else scheduler_backend,
                registry=self.registry,
                # the provisioning reconcile loop is the service's highest
                # class: never shed while lower classes can absorb, fills
                # megabatch slots first (docs/ADMISSION.md)
                priority=CRITICAL,
                deadline_s=(deadline_ms / 1000.0) if deadline_ms > 0 else None,
                # availability first: the reconcile loop has no backoff
                # story, so a (rare) shed of critical traffic is logged
                # and served from the local fallback instead of raising
                # through tick() and killing the operator
                shed_fallback=True,
            )
        else:
            self.scheduler = BatchScheduler(backend=scheduler_backend,
                                            registry=self.registry,
                                            tracer=self.tracer)
        s = self.settings.current
        self.pricing = PricingProvider(
            cloud.get_instance_types(), clock=self.clock,
            isolated_vpc=s.isolated_vpc,
        )
        self.subnets = SubnetProvider()
        self.security_groups = SecurityGroupProvider(clock=self.clock)
        self.queue = MessageQueue()
        self.provisioning = ProvisioningController(
            self.state, self.cloud, scheduler=self.scheduler, recorder=self.recorder,
            registry=self.registry, unavailable=self.unavailable, clock=self.clock,
            idle_seconds=s.batch_idle_duration, max_seconds=s.batch_max_duration,
            tracer=self.tracer,
        )
        self.termination = TerminationController(
            self.state, self.cloud, recorder=self.recorder,
            registry=self.registry, clock=self.clock,
        )
        self.deprovisioning = DeprovisioningController(
            self.state, self.cloud, self.termination, provisioning=self.provisioning,
            scheduler=self.scheduler, recorder=self.recorder, registry=self.registry,
            clock=self.clock, drift_enabled=s.drift_enabled,
            deprovisioning_ttl=s.deprovisioning_ttl,
            tracer=self.tracer,
        )
        self.interruption = InterruptionController(
            self.state, self.termination, self.queue, unavailable=self.unavailable,
            recorder=self.recorder, registry=self.registry, clock=self.clock,
        )
        self.gc = GarbageCollectController(self.state, self.cloud, recorder=self.recorder, clock=self.clock)
        self.link = LinkController(self.state, self.cloud, recorder=self.recorder, clock=self.clock)
        self.nodetemplates = NodeTemplateController(self.subnets, self.security_groups, clock=self.clock)

        self.settings.subscribe(self._on_settings)
        self.elector.on_elected(self._hydrate)
        self._http: Optional[ThreadingHTTPServer] = None
        self._stop = threading.Event()
        #: serializes the reconcile tick against HTTP-thread config applies
        self._reconcile_lock = threading.RLock()

    # ---- wiring ---------------------------------------------------------
    def _on_settings(self, s: Settings) -> None:
        self.cloud.configure_settings(s)
        self.provisioning.window = Window(
            s.batch_idle_duration, s.batch_max_duration, clock=self.clock
        )
        self.deprovisioning.drift_enabled = s.drift_enabled
        self.deprovisioning.deprovisioning_ttl = s.deprovisioning_ttl
        self.pricing.isolated_vpc = s.isolated_vpc
        if self.elector.elected:
            # settings can reshape the catalog (pod density, pod-ENI) and
            # thus the solver tensor shapes: re-warm the compile ladder
            self._warm_solver()

    def _hydrate(self) -> None:
        """Leadership-gated warm-state rebuild (SURVEY §5 checkpoint/resume):
        re-adopt orphaned instances, refresh prices, and start the solver
        shape warmup so the first real batches never stall on an XLA
        compile (compile-behind covers shapes outside the warmed ladder)."""
        self.link.reconcile()
        self.pricing.maybe_refresh()
        self._warm_solver()

    def _warm_solver(self, wait: bool = False) -> None:
        provs = [p.with_defaults() for p in self.state.provisioners.values()]
        # in-process schedulers warm the full bucket grid (single-solve
        # ladder + megabatch slot rungs); the RemoteScheduler facade only
        # has warm_startup — the sidecar owns its own rungs (serve --warmup)
        warm = getattr(self.scheduler, "precompile_buckets", None)
        kwargs = {} if warm is None else {"wait": wait}
        try:
            (warm or self.scheduler.warm_startup)(
                provs or [Provisioner(name="default").with_defaults()],
                self.cloud.get_instance_types(),
                daemonsets=self.state.daemonsets,
                existing_nodes=[n.snapshot()
                                for n in self.state.schedulable_nodes()],
                **kwargs,
            )
        except Exception:  # warmup is best-effort; solves fall back warm
            logging.getLogger(__name__).warning(
                "solver warmup failed; compile-behind will cover", exc_info=True
            )

    # ---- declarative config / admission ---------------------------------
    def apply_manifests(self, path) -> tuple:
        """Load YAML manifests (file or directory) through admission into
        the operator: Provisioners + NodeTemplates + global settings.
        Raises AdmissionError on any invalid document."""
        from .manifests import apply_path

        # attribute access passes through the metrics decorator and the
        # batching wrapper to the real provider (tests: provider attrs
        # pass through), so .templates reaches the provider's dict
        with self._reconcile_lock:
            return apply_path(
                path, state=self.state, cloud=self.cloud,
                settings_store=self.settings,
            )

    def admit_http(self, raw_body: str, *, apply: bool = False):
        """One admission review over HTTP: parse the YAML/JSON body, run it
        through the webhook layer, return (http_status, response_dict) with
        a structured allow/deny — the knative admission-response analog."""
        import yaml as _yaml

        from .manifests import admit_documents
        from .webhooks import AdmissionError

        try:
            docs = [d for d in _yaml.safe_load_all(raw_body) if d]
        except _yaml.YAMLError as err:
            return 400, {"allowed": False,
                         "errors": [f"unparseable document: {err}"]}
        if not docs:
            return 400, {"allowed": False, "errors": ["empty request body"]}
        try:
            provs, templates, overrides, storage = admit_documents(
                docs, current_settings=self.settings.current
            )
        except AdmissionError as err:
            return 422, {"allowed": False, "kind": err.kind,
                         "name": err.name, "errors": err.errors}
        if not provs and not templates and not overrides and not storage:
            kinds = sorted({str(d.get("kind", "?")) for d in docs})
            return 400, {"allowed": False,
                         "errors": [f"no recognized documents (kinds: {kinds})"]}
        if apply:
            from .manifests import apply_objects

            try:
                # under the reconcile lock: the HTTP worker thread must not
                # mutate state dicts mid-tick (dictionary-changed-size), and
                # a tick must never observe a half-applied config
                with self._reconcile_lock:
                    apply_objects(provs, templates, overrides, storage,
                                  state=self.state, cloud=self.cloud,
                                  settings_store=self.settings)
            except AdmissionError as err:
                return 422, {"allowed": False, "kind": err.kind,
                             "name": err.name, "errors": err.errors}
        return 200, {
            "allowed": True,
            "admitted": {
                "provisioners": [p.name for p in provs],
                "node_templates": [t.name for t in templates],
                "settings_keys": sorted(overrides),
                "storage_objects": [getattr(s, "name", "?") for s in storage],
            },
            "applied": bool(apply),
        }

    # ---- health / metrics -----------------------------------------------
    def healthz(self) -> bool:
        return self.cloud.liveness() and self.pricing.liveness_ok()

    def start_http(self) -> Optional[int]:
        if self.metrics_port == 0:
            return None
        op = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def do_GET(self):
                ctype = None
                if self.path == "/metrics":
                    body = op.registry.expose().encode()
                    self.send_response(200)
                elif self.path == "/healthz":
                    ok = op.healthz()
                    body = (b"ok" if ok else b"unhealthy")
                    self.send_response(200 if ok else 503)
                elif self.path.startswith("/tracez"):
                    # recent traces + per-span p50/p99 (obs/export.py)
                    body = json.dumps(obs_export.tracez(op.flight),
                                      default=str).encode()
                    ctype = "application/json"
                    self.send_response(200)
                elif self.path.startswith("/statusz"):
                    body = json.dumps(
                        obs_export.statusz(op.registry, op.flight),
                        default=str).encode()
                    ctype = "application/json"
                    self.send_response(200)
                elif self.path.startswith("/fleetz"):
                    # fleet-merged view (ISSUE 15, obs/fleet.py): fans out
                    # to the solver replicas' obs endpoints (KT_OBS_PEERS)
                    # and merges load/ownership/trace trees — the operator
                    # mounts the same document the solver sidecars serve,
                    # with ITS hops (the "remote" spans the reconciler
                    # cut) contributed from memory
                    from karpenter_tpu.obs import fleet as obs_fleet

                    body = json.dumps(
                        obs_fleet.fleetz(obs_fleet.env_peers(),
                                         local=(op.registry, op.flight,
                                                None)),
                        default=str).encode()
                    ctype = "application/json"
                    self.send_response(200)
                else:
                    body = b"not found"
                    self.send_response(404)
                if ctype:
                    self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                # admission endpoints (the knative webhook-server analog,
                # pkg/webhooks/webhooks.go:33-63): POST a YAML/JSON manifest,
                # get a structured allow/deny.  /admission/validate judges
                # only; /admission/apply admits AND applies to the operator.
                if self.path not in ("/admission/validate", "/admission/apply"):
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length).decode()
                except (ValueError, UnicodeDecodeError) as err:
                    status, body = 400, {"allowed": False,
                                         "errors": [f"unreadable body: {err}"]}
                else:
                    status, body = op.admit_http(
                        raw, apply=self.path.endswith("/apply")
                    )
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._http = ThreadingHTTPServer(("127.0.0.1", self.metrics_port), Handler)
        port = self._http.server_address[1]
        threading.Thread(target=self._http.serve_forever, daemon=True).start()
        return port

    def stop_http(self) -> None:
        if self._http:
            self._http.shutdown()
            self._http = None

    # ---- loop -----------------------------------------------------------
    def tick(self) -> None:
        """One pass over every controller (singleton-controller semantics)."""
        with self._reconcile_lock:
            self._tick_locked()

    def _tick_locked(self) -> None:
        if not self.elector.tick():
            return
        if self.settings.current.interruption_queue_name:
            # interruption handling is enabled iff a queue is configured
            # (settings.md; pkg/controllers/controllers.go gates the same way)
            self.interruption.reconcile()
        self.provisioning.reconcile()
        self.deprovisioning.reconcile()
        self.termination.reconcile()
        self.nodetemplates.reconcile()
        self.gc.reconcile()
        self.pricing.maybe_refresh()

    def run(self, interval: float = 1.0, max_ticks: Optional[int] = None) -> None:
        n = 0
        while not self._stop.is_set():
            self.tick()
            n += 1
            if max_ticks is not None and n >= max_ticks:
                break
            self.clock.sleep(interval)

    def shutdown(self) -> None:
        self._stop.set()
        # under the reconcile lock: an in-flight tick on another thread must
        # not re-acquire the lease right after the resign (the lock orders
        # resign after that tick; _stop stops any further ones)
        with self._reconcile_lock:
            self.elector.resign()  # standby takes over without waiting the TTL
        self.scheduler.stop_warms()  # don't drain queued compiles at exit
        close = getattr(self.scheduler, "close", None)
        if close is not None:  # RemoteScheduler: release the gRPC channel
            close()
        self.stop_http()


def _demo(args) -> None:
    """Self-contained scale-up/scale-down simulation against the fake cloud."""
    from .utils.clock import FakeClock

    clock = FakeClock()
    cloud = FakeCloudProvider(generate_catalog(full=not args.small), clock=clock)
    op = Operator(cloud, clock=clock, scheduler_backend=args.backend,
                  metrics_port=args.metrics_port,
                  solver_address=getattr(args, "solver_address", ""))
    port = op.start_http()
    if port:
        print(f"metrics on http://127.0.0.1:{port}/metrics")
    if getattr(args, "config", None):
        # declarative scenario: every Provisioner/NodeTemplate/setting comes
        # from YAML through admission — nothing constructed in code
        provs, templates, overrides = op.apply_manifests(args.config)
        print(f"manifests: {len(provs)} provisioner(s), "
              f"{len(templates)} node template(s), "
              f"{len(overrides)} setting override(s) admitted from {args.config}")
    else:
        op.state.apply_provisioner(
            Provisioner(name="default", consolidation_enabled=True)
        )
    if getattr(args, "warmup", False):
        # blocking AOT bucket-grid precompile before traffic: the demo's
        # first solves then never see a cold program OR a warm-tier serve
        print("warmup: blocking bucket-grid precompile...")
        op._warm_solver(wait=True)

    print(f"scale-up: {args.pods} pods")
    for i in range(args.pods):
        op.state.add_pod(PodSpec(
            name=f"pod-{i}", requests={"cpu": 0.5 + (i % 4) * 0.5}, owner_key=f"d{i%5}",
        ))
    for _ in range(4):
        op.tick()
        clock.advance(1.0)
    cost = sum(ns.node.price for ns in op.state.nodes.values())
    print(f"  -> {len(op.state.nodes)} nodes, ${cost:.2f}/hr, "
          f"pending={len(op.state.pending_pods())}")

    print("scale-down: deleting 70% of pods")
    for i in range(0, int(args.pods * 0.7)):
        op.state.delete_pod(f"pod-{i}")
    clock.advance(6 * 60)
    # enough sim time for propose -> 15s validation TTL -> execute cycles
    for _ in range(10):
        op.tick()
        clock.advance(4.0)
    for _ in range(8):  # settle: rebind pods evicted by the last action
        if not op.state.pending_pods():
            break
        op.tick()
        clock.advance(2.0)
    cost2 = sum(ns.node.price for ns in op.state.nodes.values())
    print(f"  -> {len(op.state.nodes)} nodes, ${cost2:.2f}/hr, "
          f"pending={len(op.state.pending_pods())}, saved ${cost - cost2:.2f}/hr")
    if getattr(args, "tracez", False):
        # the observability surface, rendered for the terminal (make
        # obs-demo): per-span p50/p99 over the run + the recent trace trees
        from .obs.export import render_tracez, statusz

        print(render_tracez(op.flight))
        st = statusz(op.registry, op.flight)
        print("== /statusz ==")
        print(json.dumps(st, indent=2, default=str))
    op.shutdown()


def drain_warm_threads(rc: int = 0, grace_s: float = 60.0) -> None:
    """Bounded wait for background compile threads at process exit.

    Warm threads are deliberately non-daemon (a daemon thread hard-killed
    inside XLA at interpreter teardown aborts the process — solver/tpu.py),
    so normal exit JOINS them.  A compile hung on a wedged TPU tunnel (the
    round-5 outage: device calls that never return) would pin shutdown
    forever; give legitimate compile tails a bounded grace, then force the
    exit.  Call only from process entry points, after clean shutdown steps.
    """
    # ktlint: allow[KT002] process-exit join deadline: must track real
    # elapsed time even when the operator under test runs on a FakeClock —
    # a fake-advanced clock would zero the grace and strand live compiles
    deadline = time.monotonic() + grace_s
    for t in threading.enumerate():
        if t.name == "tpu-solver-warm" and t is not threading.current_thread():
            t.join(max(0.0, deadline - time.monotonic()))  # ktlint: allow[KT002] see above
    stuck = sum(1 for t in threading.enumerate()
                if t.name == "tpu-solver-warm" and t.is_alive())
    if stuck:
        logging.getLogger(__name__).error(
            "%d background compile thread(s) still hung after %.0fs grace "
            "(wedged TPU tunnel?); forcing process exit", stuck, grace_s)
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)  # preserve the command's exit code through the force


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    parser.add_argument("--demo", action="store_true", help="run the fake-cloud simulation")
    parser.add_argument("--pods", type=int, default=200)
    parser.add_argument("--small", action="store_true", help="20-type catalog")
    parser.add_argument("--backend", default="oracle", choices=["auto", "tpu", "oracle"])
    parser.add_argument("--metrics-port", type=int, default=0)
    parser.add_argument("--solver-address",
                        default=os.environ.get("KARPENTER_SOLVER_ADDR", ""),
                        help="host:port of a solver sidecar (service.server); "
                             "empty solves in-process; defaults from "
                             "KARPENTER_SOLVER_ADDR (deploy/operator.yaml)")
    parser.add_argument("--config", default="",
                        help="YAML manifest file/dir (Provisioners, "
                             "NodeTemplates, settings) loaded through admission")
    parser.add_argument("--tracez", action="store_true",
                        help="print a /tracez + /statusz snapshot after the "
                             "demo (make obs-demo)")
    parser.add_argument("--warmup", action="store_true",
                        help="block on the AOT bucket-grid precompile "
                             "before the demo's first solve")
    args = parser.parse_args(argv)
    if args.demo:
        _demo(args)
        drain_warm_threads()
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
