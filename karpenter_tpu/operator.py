"""Operator runtime — process bootstrap, controller wiring, run loop.

The cmd/controller/main.go + core operator.NewOperator analog (SURVEY.md
§3.1): builds the cloud provider, wraps it in the metrics decorator, registers
every controller, exposes /metrics and /healthz over HTTP, and drives the
reconcile loops.  Leader election is modeled as a pluggable gate (a real
deployment plugs a lease-based elector; the sim elects immediately), and
leadership gates cache hydration exactly like launchtemplate.go:77-88.

Run a self-contained simulation:  ``python -m karpenter_tpu.operator --demo``
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional

from .batcher import Window
from .cache import UnavailableOfferings
from .cloud.base import CloudProvider
from .cloud.fake import FakeCloudProvider
from .controllers.deprovisioning import DeprovisioningController
from .controllers.garbagecollect import GarbageCollectController, LinkController
from .controllers.interruption import InterruptionController, MessageQueue
from .controllers.nodetemplate import NodeTemplateController
from .controllers.provisioning import ProvisioningController
from .controllers.state import ClusterState
from .controllers.termination import TerminationController
from .events import Recorder
from .metrics import Registry, decorate, registry as default_registry
from .models.catalog import generate_catalog
from .models.pod import PodSpec
from .models.provisioner import Provisioner
from .providers.pricing import PricingProvider
from .providers.securitygroup import SecurityGroupProvider
from .providers.subnet import SubnetProvider
from .settings import Settings, SettingsStore
from .solver.scheduler import BatchScheduler
from .utils.clock import Clock


class LeaderElector:
    """Pluggable leadership gate (operator.Elected() analog)."""

    def __init__(self, elect: Callable[[], bool] = lambda: True) -> None:
        self._elect = elect
        self.elected = False
        self._on_elected: List[Callable[[], None]] = []

    def on_elected(self, fn: Callable[[], None]) -> None:
        self._on_elected.append(fn)

    def tick(self) -> bool:
        if not self.elected and self._elect():
            self.elected = True
            for fn in self._on_elected:
                fn()
        return self.elected


class Operator:
    def __init__(
        self,
        cloud: CloudProvider,
        clock: Optional[Clock] = None,
        settings: Optional[SettingsStore] = None,
        registry: Optional[Registry] = None,
        scheduler_backend: str = "auto",
        metrics_port: int = 0,  # 0 disables the HTTP server
    ) -> None:
        self.clock = clock or Clock()
        self.settings = settings or SettingsStore()
        self.registry = registry or default_registry
        self.recorder = Recorder()
        self.elector = LeaderElector()
        self.metrics_port = metrics_port

        self.state = ClusterState(clock=self.clock)
        # request coalescing under the metrics decorator, like the
        # reference's pkg/batcher sits inside the provider under
        # core's metrics.Decorate (cmd/controller/main.go:46).
        # idle_seconds=0: the operator tick is single-threaded, so waiting
        # for peers would only add dead latency; coalescing engages for
        # concurrent callers (e.g. the gRPC solver service threads).
        from .cloud.batched import BatchedCloud

        self.cloud = decorate(BatchedCloud(cloud, idle_seconds=0.0), self.registry)
        self.unavailable = UnavailableOfferings(clock=self.clock)
        self.scheduler = BatchScheduler(backend=scheduler_backend, registry=self.registry)
        s = self.settings.current
        self.pricing = PricingProvider(
            cloud.get_instance_types(), clock=self.clock,
            isolated_vpc=s.isolated_vpc,
        )
        self.subnets = SubnetProvider()
        self.security_groups = SecurityGroupProvider(clock=self.clock)
        self.queue = MessageQueue()
        self.provisioning = ProvisioningController(
            self.state, self.cloud, scheduler=self.scheduler, recorder=self.recorder,
            registry=self.registry, unavailable=self.unavailable, clock=self.clock,
            idle_seconds=s.batch_idle_duration, max_seconds=s.batch_max_duration,
        )
        self.termination = TerminationController(
            self.state, self.cloud, recorder=self.recorder,
            registry=self.registry, clock=self.clock,
        )
        self.deprovisioning = DeprovisioningController(
            self.state, self.cloud, self.termination, provisioning=self.provisioning,
            scheduler=self.scheduler, recorder=self.recorder, registry=self.registry,
            clock=self.clock, drift_enabled=s.drift_enabled,
            deprovisioning_ttl=s.deprovisioning_ttl,
        )
        self.interruption = InterruptionController(
            self.state, self.termination, self.queue, unavailable=self.unavailable,
            recorder=self.recorder, registry=self.registry, clock=self.clock,
        )
        self.gc = GarbageCollectController(self.state, self.cloud, recorder=self.recorder, clock=self.clock)
        self.link = LinkController(self.state, self.cloud, recorder=self.recorder, clock=self.clock)
        self.nodetemplates = NodeTemplateController(self.subnets, self.security_groups, clock=self.clock)

        self.settings.subscribe(self._on_settings)
        self.elector.on_elected(self._hydrate)
        self._http: Optional[ThreadingHTTPServer] = None
        self._stop = threading.Event()

    # ---- wiring ---------------------------------------------------------
    def _on_settings(self, s: Settings) -> None:
        self.provisioning.window = Window(
            s.batch_idle_duration, s.batch_max_duration, clock=self.clock
        )
        self.deprovisioning.drift_enabled = s.drift_enabled
        self.deprovisioning.deprovisioning_ttl = s.deprovisioning_ttl
        self.pricing.isolated_vpc = s.isolated_vpc
        if self.elector.elected:
            # settings can reshape the catalog (pod density, pod-ENI) and
            # thus the solver tensor shapes: re-warm the compile ladder
            self._warm_solver()

    def _hydrate(self) -> None:
        """Leadership-gated warm-state rebuild (SURVEY §5 checkpoint/resume):
        re-adopt orphaned instances, refresh prices, and start the solver
        shape warmup so the first real batches never stall on an XLA
        compile (compile-behind covers shapes outside the warmed ladder)."""
        self.link.reconcile()
        self.pricing.maybe_refresh()
        self._warm_solver()

    def _warm_solver(self) -> None:
        provs = [p.with_defaults() for p in self.state.provisioners.values()]
        try:
            self.scheduler.warm_startup(
                provs or [Provisioner(name="default").with_defaults()],
                self.cloud.get_instance_types(),
                daemonsets=self.state.daemonsets,
                existing_nodes=[n.snapshot()
                                for n in self.state.schedulable_nodes()],
            )
        except Exception:  # warmup is best-effort; solves fall back warm
            logging.getLogger(__name__).warning(
                "solver warmup failed; compile-behind will cover", exc_info=True
            )

    # ---- health / metrics -----------------------------------------------
    def healthz(self) -> bool:
        return self.cloud.liveness() and self.pricing.liveness_ok()

    def start_http(self) -> Optional[int]:
        if self.metrics_port == 0:
            return None
        op = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    body = op.registry.expose().encode()
                    self.send_response(200)
                elif self.path == "/healthz":
                    ok = op.healthz()
                    body = (b"ok" if ok else b"unhealthy")
                    self.send_response(200 if ok else 503)
                else:
                    body = b"not found"
                    self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._http = ThreadingHTTPServer(("127.0.0.1", self.metrics_port), Handler)
        port = self._http.server_address[1]
        threading.Thread(target=self._http.serve_forever, daemon=True).start()
        return port

    def stop_http(self) -> None:
        if self._http:
            self._http.shutdown()
            self._http = None

    # ---- loop -----------------------------------------------------------
    def tick(self) -> None:
        """One pass over every controller (singleton-controller semantics)."""
        if not self.elector.tick():
            return
        if self.settings.current.interruption_queue_name:
            # interruption handling is enabled iff a queue is configured
            # (settings.md; pkg/controllers/controllers.go gates the same way)
            self.interruption.reconcile()
        self.provisioning.reconcile()
        self.deprovisioning.reconcile()
        self.termination.reconcile()
        self.nodetemplates.reconcile()
        self.gc.reconcile()
        self.pricing.maybe_refresh()

    def run(self, interval: float = 1.0, max_ticks: Optional[int] = None) -> None:
        n = 0
        while not self._stop.is_set():
            self.tick()
            n += 1
            if max_ticks is not None and n >= max_ticks:
                break
            self.clock.sleep(interval)

    def shutdown(self) -> None:
        self._stop.set()
        self.scheduler.stop_warms()  # don't drain queued compiles at exit
        self.stop_http()


def _demo(args) -> None:
    """Self-contained scale-up/scale-down simulation against the fake cloud."""
    from .utils.clock import FakeClock

    clock = FakeClock()
    cloud = FakeCloudProvider(generate_catalog(full=not args.small), clock=clock)
    op = Operator(cloud, clock=clock, scheduler_backend=args.backend,
                  metrics_port=args.metrics_port)
    port = op.start_http()
    if port:
        print(f"metrics on http://127.0.0.1:{port}/metrics")
    op.state.apply_provisioner(Provisioner(name="default", consolidation_enabled=True))

    print(f"scale-up: {args.pods} pods")
    for i in range(args.pods):
        op.state.add_pod(PodSpec(
            name=f"pod-{i}", requests={"cpu": 0.5 + (i % 4) * 0.5}, owner_key=f"d{i%5}",
        ))
    for _ in range(4):
        op.tick()
        clock.advance(1.0)
    cost = sum(ns.node.price for ns in op.state.nodes.values())
    print(f"  -> {len(op.state.nodes)} nodes, ${cost:.2f}/hr, "
          f"pending={len(op.state.pending_pods())}")

    print("scale-down: deleting 70% of pods")
    for i in range(0, int(args.pods * 0.7)):
        op.state.delete_pod(f"pod-{i}")
    clock.advance(6 * 60)
    # enough sim time for propose -> 15s validation TTL -> execute cycles
    for _ in range(10):
        op.tick()
        clock.advance(4.0)
    for _ in range(8):  # settle: rebind pods evicted by the last action
        if not op.state.pending_pods():
            break
        op.tick()
        clock.advance(2.0)
    cost2 = sum(ns.node.price for ns in op.state.nodes.values())
    print(f"  -> {len(op.state.nodes)} nodes, ${cost2:.2f}/hr, "
          f"pending={len(op.state.pending_pods())}, saved ${cost - cost2:.2f}/hr")
    op.shutdown()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    parser.add_argument("--demo", action="store_true", help="run the fake-cloud simulation")
    parser.add_argument("--pods", type=int, default=200)
    parser.add_argument("--small", action="store_true", help="20-type catalog")
    parser.add_argument("--backend", default="oracle", choices=["auto", "tpu", "oracle"])
    parser.add_argument("--metrics-port", type=int, default=0)
    args = parser.parse_args(argv)
    if args.demo:
        _demo(args)
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
