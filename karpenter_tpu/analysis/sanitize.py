"""Runtime lock-discipline sanitizer (``KT_SANITIZE=1``).

The static rules (KT004) check what annotations declare; this module checks
what threads actually DO.  It wraps the mutating entry points of the four
thread-sensitive solver-path classes in *lock-assertion proxies* that raise
:class:`SanitizerError` the moment two threads are inside the same
non-reentrant section of the same object — the PR 1 scheduler re-entrancy
race (two concurrent ``Solve`` RPCs racing one ``BatchScheduler``) becomes a
deterministic exception at the violation site instead of a corrupted solve
three calls later.

Guarded sections (one group per contract, per instance):

- ``BatchScheduler.solve`` / ``.submit`` — the scheduler is not re-entrant:
  all dispatch funnels through one thread at a time (``SolvePipeline``'s
  dispatcher in the pipelined path; ``_direct_lock`` serialization in the
  direct path).  Thread HANDOFF is legal (the pipeline is constructed on the
  RPC thread, dispatches on its own) — only *concurrent* entry raises.
- ``TensorizeCache.tensorize`` — documented "callers serialize solves".
- ``InflightQueue.push`` — single producer (the dispatcher).  ``pop_to`` is
  deliberately shared at shutdown (``SolvePipeline.stop`` drains a wedged
  dispatcher's queue; deque ops are thread-safe), so it is not wrapped.
- ``SolvePipeline._finalize`` — finalization is FIFO on the dispatcher;
  a second concurrent finalizer means two threads fencing one queue.

Enabled by exporting ``KT_SANITIZE=1`` before importing ``karpenter_tpu``
(``make battletest`` does) or by calling :func:`install` directly (tests).
The proxies add one dict lookup per call — cheap enough to leave on for the
whole battletest sweep — and wrapping is idempotent; :func:`uninstall`
restores the original methods.
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Dict, List, Tuple

logger = logging.getLogger(__name__)

#: serializes the per-object holder check; held only for the dict peek
_STATE_LOCK = threading.Lock()

_originals: Dict[Tuple[type, str], object] = {}


class SanitizerError(AssertionError):
    """Two threads entered a non-reentrant section of one object."""


def _notify_flight(obj, detail: str) -> None:
    """Hand the violation to the flight recorder so the dump captures the
    traces/events leading up to it (a sanitizer error IS an anomaly — the
    black-box must survive the crash site).  Prefer the violating object's
    OWN recorder (a BatchScheduler over a private registry rings its own
    black box, not the process-global one whose ring holds unrelated
    traffic); fall back to the process default.  Best-effort: observability
    must never mask the error it is reporting."""
    try:
        from ..obs import default_flight

        flight = getattr(getattr(obj, "tracer", None), "flight", None)
        (flight or default_flight()).anomaly("sanitizer_error", detail=detail)
    except Exception:  # noqa: BLE001 — the SanitizerError must still raise
        logger.debug("sanitizer flight-recorder dump failed", exc_info=True)


def _wrap(cls: type, name: str, group: str):
    fn = cls.__dict__[name]
    slot = f"_kt_san_{group}"

    @functools.wraps(fn)
    def guarded(self, *args, **kwargs):
        me = threading.current_thread()
        with _STATE_LOCK:
            holder = getattr(self, slot, None)
            if holder is None or holder is me:
                reentrant = holder is me
                setattr(self, slot, me)
        if holder is not None and holder is not me:
            # outside _STATE_LOCK: the flight-recorder dump serializes the
            # trace ring and must not run under the sanitizer's own lock
            msg = (
                f"KT_SANITIZE: unguarded cross-thread mutation — "
                f"{cls.__name__}.{name} entered by {me.name!r} while "
                f"{holder.name!r} is still inside the {group!r} section "
                f"of the same object; this object's {group} contract is "
                "single-threaded (serialize callers or route through "
                "the pipeline dispatcher)"
            )
            _notify_flight(self, msg)
            raise SanitizerError(msg)
        try:
            return fn(self, *args, **kwargs)
        finally:
            if not reentrant:
                with _STATE_LOCK:
                    setattr(self, slot, None)

    guarded._kt_sanitized = True  # type: ignore[attr-defined]
    _originals.setdefault((cls, name), fn)
    setattr(cls, name, guarded)


def installed() -> bool:
    return bool(_originals)


def install() -> None:
    """Wrap the solver-path classes in lock-assertion proxies (idempotent)."""
    from ..batcher import InflightQueue
    from ..models.tensorize import TensorizeCache
    from ..solver.scheduler import BatchScheduler

    plan: List[Tuple[type, str, str]] = [
        (BatchScheduler, "solve", "dispatch"),
        (BatchScheduler, "submit", "dispatch"),
        # the megabatch entries share the dispatch contract: registration,
        # bucketing, and the vmapped dispatch all belong to ONE thread at a
        # time (the pipeline's dispatcher)
        (BatchScheduler, "submit_many", "dispatch"),
        (BatchScheduler, "bucket_key", "dispatch"),
        (TensorizeCache, "tensorize", "tensorize"),
        (InflightQueue, "push", "inflight-producer"),
    ]
    try:
        from ..service.server import SolvePipeline
    except ImportError as err:  # grpc-less install: everything else still on
        logger.warning("KT_SANITIZE: SolvePipeline proxy skipped (%r)", err)
    else:
        plan.append((SolvePipeline, "_finalize", "finalize"))
    for cls, name, group in plan:
        if not getattr(cls.__dict__[name], "_kt_sanitized", False):
            _wrap(cls, name, group)
    logger.info("KT_SANITIZE: lock-assertion proxies installed on %d "
                "methods", len(plan))


def uninstall() -> None:
    """Restore the original methods (test teardown)."""
    for (cls, name), fn in _originals.items():
        setattr(cls, name, fn)
    _originals.clear()
