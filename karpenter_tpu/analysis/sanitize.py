"""Runtime lock-discipline sanitizer (``KT_SANITIZE=1``).

The static rules (KT004) check what annotations declare; this module checks
what threads actually DO.  It wraps the mutating entry points of the four
thread-sensitive solver-path classes in *lock-assertion proxies* that raise
:class:`SanitizerError` the moment two threads are inside the same
non-reentrant section of the same object — the PR 1 scheduler re-entrancy
race (two concurrent ``Solve`` RPCs racing one ``BatchScheduler``) becomes a
deterministic exception at the violation site instead of a corrupted solve
three calls later.

Guarded sections (one group per contract, per instance):

- ``BatchScheduler.solve`` / ``.submit`` — the scheduler is not re-entrant:
  all dispatch funnels through one thread at a time (``SolvePipeline``'s
  dispatcher in the pipelined path; ``_direct_lock`` serialization in the
  direct path).  Thread HANDOFF is legal (the pipeline is constructed on the
  RPC thread, dispatches on its own) — only *concurrent* entry raises.
- ``TensorizeCache.tensorize`` — documented "callers serialize solves".
- ``InflightQueue.push`` — single producer (the dispatcher).  ``pop_to`` is
  deliberately shared at shutdown (``SolvePipeline.stop`` drains a wedged
  dispatcher's queue; deque ops are thread-safe), so it is not wrapped.
- ``SolvePipeline._finalize`` — finalization is FIFO on the dispatcher;
  a second concurrent finalizer means two threads fencing one queue.

Enabled by exporting ``KT_SANITIZE=1`` before importing ``karpenter_tpu``
(``make battletest`` does) or by calling :func:`install` directly (tests).
The proxies add one dict lookup per call — cheap enough to leave on for the
whole battletest sweep — and wrapping is idempotent; :func:`uninstall`
restores the original methods.
"""

from __future__ import annotations

import functools
import logging
import threading
from typing import Dict, List, Tuple

logger = logging.getLogger(__name__)

#: serializes the per-object holder check; held only for the dict peek
_STATE_LOCK = threading.Lock()

_originals: Dict[Tuple[type, str], object] = {}


class SanitizerError(AssertionError):
    """Two threads entered a non-reentrant section of one object, or one
    thread acquired two tracked locks against the global order."""


#: The ONE global lock-acquisition order (outer first): a thread may
#: acquire a lock only while holding locks that appear EARLIER in this
#: tuple; the runtime watcher below raises SanitizerError on an inversion.
#: This is the linear extension of the KT012 static acquisition-order
#: graph (`python -m karpenter_tpu.analysis --lock-order` prints the
#: derived edges; tests/test_lint.py cross-validates that every static
#: edge is consistent with this table — the static pass and the sanitizer
#: check the same order from opposite sides: the pass proves what the
#: source CAN do, the watcher observes what threads actually DO, including
#: the closure/callback nestings no static pass can see, e.g. the
#: admission queue's token-bucket gate running under the queue condition).
LOCK_ORDER: Tuple[str, ...] = (
    "Operator._reconcile_lock",
    "SolverService._direct_lock",
    "SolvePipeline._submit_lock",
    "SolvePipeline._sched_lock",  # held across dispatch/finalize + inline
    "AdmissionControl._lock",
    "AdmissionQueue._cond",
    "RateLimiter._lock",        # the put() gate runs under the queue cond
    "CircuitBreaker._lock",
    "DeltaSessionTable._lock",  # table dict only; never held across solves
    "BatchScheduler._cold_lock",
    "TpuSolver._lock",
    "DeviceGuard._lock",
    "InMemoryLeaseStore._lock",
    "ThreadCoalescer._lock",
)


def _notify_flight(obj, detail: str) -> None:
    """Hand the violation to the flight recorder so the dump captures the
    traces/events leading up to it (a sanitizer error IS an anomaly — the
    black-box must survive the crash site).  Prefer the violating object's
    OWN recorder (a BatchScheduler over a private registry rings its own
    black box, not the process-global one whose ring holds unrelated
    traffic); fall back to the process default.  Best-effort: observability
    must never mask the error it is reporting."""
    try:
        from ..obs import default_flight

        flight = getattr(getattr(obj, "tracer", None), "flight", None)
        (flight or default_flight()).anomaly("sanitizer_error", detail=detail)
    except Exception:  # noqa: BLE001 — the SanitizerError must still raise
        logger.debug("sanitizer flight-recorder dump failed", exc_info=True)


def _wrap(cls: type, name: str, group: str):
    fn = cls.__dict__[name]
    slot = f"_kt_san_{group}"

    @functools.wraps(fn)
    def guarded(self, *args, **kwargs):
        me = threading.current_thread()
        with _STATE_LOCK:
            holder = getattr(self, slot, None)
            if holder is None or holder is me:
                reentrant = holder is me
                setattr(self, slot, me)
        if holder is not None and holder is not me:
            # outside _STATE_LOCK: the flight-recorder dump serializes the
            # trace ring and must not run under the sanitizer's own lock
            msg = (
                f"KT_SANITIZE: unguarded cross-thread mutation — "
                f"{cls.__name__}.{name} entered by {me.name!r} while "
                f"{holder.name!r} is still inside the {group!r} section "
                f"of the same object; this object's {group} contract is "
                "single-threaded (serialize callers or route through "
                "the pipeline dispatcher)"
            )
            _notify_flight(self, msg)
            raise SanitizerError(msg)
        try:
            return fn(self, *args, **kwargs)
        finally:
            if not reentrant:
                with _STATE_LOCK:
                    setattr(self, slot, None)

    guarded._kt_sanitized = True  # type: ignore[attr-defined]
    _originals.setdefault((cls, name), fn)
    setattr(cls, name, guarded)


# ---------------------------------------------------------------------------
# runtime lock-order confirmation (the KT012 cross-check)
# ---------------------------------------------------------------------------

#: per-thread stack of (rank, name) for currently-held tracked locks
_held = threading.local()

#: gates checking/recording only — push/pop always maintain the held
#: stack so proxies surviving an uninstall keep it truthful, while their
#: order assertions and edge recording go silent (install() re-arms them)
_watch_enabled = False

#: (outer name, inner name) pairs actually observed nested at runtime —
#: tests assert every observed edge is consistent with LOCK_ORDER, which
#: is how the dynamic side cross-validates the static table
_observed_edges: set = set()

_init_originals: Dict[type, object] = {}


def observed_lock_edges() -> set:
    """Snapshot of the (outer, inner) nestings threads actually performed."""
    with _STATE_LOCK:
        return set(_observed_edges)


class _OrderedLock:
    """Order-asserting proxy around one tracked component lock.

    ``acquire`` checks the acquiring thread's held stack against
    :data:`LOCK_ORDER` and raises :class:`SanitizerError` on an inversion
    — the deadlock's FIRST half becomes a deterministic exception at the
    acquisition site instead of a wedged process under load.  Re-acquiring
    the same proxy (RLock / Condition re-entry, condition-wait wakeups) is
    always legal.  All other attributes (``wait``, ``notify``, ...)
    delegate, so a wrapped Condition keeps its full surface."""

    def __init__(self, inner, name: str):
        self._kt_inner = inner
        self._kt_name = name
        self._kt_rank = LOCK_ORDER.index(name) if name in LOCK_ORDER \
            else len(LOCK_ORDER)

    def _kt_check(self) -> None:
        if not _watch_enabled:
            return  # uninstalled: surviving proxies delegate silently
        stack = getattr(_held, "stack", None)
        if not stack:
            return
        if any(name == self._kt_name for _rank, name in stack):
            # re-entry of an already-held lock (RLock/Condition), however
            # deep in the stack: the lock's own business, never an edge —
            # the thread cannot deadlock on a lock it already owns
            return
        # the binding constraint is the HIGHEST-ranked distinct held lock,
        # not the top of the stack: a legal re-entry of an early lock can
        # sit on top with a low rank and must not mask a real inversion
        # against a later-ranked lock still held beneath it
        top_rank, top_name = max(stack, key=lambda e: e[0])
        if top_rank > self._kt_rank:
            # raise BEFORE recording: an acquisition that raises never
            # happened, and the inverted pair must not poison the
            # observed-edge set the cross-validation tests assert over
            raise SanitizerError(
                f"KT_SANITIZE: lock-order inversion — "
                f"{threading.current_thread().name!r} acquiring "
                f"`{self._kt_name}` while holding `{top_name}`; the global "
                f"order (analysis/sanitize.py LOCK_ORDER, KT012) puts "
                f"`{self._kt_name}` BEFORE `{top_name}` — two threads "
                "taking opposite routes deadlock"
            )
        with _STATE_LOCK:
            _observed_edges.add((top_name, self._kt_name))

    def _kt_push(self) -> None:
        if not hasattr(_held, "stack"):
            _held.stack = []
        _held.stack.append((self._kt_rank, self._kt_name))

    def _kt_pop(self) -> None:
        stack = getattr(_held, "stack", None)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][1] == self._kt_name:
                    del stack[i]
                    break

    def acquire(self, *args, **kwargs):
        self._kt_check()
        got = self._kt_inner.acquire(*args, **kwargs)
        if got:
            self._kt_push()
        return got

    def release(self):
        self._kt_pop()
        return self._kt_inner.release()

    def __enter__(self):
        self._kt_check()
        got = self._kt_inner.__enter__()
        self._kt_push()
        return got

    def __exit__(self, *exc):
        self._kt_pop()
        return self._kt_inner.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._kt_inner, name)


def _wrap_locks(cls: type, attrs: Tuple[str, ...]) -> None:
    """Post-``__init__`` hook replacing the instance's lock attributes with
    order-asserting proxies (idempotent; uninstall restores __init__ — live
    instances keep their proxies, which is harmless: a proxy without the
    watcher installed still delegates)."""
    if cls in _init_originals:
        return
    orig = cls.__init__
    _init_originals[cls] = orig

    @functools.wraps(orig)
    def __init__(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        for attr in attrs:
            inner = getattr(self, attr, None)
            if inner is not None and not isinstance(inner, _OrderedLock):
                setattr(self, attr, _OrderedLock(
                    inner, f"{cls.__name__}.{attr}"))

    cls.__init__ = __init__


def installed() -> bool:
    return bool(_originals)


def install() -> None:
    """Wrap the solver-path classes in lock-assertion proxies and their
    declared locks in order-asserting proxies (idempotent)."""
    from ..admission import AdmissionControl, CircuitBreaker, RateLimiter
    from ..admission.queue import AdmissionQueue
    from ..batcher import InflightQueue, ThreadCoalescer
    from ..models.tensorize import TensorizeCache
    from ..solver.guard import DeviceGuard
    from ..solver.scheduler import BatchScheduler
    from ..solver.tpu import TpuSolver

    # runtime confirmation of the KT012 static lock order: every tracked
    # component lock becomes an order-asserting proxy; an acquisition that
    # inverts LOCK_ORDER raises at the site (the deadlock's first half,
    # made deterministic), and the nestings threads actually perform are
    # recorded for the cross-validation tests
    lock_plan: List[Tuple[type, Tuple[str, ...]]] = [
        (BatchScheduler, ("_cold_lock",)),
        (TpuSolver, ("_lock",)),
        (DeviceGuard, ("_lock",)),
        (AdmissionControl, ("_lock",)),
        (AdmissionQueue, ("_cond",)),
        (RateLimiter, ("_lock",)),
        (CircuitBreaker, ("_lock",)),
        (ThreadCoalescer, ("_lock",)),
    ]
    try:
        from ..service.delta import DeltaSessionTable as _DT
        from ..service.server import SolvePipeline as _SP
        from ..service.server import SolverService as _SS
    except ImportError:
        pass  # grpc-less install: the in-process locks still watched
    else:
        lock_plan.append((_SP, ("_submit_lock", "_sched_lock")))
        lock_plan.append((_SS, ("_direct_lock",)))
        lock_plan.append((_DT, ("_lock",)))
    try:
        from ..operator import InMemoryLeaseStore as _LS
        from ..operator import Operator as _Op
    except ImportError:
        pass  # keep the solver-side locks watched regardless
    else:
        lock_plan.append((_Op, ("_reconcile_lock",)))
        lock_plan.append((_LS, ("_lock",)))
    for cls, attrs in lock_plan:
        _wrap_locks(cls, attrs)
    global _watch_enabled
    _watch_enabled = True

    plan: List[Tuple[type, str, str]] = [
        (BatchScheduler, "solve", "dispatch"),
        (BatchScheduler, "submit", "dispatch"),
        # the megabatch entries share the dispatch contract: registration,
        # bucketing, and the vmapped dispatch all belong to ONE thread at a
        # time (the pipeline's dispatcher)
        (BatchScheduler, "submit_many", "dispatch"),
        (BatchScheduler, "bucket_key", "dispatch"),
        (TensorizeCache, "tensorize", "tensorize"),
        (InflightQueue, "push", "inflight-producer"),
    ]
    try:
        from ..service.server import SolvePipeline
    except ImportError as err:  # grpc-less install: everything else still on
        logger.warning("KT_SANITIZE: SolvePipeline proxy skipped (%r)", err)
    else:
        plan.append((SolvePipeline, "_finalize", "finalize"))
    for cls, name, group in plan:
        if not getattr(cls.__dict__[name], "_kt_sanitized", False):
            _wrap(cls, name, group)
    logger.info("KT_SANITIZE: lock-assertion proxies installed on %d "
                "methods", len(plan))


def uninstall() -> None:
    """Restore the original methods (test teardown).  Instances built while
    installed keep their _OrderedLock proxies, but with the watch disabled
    they delegate without checking or recording — sanitizer state cannot
    leak into 'sanitizer off' test phases; new instances get plain locks."""
    global _watch_enabled
    _watch_enabled = False
    for (cls, name), fn in _originals.items():
        setattr(cls, name, fn)
    _originals.clear()
    for cls, init in _init_originals.items():
        cls.__init__ = init
    _init_originals.clear()
    with _STATE_LOCK:
        _observed_edges.clear()
