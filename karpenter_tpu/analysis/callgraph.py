"""Project-wide symbol table + call graph for whole-program ktlint passes.

The function-local rules (KT001-KT011) encode invariants a single ``def``
can witness; the three invariants the serving stack actually lives and dies
by are *interprocedural*:

- "no host<->device sync reachable from a hot path except through a fence"
  (KT013) needs every call chain from the serving entry points;
- "locks are always acquired in one global order" (KT012) needs lock-held
  sets propagated across call edges;
- "every jit signature constructible at runtime is warmed" (KT014) needs
  the rung vocabulary cross-referenced between modules.

This module builds what those passes share: a per-file :class:`FileSummary`
(functions, calls, lock acquisitions, sync constructs, attribute types) and
a linked :class:`Project` (symbol table, resolved call graph, lock/sync
indexes).  Like ktlint core it is pure stdlib ``ast`` — importing it must
never pull jax, so ``make lint`` stays fast and runs anywhere.

Resolution is deliberately *best-effort*: anything the resolver cannot
follow (dynamic dispatch, ``getattr`` facades, callbacks) becomes an entry
in ``Project.unresolved`` and NO edge — whole-program passes degrade to
their function-local approximations instead of crashing or crying wolf
(tests/test_lint.py pins the graceful-degradation paths).  What static
resolution cannot see (futures' done-callbacks, thread targets), the
runtime sanitizer (``analysis/sanitize.py``, KT_SANITIZE=1) cross-checks.

Summaries are JSON-serializable and cached per file keyed on the content
hash (:class:`SummaryCache`), so a warm whole-package run skips the
extraction walk entirely — the speed gate in tests/test_lint.py holds the
full v2 suite under its budget.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .ktlint import SourceFile, dotted_name, file_nodes

#: bump when the summary format changes — stale caches are discarded, never
#: migrated (the extraction is cheap; correctness of the cache is not)
SUMMARY_VERSION = 3  # v3: env_reads gains the env= keyword shape (KT022)

#: parameter names treated as device-resident by convention (KT001's taint)
TAINT_PARAMS = {"carry", "ys"}

#: lock constructor names -> reentrancy.  threading.Condition wraps an RLock
#: by default, so re-acquiring under a holding caller is legal (the
#: admission queue's ``_bump`` depends on exactly that).
LOCK_KINDS = {"Lock": False, "RLock": True, "Condition": True}


# ---------------------------------------------------------------------------
# per-file summary (JSON-able, cacheable)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuncSummary:
    """One function as the whole-program passes see it."""

    qual: str                 #: "Class.method" | "func" | "outer.inner"
    cls: Optional[str]        #: declaring class name, None for module funcs
    lineno: int
    end_lineno: int
    fence: bool               #: carries `# ktlint: fence <why>`
    nested: bool              #: defined inside another function
    #: [(lineno, dotted, in_closure)] — every call with a nameable callee;
    #: in_closure marks calls inside nested defs/lambdas (they do NOT
    #: execute at their lexical position, so lock propagation skips them)
    calls: List[Tuple[int, str, bool]] = dataclasses.field(default_factory=list)
    #: [(lineno, end_lineno, ref)] — `with <ref>:` acquisitions; ref is
    #: "self._lock"-style or a bare module-global name.  Closure bodies are
    #: excluded (same reason as above).
    locks: List[Tuple[int, int, str]] = dataclasses.field(default_factory=list)
    #: [(lineno, kind)] — blocking host<->device sync constructs
    syncs: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    #: local var name -> [raw type exprs] (constructor calls / annotations)
    local_types: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    #: parameter name -> raw annotation expr
    param_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClassSummary:
    name: str
    lineno: int
    bases: List[str] = dataclasses.field(default_factory=list)
    methods: List[str] = dataclasses.field(default_factory=list)
    #: self attribute -> [raw type exprs seen assigned to it]
    attr_types: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    #: self attribute -> lock kind name ("Lock"/"RLock"/"Condition")
    locks: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FileSummary:
    path: str
    module: str               #: dotted module name derived from the path
    #: local name -> absolute dotted target ("pkg.mod" or "pkg.mod.symbol")
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    functions: List[FuncSummary] = dataclasses.field(default_factory=list)
    classes: Dict[str, ClassSummary] = dataclasses.field(default_factory=dict)
    #: module-level lock name -> kind
    module_locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: module-level names bound to jitted callables (KT013's taint needs
    #: "np.asarray(jitted(...))" to count as a device read)
    jitted: List[str] = dataclasses.field(default_factory=list)
    #: [(lineno, pattern)] — every ``KT_*`` environment read in the file
    #: (KT022); dynamically-suffixed keys (f-strings) become ``KT_FOO_*``
    #: wildcard patterns
    env_reads: List[Tuple[int, str]] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "FileSummary":
        funcs = [FuncSummary(**{**f, "calls": [tuple(c) for c in f["calls"]],
                                "locks": [tuple(x) for x in f["locks"]],
                                "syncs": [tuple(s) for s in f["syncs"]]})
                 for f in d["functions"]]
        classes = {k: ClassSummary(**v) for k, v in d["classes"].items()}
        return cls(path=d["path"], module=d["module"], imports=d["imports"],
                   functions=funcs, classes=classes,
                   module_locks=d["module_locks"], jitted=d["jitted"],
                   env_reads=[tuple(e) for e in d.get("env_reads", [])])


def module_name(path: str) -> str:
    """Dotted module name for a slash-normalized .py path."""
    parts = path.replace("\\", "/").lstrip("/").split("/")
    parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") else parts[-1]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p)


def _is_pkg(path: str) -> bool:
    return path.endswith("__init__.py")


# ---- extraction ----------------------------------------------------------


def _ann_types(node: Optional[ast.AST]) -> List[str]:
    """Raw class-name strings named by a type annotation: unwraps
    ``Optional[X]``, string annotations, and ``Union``-style subscripts."""
    if node is None:
        return []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return []
    if isinstance(node, (ast.Name, ast.Attribute)):
        d = dotted_name(node)
        return [d] if d else []
    if isinstance(node, ast.Subscript):
        head = dotted_name(node.value) or ""
        if head.split(".")[-1] in ("Optional", "Union"):
            inner = node.slice
            elts = inner.elts if isinstance(inner, ast.Tuple) else [inner]
            out: List[str] = []
            for e in elts:
                out.extend(_ann_types(e))
            return out
    return []


def _value_types(node: ast.AST, param_types: Dict[str, str]) -> List[str]:
    """Raw type strings for an assigned value: constructor calls (possibly
    behind ``or`` / ``if-else`` defaulting) and annotated-parameter
    passthrough (``self.x = scheduler`` with ``scheduler: BatchScheduler``)."""
    out: List[str] = []
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d and d.split(".")[-1][:1].isupper():
            out.append(d)
    elif isinstance(node, ast.Name) and node.id in param_types:
        out.append(param_types[node.id])
    elif isinstance(node, ast.BoolOp):
        for v in node.values:
            out.extend(_value_types(v, param_types))
    elif isinstance(node, ast.IfExp):
        out.extend(_value_types(node.body, param_types))
        out.extend(_value_types(node.orelse, param_types))
    return out


def _lock_ctor(node: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` / RLock / Condition -> kind name."""
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        if d is not None and d.split(".")[-1] in LOCK_KINDS:
            return d.split(".")[-1]
    return None


def _jit_bound_names(tree: ast.AST) -> Set[str]:
    """Module-level names bound to jitted callables: ``f = jax.jit(g)``,
    ``f = partial(jax.jit, ...)(g)``, and ``@jax.jit``/``@partial(jax.jit,
    ...)``-decorated defs."""

    def is_jit(node: ast.AST) -> bool:
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted_name(node)
            return d is not None and d.split(".")[-1] == "jit"
        if isinstance(node, ast.Call):
            f = node.func
            if is_jit(f):
                return True
            if (isinstance(f, ast.Name) and f.id == "partial" and node.args
                    and is_jit(node.args[0])):
                return True
            if isinstance(f, ast.Call):  # partial(jax.jit, ...)(fn)
                return is_jit(f)
        return False

    out: Set[str] = set()
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign) and is_jit(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit(d) for d in node.decorator_list):
                out.add(node.name)
    return out


class _TaintScan:
    """KT001's light device taint, extended with locally-jitted callees:
    ``np.asarray(_screen_kernel(*args))`` is a D2H read even though no name
    in scope is tainted."""

    def __init__(self, fn: ast.AST, jitted: Set[str]):
        self.jitted = jitted
        self.tainted: Set[str] = set()
        args = getattr(fn, "args", None)
        for arg in (args.args if args is not None else ()):
            if arg.arg in TAINT_PARAMS:
                self.tainted.add(arg.arg)
        changed = True
        while changed:
            changed = False
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and self.expr(n.value):
                    for t in n.targets:
                        for nm in ast.walk(t):
                            if isinstance(nm, ast.Name) \
                                    and nm.id not in self.tainted:
                                self.tainted.add(nm.id)
                                changed = True

    def expr(self, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in self.tainted:
                return True
            if isinstance(n, ast.Attribute):
                d = dotted_name(n)
                if d is not None and d.split(".", 1)[0] == "jnp":
                    return True
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Name) and (
                        n.func.id == "run" or n.func.id in self.jitted):
                    return True
                d = dotted_name(n.func)
                if d is not None and d in self.jitted:
                    return True
        return False


def _scan_syncs(fn: ast.AST, taint: _TaintScan, fence_lines: set,
                skip_defs: bool) -> List[Tuple[int, str]]:
    """Blocking sync constructs in ``fn``.  Closure bodies are INCLUDED
    (KT001 precedent: closures scan with their enclosing method) unless the
    nested def itself is fence-annotated; when ``skip_defs`` the scan stops
    at nested defs entirely (they are separate FuncSummary entries)."""
    out: List[Tuple[int, str]] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if skip_defs or child.lineno in fence_lines:
                    continue
            if isinstance(child, ast.Call):
                kind = _sync_kind(child, taint)
                if kind is not None:
                    out.append((child.lineno, kind))
            visit(child)

    visit(fn)
    return out


def _sync_kind(n: ast.Call, taint: _TaintScan) -> Optional[str]:
    func = n.func
    if isinstance(func, ast.Attribute):
        d = dotted_name(func)
        if func.attr == "block_until_ready":
            return "`.block_until_ready()`"
        if d in ("jax.block_until_ready",):
            return "`jax.block_until_ready()`"
        if d in ("jax.device_get",):
            return "`jax.device_get()`"
        if func.attr == "item" and taint.expr(func.value):
            return "`.item()` on a device value"
        if func.attr == "asarray":
            root = dotted_name(func.value)
            if root in ("np", "numpy") and n.args and taint.expr(n.args[0]):
                return "`np.asarray()` on a device value"
    elif (isinstance(func, ast.Name) and func.id == "float"
          and n.args and taint.expr(n.args[0])):
        return "`float()` on a device value"
    return None


def _with_lock_ref(item: ast.withitem) -> Optional[str]:
    ctx = item.context_expr
    d = dotted_name(ctx)
    if d is None:
        return None
    parts = d.split(".")
    if parts[0] == "self" and len(parts) >= 2:
        return d
    if len(parts) == 1 and (parts[0].isupper() or parts[0].startswith("_")):
        # module-global lock convention (_STATE_LOCK, _defaults_lock)
        return d
    return None


def _env_reads(f: SourceFile) -> List[Tuple[int, str]]:
    """Every ``KT_*`` environment-variable READ in the file (KT022).

    Matched shapes (the package's actual idioms — validated against every
    knob in the tree, not a grep):

    - ``os.environ.get("KT_X")`` / ``os.getenv("KT_X")`` /
      ``os.environ.setdefault("KT_X", ...)``
    - ``os.environ["KT_X"]`` in Load context (Store/Del are writes)
    - one-hop module-constant indirection: ``NAME = "KT_X"`` then
      ``environ.get(NAME)`` (admission/policy.py's DEFAULT_CLASS_ENV)
    - wrapper helpers whose name mentions ``env`` called with a literal
      key (``_env_int("KT_X", 4)``)
    - registry declarations binding an env key through an ``env=``
      keyword (``KnobSpec(..., env="KT_X", ...)`` — the tuning
      registry's knobs are READ through the spec's ``from_env``, whose
      dynamic ``self.env`` lookup is invisible to the shapes above)
    - f-string keys with a literal ``KT_`` head become WILDCARD patterns
      (``f"KT_QUOTA_{cls}"`` -> ``KT_QUOTA_*``) — the README documents
      those as a family row
    """
    consts: Dict[str, str] = {}
    for node in ast.iter_child_nodes(f.tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and node.value.value.startswith("KT_"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = node.value.value

    def key_of(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value.startswith("KT_") else None
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        if isinstance(node, ast.JoinedStr) and node.values:
            head = node.values[0]
            if isinstance(head, ast.Constant) \
                    and isinstance(head.value, str) \
                    and head.value.startswith("KT_"):
                return head.value + "*"
        return None

    out: List[Tuple[int, str]] = []
    for n in file_nodes(f):
        if isinstance(n, ast.Call):
            for kw in n.keywords:
                if kw.arg == "env":
                    key = key_of(kw.value)
                    if key is not None:
                        out.append((n.lineno, key))
            d = dotted_name(n.func)
            if d is None or not n.args:
                continue
            base = d.split(".")[-1]
            direct = (base == "getenv" or d.endswith("environ.get")
                      or d.endswith("environ.setdefault"))
            wrapper = not direct and "env" in base.lower()
            if direct or wrapper:
                key = key_of(n.args[0])
                if key is not None:
                    out.append((n.lineno, key))
        elif isinstance(n, ast.Subscript) and isinstance(n.ctx, ast.Load):
            d = dotted_name(n.value)
            if d is not None and d.endswith("environ"):
                key = key_of(n.slice)
                if key is not None:
                    out.append((n.lineno, key))
    return out


def summarize(f: SourceFile) -> FileSummary:
    """Extract the whole-program facts for one parsed file."""
    mod = module_name(f.path)
    summ = FileSummary(path=f.path, module=mod)
    summ.env_reads = _env_reads(f)
    pkg_parts = mod.split(".") if _is_pkg(f.path) else mod.split(".")[:-1]

    # imports
    for node in file_nodes(f):
        if isinstance(node, ast.Import):
            for a in node.names:
                summ.imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            else:
                base = []
            src = ".".join(base + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                summ.imports[a.asname or a.name] = (
                    f"{src}.{a.name}" if src else a.name)

    # module-level locks + jitted names
    for node in ast.iter_child_nodes(f.tree):
        if isinstance(node, ast.Assign):
            kind = _lock_ctor(node.value)
            if kind is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        summ.module_locks[t.id] = kind
    jitted = _jit_bound_names(f.tree)
    summ.jitted = sorted(jitted)

    # classes + functions
    def visit(node: ast.AST, cls: Optional[ast.ClassDef], prefix: str,
              in_func: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                cs = ClassSummary(
                    name=child.name, lineno=child.lineno,
                    bases=[b for b in (dotted_name(x) for x in child.bases)
                           if b],
                )
                summ.classes[child.name] = cs
                visit(child, child, f"{child.name}.", in_func)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _summarize_func(summ, f, child, cls, prefix, in_func, jitted)
                visit(child, cls, f"{prefix}{child.name}.", True)
            else:
                visit(child, cls, prefix, in_func)

    visit(f.tree, None, "", False)
    return summ


def _summarize_func(summ: FileSummary, f: SourceFile, fn: ast.AST,
                    cls: Optional[ast.ClassDef], prefix: str, nested: bool,
                    jitted: Set[str]) -> None:
    qual = f"{prefix}{fn.name}"
    fs = FuncSummary(
        qual=qual, cls=cls.name if cls is not None else None,
        lineno=fn.lineno, end_lineno=getattr(fn, "end_lineno", fn.lineno),
        fence=fn.lineno in f.fence_lines, nested=nested,
    )
    if cls is not None and not nested:
        summ.classes[cls.name].methods.append(fn.name)

    for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
        types = _ann_types(arg.annotation)
        if types:
            fs.param_types[arg.arg] = types[0]

    taint = _TaintScan(fn, jitted)
    fs.syncs = _scan_syncs(fn, taint, f.fence_lines, skip_defs=False)

    # calls / locks / assignments: stop at nested defs (their own summary)
    # and mark lambda bodies in_closure (they do not run where they appear)
    def visit(node: ast.AST, in_closure: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            closure = in_closure or isinstance(child, ast.Lambda)
            if isinstance(child, ast.Call):
                d = dotted_name(child.func)
                if d is not None:
                    fs.calls.append((child.lineno, d, closure))
            if isinstance(child, ast.With) and not closure:
                for item in child.items:
                    ref = _with_lock_ref(item)
                    if ref is not None:
                        fs.locks.append((
                            child.lineno,
                            getattr(child, "end_lineno", child.lineno), ref))
            if isinstance(child, ast.Assign) and not closure:
                types = _value_types(child.value, fs.param_types)
                for t in child.targets:
                    self_attr = _self_attr(t)
                    if self_attr is not None and cls is not None:
                        entry = summ.classes[cls.name].attr_types.setdefault(
                            self_attr, [])
                        for ty in types:
                            if ty not in entry:
                                entry.append(ty)
                        kind = _lock_ctor(child.value)
                        if kind is not None:
                            summ.classes[cls.name].locks[self_attr] = kind
                    elif isinstance(t, ast.Name) and types:
                        entry = fs.local_types.setdefault(t.id, [])
                        for ty in types:
                            if ty not in entry:
                                entry.append(ty)
            if isinstance(child, ast.AnnAssign) and not closure:
                self_attr = _self_attr(child.target)
                types = _ann_types(child.annotation)
                if not types and child.value is not None:
                    types = _value_types(child.value, fs.param_types)
                if self_attr is not None and cls is not None and types:
                    entry = summ.classes[cls.name].attr_types.setdefault(
                        self_attr, [])
                    for ty in types:
                        if ty not in entry:
                            entry.append(ty)
                elif isinstance(child.target, ast.Name) and types:
                    fs.local_types.setdefault(child.target.id, []).extend(
                        t for t in types
                        if t not in fs.local_types.get(child.target.id, []))
            visit(child, closure)

    visit(fn, False)
    summ.functions.append(fs)


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# summary cache
# ---------------------------------------------------------------------------


class SummaryCache:
    """Per-file summary cache keyed on content hash.

    ``path=None`` keeps the cache in-memory only (tests); otherwise it
    persists as one JSON file (default: under the user's cache dir —
    ``$XDG_CACHE_HOME``/``~/.cache`` — NEVER the world-shared temp dir,
    where another local user could pre-create the file the lint gate
    trusts; override with ``KT_LINT_CACHE``, ``0`` disables).  A stale or
    corrupt cache file is discarded wholesale — the cache is an
    accelerator, never a source of truth."""

    def __init__(self, path: Optional[Path] = None):
        self.path = path
        self.hits = 0
        self.misses = 0
        self._entries: Dict[str, dict] = {}
        if path is not None and Path(path).exists():
            try:
                data = json.loads(Path(path).read_text())
                if data.get("version") == SUMMARY_VERSION:
                    self._entries = data.get("entries", {})
            except (OSError, ValueError):
                self._entries = {}

    @classmethod
    def default(cls) -> "SummaryCache":
        env = os.environ.get("KT_LINT_CACHE")
        if env == "0":
            return cls(path=None)
        if env:
            return cls(path=Path(env))
        base = Path(os.environ.get("XDG_CACHE_HOME")
                    or Path.home() / ".cache") / "karpenter-ktlint"
        try:
            base.mkdir(parents=True, exist_ok=True)
        except OSError:
            return cls(path=None)  # no writable cache dir: run uncached
        return cls(path=base / "cache.json")

    def get(self, f: SourceFile) -> FileSummary:
        # keyed by (derived module, content hash), not raw path: an
        # explicit-path run (`ktlint karpenter_tpu`) and the package run
        # see the same file and must share one entry.  The module part
        # matters — relative-import resolution in the summary depends on
        # the path-derived module, so identical text seen under a
        # different package spelling must NOT hit.
        sha = hashlib.sha256(f.text.encode()).hexdigest()
        key = f"{module_name(f.path)}:{sha}"
        entry = self._entries.get(key)
        if entry is not None:
            try:
                summ = FileSummary.from_json(entry["summary"])
            except (KeyError, TypeError):
                pass  # format drift inside one entry: re-extract
            else:
                summ.path = f.path  # the caller's spelling of the path
                self.hits += 1
                return summ
        self.misses += 1
        summ = summarize(f)
        self._entries[key] = {"summary": summ.to_json()}
        return summ

    def save(self) -> None:
        if self.path is None or self.misses == 0:
            return
        try:
            tmp = Path(f"{self.path}.tmp.{os.getpid()}")
            tmp.write_text(json.dumps(
                {"version": SUMMARY_VERSION, "entries": self._entries}))
            tmp.replace(self.path)
        except OSError:
            pass  # cache is best-effort; the run already has its summaries


# ---------------------------------------------------------------------------
# the linked project
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FuncNode:
    """One function in the linked graph.  ``fid`` is ``module:qual``."""

    fid: str
    summary: FuncSummary
    path: str
    module: str
    #: resolved callees: [(lineno, callee fid, in_closure)]
    edges: List[Tuple[int, str, bool]] = dataclasses.field(default_factory=list)


class Project:
    """Symbol table + resolved call graph over a set of summaries."""

    def __init__(self, summaries: Sequence[FileSummary]):
        self.summaries = list(summaries)
        self.modules: Dict[str, FileSummary] = {s.module: s for s in summaries}
        self.funcs: Dict[str, FuncNode] = {}
        #: class id ("module:Class") -> ClassSummary
        self.classes: Dict[str, ClassSummary] = {}
        self._class_by_name: Dict[str, List[str]] = {}
        self._func_index: Dict[str, FuncSummary] = {}
        self.unresolved: List[Tuple[str, int, str]] = []  # (fid, line, name)
        for s in summaries:
            for cname, cs in s.classes.items():
                cid = f"{s.module}:{cname}"
                self.classes[cid] = cs
                self._class_by_name.setdefault(cname, []).append(cid)
            for fn in s.functions:
                fid = f"{s.module}:{fn.qual}"
                self.funcs[fid] = FuncNode(
                    fid=fid, summary=fn, path=s.path, module=s.module)
        self._link()

    @classmethod
    def build(cls, files: Sequence[SourceFile],
              cache: Optional[SummaryCache] = None) -> "Project":
        cache = cache if cache is not None else SummaryCache(path=None)
        project = cls([cache.get(f) for f in files])
        cache.save()
        return project

    # ---- symbol resolution ---------------------------------------------

    def resolve_class(self, module: str, raw: str) -> Optional[str]:
        """Class id for a raw type string as seen from ``module``."""
        if not raw:
            return None
        parts = raw.split(".")
        summ = self.modules.get(module)
        # same-module class
        if summ is not None and parts[0] in summ.classes and len(parts) == 1:
            return f"{module}:{parts[0]}"
        # through the import table
        if summ is not None and parts[0] in summ.imports:
            target = summ.imports[parts[0]]
            return self._class_at(".".join([target] + parts[1:]))
        # unique bare-name fallback (facade params annotated with a class
        # the module only imports under TYPE_CHECKING, doc examples, etc.)
        if len(parts) == 1:
            cands = self._class_by_name.get(parts[0], [])
            if len(cands) == 1:
                return cands[0]
        return None

    def _class_at(self, dotted: str) -> Optional[str]:
        """Class id for an absolute dotted path ``pkg.mod.Class``."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod in self.modules and parts[i] in self.modules[mod].classes:
                if i == len(parts) - 1:
                    return f"{mod}:{parts[i]}"
        return None

    def _func_at(self, dotted: str,
                 _seen: Optional[Set[str]] = None) -> Optional[str]:
        """fid for an absolute dotted path ``pkg.mod.func``.  ``_seen``
        bounds re-export chains: a circular ``from . import f`` alias pair
        must resolve to None, never recurse the lint run to death."""
        seen = _seen if _seen is not None else set()
        if dotted in seen:
            return None
        seen.add(dotted)
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:i])
            if mod not in self.modules:
                continue
            qual = ".".join(parts[i:])
            fid = f"{mod}:{qual}"
            if fid in self.funcs:
                return fid
            # pkg re-export: from .sub import f in __init__
            summ = self.modules[mod]
            if parts[i] in summ.imports and i == len(parts) - 1:
                return self._func_at(summ.imports[parts[i]], seen)
        return None

    def method_on(self, cid: str, name: str,
                  _seen: Optional[Set[str]] = None) -> Optional[str]:
        """fid of ``name`` on class ``cid``, walking project-local bases."""
        seen = _seen or set()
        if cid in seen:
            return None
        seen.add(cid)
        cs = self.classes.get(cid)
        if cs is None:
            return None
        module = cid.split(":", 1)[0]
        if name in cs.methods:
            return f"{module}:{cs.name}.{name}"
        for base in cs.bases:
            base_cid = self.resolve_class(module, base)
            if base_cid is not None:
                found = self.method_on(base_cid, name, seen)
                if found is not None:
                    return found
        return None

    def attr_class(self, cid: str, attr: str) -> Optional[str]:
        """Class id of ``self.<attr>`` on ``cid`` (first resolvable type)."""
        cs = self.classes.get(cid)
        if cs is None:
            return None
        module = cid.split(":", 1)[0]
        for raw in cs.attr_types.get(attr, []):
            got = self.resolve_class(module, raw)
            if got is not None:
                return got
        return None

    # ---- call resolution -----------------------------------------------

    def _resolve_call(self, node: FuncNode, dotted: str) -> Optional[str]:
        summ = self.modules.get(node.module)
        fn = node.summary
        parts = dotted.split(".")

        def chain_method(start_cid: Optional[str],
                         chain: List[str]) -> Optional[str]:
            cid = start_cid
            for attr in chain[:-1]:
                if cid is None:
                    return None
                cid = self.attr_class(cid, attr)
            if cid is None:
                return None
            return self.method_on(cid, chain[-1])

        if parts[0] == "self" and fn.cls is not None and len(parts) >= 2:
            return chain_method(f"{node.module}:{fn.cls}", parts[1:])

        root = parts[0]
        # locally-typed variable / annotated parameter receiver
        raw_types = list(fn.local_types.get(root, []))
        if root in fn.param_types:
            raw_types.append(fn.param_types[root])
        for raw in raw_types:
            cid = self.resolve_class(node.module, raw)
            if cid is not None and len(parts) >= 2:
                got = chain_method(cid, parts[1:])
                if got is not None:
                    return got

        if len(parts) == 1:
            # same-module function (methods never bind bare), constructor,
            # or imported symbol
            fid = f"{node.module}:{root}"
            if fid in self.funcs and self.funcs[fid].summary.cls is None:
                return fid
            if summ is not None and root in summ.classes:
                return self.method_on(f"{node.module}:{root}", "__init__")
            if summ is not None and root in summ.imports:
                target = summ.imports[root]
                got = self._func_at(target)
                if got is not None:
                    return got
                cid = self._class_at(target)
                if cid is not None:
                    return self.method_on(cid, "__init__")
            return None

        # dotted root: imported module / imported or local class
        if summ is not None and root in summ.imports:
            target = ".".join([summ.imports[root]] + parts[1:])
            got = self._func_at(target)
            if got is not None:
                return got
            cid = self._class_at(target)
            if cid is not None:
                return self.method_on(cid, "__init__")
            # Class.method through an imported class
            cid = self._class_at(".".join([summ.imports[root]] + parts[1:-1]))
            if cid is not None:
                return self.method_on(cid, parts[-1])
        if summ is not None and root in summ.classes and len(parts) == 2:
            return self.method_on(f"{node.module}:{root}", parts[1])
        return None

    def _link(self) -> None:
        for node in self.funcs.values():
            for lineno, dotted, in_closure in node.summary.calls:
                fid = self._resolve_call(node, dotted)
                if fid is not None:
                    node.edges.append((lineno, fid, in_closure))
                else:
                    self.unresolved.append((node.fid, lineno, dotted))

    # ---- shared queries -------------------------------------------------

    def find_function(self, path_suffix: str, qual: str) -> Optional[str]:
        """fid of ``qual`` in the file whose path ends with ``path_suffix``."""
        for s in self.summaries:
            if s.path.endswith(path_suffix):
                fid = f"{s.module}:{qual}"
                if fid in self.funcs:
                    return fid
        return None

    def lock_id(self, node: FuncNode, ref: str) -> Optional[str]:
        """Canonical lock name for an acquisition ref in ``node``:
        ``ClassName._lock`` for instance locks, ``mod._NAME`` for module
        globals.  None when the ref resolves to no declared lock (the
        acquisition still counts; kind is then unknown)."""
        fn = node.summary
        parts = ref.split(".")
        if parts[0] == "self" and fn.cls is not None:
            cid: Optional[str] = f"{node.module}:{fn.cls}"
            for attr in parts[1:-1]:
                cid = self.attr_class(cid, attr) if cid else None
            if cid is not None:
                owner = cid.split(":", 1)[1]
                return f"{owner}.{parts[-1]}"
            return f"{fn.cls}.{parts[-1]}" if len(parts) == 2 else None
        summ = self.modules.get(node.module)
        if summ is not None and ref in summ.module_locks:
            return f"{node.module.split('.')[-1]}.{ref}"
        return None

    def lock_kind(self, node: FuncNode, ref: str) -> Optional[str]:
        fn = node.summary
        parts = ref.split(".")
        if parts[0] == "self" and fn.cls is not None and len(parts) == 2:
            cs = self.modules[node.module].classes.get(fn.cls)
            # fall back through bases for inherited locks
            cid: Optional[str] = f"{node.module}:{fn.cls}"
            while cid is not None:
                cs = self.classes.get(cid)
                if cs is None:
                    break
                if parts[1] in cs.locks:
                    return cs.locks[parts[1]]
                module = cid.split(":", 1)[0]
                cid = None
                for base in cs.bases:
                    got = self.resolve_class(module, base)
                    if got is not None:
                        cid = got
                        break
            return None
        summ = self.modules.get(node.module)
        if summ is not None and ref in summ.module_locks:
            return summ.module_locks[ref]
        return None


def build_project(files: Sequence[SourceFile],
                  cache: Optional[SummaryCache] = None) -> Project:
    """Module-level convenience used by the rule modules and the CLI."""
    return Project.build(files, cache=cache)
