"""KT014 — compile-surface audit: runtime-constructible signatures must be
a subset of what the AOT precompile warms.

The no-compile serving contract (KT008's premise) has a global half KT008
cannot see: the rung/dims vocabulary the runtime can *construct* — the
``solve_dims`` single-solve ladder, the ``_mega_rung`` megabatch slot rungs
(including the sharded mesh device-count floor), the ``sweep_dims`` fine
rungs, the mesh-signature key tail — and the set ``precompile_buckets``
actually *warms* live in different modules and drift independently.  A new
ladder rung added on one side silently reintroduces inline compiles on the
serving path; nothing fails until a latency SLO does.  This pass proves the
subset relation statically, cross-module:

1. **dims-key vocabulary sync** — the dict keys ``solve_dims`` returns
   (plus the kernel statics and the ``_mega_key_tail`` names) must match
   KT008's ``BUCKET_GRID_STATICS`` registry in BOTH directions: an
   unregistered key would make KT008 flag the solver's own kernels; a
   stale registry entry would let an off-grid name hide under a recycled
   key.
2. **megabatch rung coverage** — for every shardable device-count floor,
   the slot rungs constructible under ``DEFAULT_MAX_SLOTS`` (through the
   ``_mega_rung`` ladder: floor at the device count, double to
   ``MEGA_MAX_SLOTS``) must be covered by the rungs ``WARM_MEGA_SLOTS``
   resolves to.  Bumping the default slot cap without extending the warm
   grid is THE silent-compile regression; dead warm entries (outside the
   ladder) are flagged too.  The rule mirrors the ladder math;
   tests/test_lint.py pins the mirror against the real ``_mega_rung`` over
   the full domain, so the mirror cannot drift silently either.
3. **single-source key tail** — the ``("mega_slots", ...)`` compile-key
   tail may only be constructed by ``_mega_key_tail``; signature builders
   (``mega_signature``, ``_dispatch_prepared``, ``sweep_signature``) must
   call it rather than hand-rolling the tuple.
4. **plumbing** — ``precompile_buckets`` must bound its rung filter by
   ``MEGA_MAX_SLOTS`` (not a literal that can rot), ``sweep_dims`` must
   delegate to ``solve_dims`` (its fine rungs override axes, never invent
   keys), and the ``serve --warmup`` blocking precompile must pass an
   explicit ``mega_slots`` grid so a configured ``--max-slots`` above the
   default is warmed, not discovered at the first full flush.
5. **relax-rung surface** (``solver/relax.py``) — ``relax_dims`` must
   delegate to ``solve_dims`` and emit only its keys; ``relax_signature``
   must route through ``relax_dims`` AND ``_relax_key_tail``;
   ``warm_relax`` must key its warm on ``relax_signature`` (the warm must
   target exactly what dispatch will look up); the ``"relax_iters"``
   key-tail literal is single-sourced in ``_relax_key_tail`` (the jit
   wrapper's ``static_argnames`` naming the parameter is the one other
   legal spelling); and ``RELAX_ITER_RUNGS`` must be a strictly-ascending
   positive ladder — a duplicate, out-of-order, or non-positive entry is
   unreachable through ``iter_rung``'s smallest-rung-≥-n bucketing, i.e.
   a DEAD warm entry that warms a program no solve can ever dispatch.

Every check degrades gracefully: it runs only when the module owning its
anchor is in the analyzed set, and an anchor that has *moved* (function
renamed, constant no longer a literal) is itself a finding — the audit
surface must never silently shrink.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import Project, build_project
from ..ktlint import Finding, SourceFile, file_nodes
from .kt008 import BUCKET_GRID_STATICS

ID = "KT014"
TITLE = "runtime-constructible compile signature not covered by precompile"
WHOLE_PROGRAM = True
HINT = ("the runtime vocabulary (solve_dims keys, _mega_rung slot rungs, "
        "_mega_key_tail) and the warmed set (precompile_buckets, "
        "WARM_MEGA_SLOTS, BUCKET_GRID_STATICS) must move together — "
        "extend the warm grid / registry in the same PR that extends the "
        "ladder; `scripts/profile_solve.py --lint-surface` dumps both "
        "sides for human diffing")

#: kernel vocab-position statics (KT008's registry carries them alongside
#: the dims keys; they are compile-signature axes of the vmapped kernel)
KERNEL_STATICS = frozenset({"zone_key", "ct_key"})

#: the relax rung's key-tail statics (solver/relax.py _relax_key_tail —
#: the rule checks the real tail emits exactly these, so the model cannot
#: drift from the source)
RELAX_STATICS = frozenset({"relax_iters"})

TPU = "solver/tpu.py"
SCHED = "solver/scheduler.py"
SERVER = "service/server.py"
SWEEP = "solver/consolidation.py"
RELAX = "solver/relax.py"
KT008_FILE = "rules/kt008.py"


def mega_rung(n: int, n_dev: int, cap: int) -> int:
    """Mirror of ``solver/tpu.py _mega_rung`` with the cap explicit.
    tests/test_lint.py pins this mirror against the real function over the
    whole (n, n_dev) domain — the audit must never model a ladder the
    solver does not climb."""
    r = max(1, n_dev)
    while r < min(max(1, n), cap) and r * 2 <= cap:
        r *= 2
    return r


# ---- tiny AST extractors -------------------------------------------------


def _file(files, suffix: str) -> Optional[SourceFile]:
    for f in files:
        if f.path.endswith(suffix):
            return f
    return None


def _func_def(tree: ast.AST, name: str) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _int_const(tree: ast.AST, name: str) -> Optional[Tuple[int, int]]:
    """(value, lineno) of a module/class-level ``NAME = <int>``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, int):
                    return node.value.value, node.lineno
    return None


def _int_tuple(tree: ast.AST, name: str) -> Optional[Tuple[Tuple[int, ...], int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name \
                        and isinstance(node.value, (ast.Tuple, ast.List)):
                    vals = []
                    for el in node.value.elts:
                        if not (isinstance(el, ast.Constant)
                                and isinstance(el.value, int)):
                            return None
                        vals.append(el.value)
                    return tuple(vals), node.lineno
    return None


def _dict_return_keys(fn: ast.AST) -> Optional[Tuple[Set[str], int]]:
    """Keys of a ``return dict(...)`` (keyword form) inside ``fn``."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Name) and call.func.id == "dict":
                keys = {kw.arg for kw in call.keywords if kw.arg is not None}
                if keys:
                    return keys, node.lineno
    return None


def _calls_name(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == name:
                return True
            if isinstance(f, ast.Attribute) and f.attr == name:
                return True
    return False


def _uses_name(fn: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(fn))


def _moved(out: List[Finding], path: str, what: str) -> None:
    out.append(Finding(
        ID, path, 1,
        f"compile-surface audit anchor {what} not found — the surface "
        "this rule proves moved; update analysis/rules/kt014.py in the "
        "same PR so the subset proof keeps covering the serving path",
        hint=HINT,
    ))


# ---- the checks ----------------------------------------------------------


def check(files, project: Optional[Project] = None) -> List[Finding]:
    out: List[Finding] = []
    tpu = _file(files, TPU)
    sched = _file(files, SCHED)
    server = _file(files, SERVER)
    sweep = _file(files, SWEEP)
    kt008f = _file(files, KT008_FILE)

    dims_keys: Optional[Set[str]] = None
    dims_line = 1
    mega_max: Optional[int] = None
    tail_keys: Set[str] = set()

    # staleness guard vs fixture tolerance: a file with NONE of its anchors
    # is a test fixture or partial run and is skipped wholesale; a file
    # with SOME anchors is the real one, and each missing anchor is a
    # finding (the audit surface moved under the rule).  The package gate
    # in tests/test_lint.py separately pins that the real tree yields every
    # anchor, so wholesale renames cannot silently shrink the audit either.
    if tpu is not None:
        fn = _func_def(tpu.tree, "solve_dims")
        mm = _int_const(tpu.tree, "MEGA_MAX_SLOTS")
        tailfn = _func_def(tpu.tree, "_mega_key_tail")
        if fn is None and mm is None and tailfn is None:
            tpu = None
    if tpu is not None:
        got = _dict_return_keys(fn) if fn is not None else None
        if got is None:
            _moved(out, tpu.path, "`solve_dims` returning `dict(...)`")
        else:
            dims_keys, dims_line = got
        if mm is None:
            _moved(out, tpu.path, "`MEGA_MAX_SLOTS` as an int literal")
        else:
            mega_max = mm[0]
        if tailfn is None:
            _moved(out, tpu.path, "`_mega_key_tail`")
        else:
            for node in ast.walk(tailfn):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    tail_keys.add(node.value)
        # (1) vocabulary sync, both directions
        if dims_keys is not None:
            vocab = dims_keys | KERNEL_STATICS
            for key in sorted(vocab - BUCKET_GRID_STATICS):
                out.append(Finding(
                    ID, tpu.path, dims_line,
                    f"solve_dims emits dims key `{key}` that KT008's "
                    "BUCKET_GRID_STATICS does not register — the rule "
                    "would flag the solver's own kernels as off-grid",
                    hint=HINT,
                ))
            stale = BUCKET_GRID_STATICS - vocab - tail_keys - RELAX_STATICS
            if stale and kt008f is not None:
                line = 1
                for node in ast.walk(kt008f.tree):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if isinstance(t, ast.Name) \
                                    and t.id == "BUCKET_GRID_STATICS":
                                line = node.lineno
                for key in sorted(stale):
                    out.append(Finding(
                        ID, kt008f.path, line,
                        f"BUCKET_GRID_STATICS entry `{key}` matches no "
                        "solve_dims key, kernel static, or key-tail name — "
                        "a stale registry entry lets an off-grid "
                        "static_argname hide under a recycled name",
                        hint=HINT,
                    ))
        # (3) single-source key tail: "mega_slots" literal outside
        # _mega_key_tail anywhere in the serving tree
        for f in files:
            for node in file_nodes(f):
                if isinstance(node, ast.Constant) \
                        and node.value == "mega_slots":
                    if f is tpu and tailfn is not None \
                            and tailfn.lineno <= node.lineno \
                            <= getattr(tailfn, "end_lineno", tailfn.lineno):
                        continue
                    if f.path.endswith(("test_lint.py", "kt014.py")):
                        continue
                    out.append(Finding(
                        ID, f.path, node.lineno,
                        "`\"mega_slots\"` compile-key tail constructed "
                        "outside `_mega_key_tail` — two construction sites "
                        "drift apart the day one spec changes (the tail is "
                        "single-source by contract)",
                        hint=HINT,
                    ))
        # (3b) the signature builders must route through _mega_key_tail
        for fname in ("mega_signature", "_dispatch_prepared"):
            f2 = _func_def(tpu.tree, fname)
            if f2 is None:
                _moved(out, tpu.path, f"`{fname}`")
            elif not _calls_name(f2, "_mega_key_tail"):
                out.append(Finding(
                    ID, tpu.path, f2.lineno,
                    f"`{fname}` does not call `_mega_key_tail` — its "
                    "compile key can drift from what readiness/warm "
                    "bookkeeping tracks",
                    hint=HINT,
                ))

    warm_slots: Optional[Tuple[int, ...]] = None
    warm_line = 1
    if sched is not None:
        ws = _int_tuple(sched.tree, "WARM_MEGA_SLOTS")
        pcb = _func_def(sched.tree, "precompile_buckets")
        if ws is None and pcb is None:
            sched = None
    if sched is not None:
        if ws is None:
            _moved(out, sched.path, "`WARM_MEGA_SLOTS` as an int tuple")
        else:
            warm_slots, warm_line = ws
        if pcb is None:
            _moved(out, sched.path, "`precompile_buckets`")
        elif not _uses_name(pcb, "MEGA_MAX_SLOTS"):
            out.append(Finding(
                ID, sched.path, pcb.lineno,
                "`precompile_buckets` does not bound its slot-rung filter "
                "by `MEGA_MAX_SLOTS` — a literal bound rots the day the "
                "ladder cap moves",
                hint=HINT,
            ))

    default_max: Optional[int] = None
    if server is not None:
        dm = _int_const(server.tree, "DEFAULT_MAX_SLOTS")
        has_pcb_call = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "precompile_buckets"
            for n in ast.walk(server.tree))
        if dm is None and not has_pcb_call:
            server = None
    if server is not None:
        if dm is None:
            _moved(out, server.path, "`DEFAULT_MAX_SLOTS` as an int literal")
        else:
            default_max = dm[0]
        # (4) serve --warmup: the blocking precompile must name its grid
        for node in ast.walk(server.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "precompile_buckets":
                kwargs = {kw.arg for kw in node.keywords}
                blocking = any(
                    kw.arg == "wait" and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True for kw in node.keywords)
                if blocking and "mega_slots" not in kwargs:
                    out.append(Finding(
                        ID, server.path, node.lineno,
                        "blocking `precompile_buckets(wait=True)` without "
                        "an explicit `mega_slots` grid — a configured "
                        "--max-slots above the default warms nothing past "
                        "the default rungs, and the first full flush pays "
                        "the compile inline",
                        hint=HINT,
                    ))

    # (2) megabatch rung coverage over every shardable device-count floor
    if mega_max is not None and warm_slots is not None \
            and default_max is not None:
        live = [s for s in warm_slots if 2 <= s <= mega_max]
        for s in warm_slots:
            if not 2 <= s <= mega_max:
                out.append(Finding(
                    ID, sched.path, warm_line,
                    f"WARM_MEGA_SLOTS entry {s} is outside the megabatch "
                    f"ladder [2, {mega_max}] — precompile_buckets filters "
                    "it out, so it warms nothing (dead config)",
                    hint=HINT,
                ))
        for n_dev in range(1, mega_max + 1):
            warm_rungs = {mega_rung(s, n_dev, mega_max) for s in live}
            eff_cap = min(max(default_max, n_dev),
                          mega_rung(mega_max, n_dev, mega_max))
            runtime_rungs = {mega_rung(n, n_dev, mega_max)
                             for n in range(2, eff_cap + 1)}
            missing = sorted(runtime_rungs - warm_rungs)
            if missing:
                out.append(Finding(
                    ID, sched.path, warm_line,
                    f"megabatch slot rung(s) {missing} are constructible "
                    f"at runtime (device floor {n_dev}, slot cap "
                    f"{eff_cap}) but WARM_MEGA_SLOTS={tuple(live)} never "
                    "warms them — the first flush at that occupancy "
                    "compiles inline on the serving path",
                    hint=HINT,
                ))
                break  # one floor's witness is enough; don't spam 32 rows

    # (4b) sweep_dims: fine rungs may override axes, never invent keys
    if sweep is not None:
        sd = _func_def(sweep.tree, "sweep_dims")
        ss = _func_def(sweep.tree, "sweep_signature")
        if sd is None and ss is None:
            sweep = None
    if sweep is not None:
        if sd is None:
            _moved(out, sweep.path, "`sweep_dims`")
        else:
            if not _calls_name(sd, "solve_dims"):
                out.append(Finding(
                    ID, sweep.path, sd.lineno,
                    "`sweep_dims` does not delegate to `solve_dims` — the "
                    "sweep's compile signatures would fork from the single "
                    "source of the bucketing math",
                    hint=HINT,
                ))
            if dims_keys is not None:
                for node in ast.walk(sd):
                    if isinstance(node, ast.Assign) and node.targets \
                            and isinstance(node.targets[0], ast.Subscript):
                        sub = node.targets[0]
                        if isinstance(sub.slice, ast.Constant) \
                                and isinstance(sub.slice.value, str) \
                                and sub.slice.value not in dims_keys:
                            out.append(Finding(
                                ID, sweep.path, node.lineno,
                                f"`sweep_dims` writes dims key "
                                f"`{sub.slice.value}` that `solve_dims` "
                                "never emits — an invented key is a "
                                "compile-signature axis no rung ladder "
                                "bounds",
                                hint=HINT,
                            ))
        if ss is None:
            _moved(out, sweep.path, "`sweep_signature`")
        elif not _calls_name(ss, "_mega_key_tail"):
            out.append(Finding(
                ID, sweep.path, ss.lineno,
                "`sweep_signature` does not call `_mega_key_tail` — the "
                "sweep's compile key can drift from what dispatch keys",
                hint=HINT,
            ))

    # (5) relax-rung surface (solver/relax.py): dims delegation, key-tail
    # single-sourcing, warm-targets-dispatch-key, and the iteration-rung
    # ladder's dead-entry audit
    relaxf = _file(files, RELAX)
    rd = rs = rt = wr = ir = None
    rungs = None
    if relaxf is not None:
        rd = _func_def(relaxf.tree, "relax_dims")
        rs = _func_def(relaxf.tree, "relax_signature")
        rt = _func_def(relaxf.tree, "_relax_key_tail")
        wr = _func_def(relaxf.tree, "warm_relax")
        ir = _func_def(relaxf.tree, "iter_rung")
        rungs = _int_tuple(relaxf.tree, "RELAX_ITER_RUNGS")
        if (all(x is None for x in (rd, rs, rt, wr, ir))
                and rungs is None):
            relaxf = None  # fixture tolerance, like the anchors above
    if relaxf is not None:
        if rd is None:
            _moved(out, relaxf.path, "`relax_dims`")
        else:
            if not _calls_name(rd, "solve_dims"):
                out.append(Finding(
                    ID, relaxf.path, rd.lineno,
                    "`relax_dims` does not delegate to `solve_dims` — the "
                    "relax program's compile signatures would fork from "
                    "the single source of the bucketing math",
                    hint=HINT,
                ))
            got = _dict_return_keys(rd)
            if got is not None and dims_keys is not None:
                for key in sorted(got[0] - dims_keys):
                    out.append(Finding(
                        ID, relaxf.path, got[1],
                        f"`relax_dims` emits dims key `{key}` that "
                        "`solve_dims` never emits — an invented key is a "
                        "compile-signature axis no rung ladder bounds",
                        hint=HINT,
                    ))
        if rt is None:
            _moved(out, relaxf.path, "`_relax_key_tail`")
        else:
            got_tails = {n.value
                         for ret in ast.walk(rt)
                         if isinstance(ret, ast.Return)
                         for n in ast.walk(ret)
                         if isinstance(n, ast.Constant)
                         and isinstance(n.value, str)}
            if got_tails != set(RELAX_STATICS):
                out.append(Finding(
                    ID, relaxf.path, rt.lineno,
                    f"`_relax_key_tail` emits key(s) {sorted(got_tails)} "
                    f"but the audit registry models {sorted(RELAX_STATICS)}"
                    " — update RELAX_STATICS (and KT008's registry) in the"
                    " same PR the tail changes",
                    hint=HINT,
                ))
        if rs is None:
            _moved(out, relaxf.path, "`relax_signature`")
        else:
            for dep in ("relax_dims", "_relax_key_tail"):
                if not _calls_name(rs, dep):
                    out.append(Finding(
                        ID, relaxf.path, rs.lineno,
                        f"`relax_signature` does not call `{dep}` — its "
                        "compile key can drift from what readiness/warm "
                        "bookkeeping tracks",
                        hint=HINT,
                    ))
        if wr is None:
            _moved(out, relaxf.path, "`warm_relax`")
        elif not _calls_name(wr, "relax_signature"):
            out.append(Finding(
                ID, relaxf.path, wr.lineno,
                "`warm_relax` does not key its warm on `relax_signature` "
                "— the warmed program and the dispatched lookup can drift",
                hint=HINT,
            ))
        if ir is None:
            _moved(out, relaxf.path, "`iter_rung`")
        if rungs is None:
            _moved(out, relaxf.path, "`RELAX_ITER_RUNGS` as an int tuple")
        else:
            vals, rline = rungs
            for i, v in enumerate(vals):
                if v <= 0 or (i > 0 and v <= vals[i - 1]):
                    out.append(Finding(
                        ID, relaxf.path, rline,
                        f"RELAX_ITER_RUNGS entry {v} is unreachable "
                        "through iter_rung's smallest-rung-≥-n bucketing "
                        "(non-positive, duplicate, or out of order) — a "
                        "dead warm entry warms a program no solve "
                        "dispatches",
                        hint=HINT,
                    ))
        # single-source "relax_iters": legal only inside _relax_key_tail
        # or as a static_argnames entry (the jit parameter's own name)
        for f in files:
            if f.path.endswith(("test_lint.py", "kt014.py", "kt008.py")):
                continue
            static_arg_nodes = set()
            for node in file_nodes(f):
                if isinstance(node, ast.Call):
                    for kw in node.keywords:
                        if kw.arg == "static_argnames":
                            for n2 in ast.walk(kw.value):
                                static_arg_nodes.add(id(n2))
            for node in file_nodes(f):
                if not (isinstance(node, ast.Constant)
                        and node.value == "relax_iters"):
                    continue
                if id(node) in static_arg_nodes:
                    continue
                if f is relaxf and rt is not None \
                        and rt.lineno <= node.lineno \
                        <= getattr(rt, "end_lineno", rt.lineno):
                    continue
                out.append(Finding(
                    ID, f.path, node.lineno,
                    "`\"relax_iters\"` compile-key tail constructed "
                    "outside `_relax_key_tail` — the tail is single-source"
                    " by contract (the KT014 mega_slots precedent)",
                    hint=HINT,
                ))
    return out


# ---- the --lint-surface dump (scripts/profile_solve.py) ------------------


def surface(files) -> Dict[str, object]:
    """The two sides of the subset proof as data, for human diffing when
    the ladder changes (``scripts/profile_solve.py --lint-surface``)."""
    tpu = _file(files, TPU)
    sched = _file(files, SCHED)
    server = _file(files, SERVER)
    out: Dict[str, object] = {
        "bucket_grid_statics": sorted(BUCKET_GRID_STATICS),
        "kernel_statics": sorted(KERNEL_STATICS),
        "relax_statics": sorted(RELAX_STATICS),
    }
    relaxf = _file(files, RELAX)
    if relaxf is not None:
        rr = _int_tuple(relaxf.tree, "RELAX_ITER_RUNGS")
        out["relax_iter_rungs"] = list(rr[0]) if rr else None
        rd = _func_def(relaxf.tree, "relax_dims")
        got = _dict_return_keys(rd) if rd is not None else None
        out["relax_dims_keys"] = sorted(got[0]) if got else None
    if tpu is not None:
        fn = _func_def(tpu.tree, "solve_dims")
        got = _dict_return_keys(fn) if fn is not None else None
        out["solve_dims_keys"] = sorted(got[0]) if got else None
        mm = _int_const(tpu.tree, "MEGA_MAX_SLOTS")
        out["mega_max_slots"] = mm[0] if mm else None
    ws = _int_tuple(sched.tree, "WARM_MEGA_SLOTS") if sched is not None \
        else None
    dm = _int_const(server.tree, "DEFAULT_MAX_SLOTS") if server is not None \
        else None
    out["warm_mega_slots"] = list(ws[0]) if ws else None
    out["default_max_slots"] = dm[0] if dm else None
    mega_max = out.get("mega_max_slots")
    if mega_max and ws and dm:
        rungs: Dict[str, Dict[str, List[int]]] = {}
        for n_dev in range(1, int(mega_max) + 1):
            warm = sorted({mega_rung(s, n_dev, int(mega_max))
                           for s in ws[0] if 2 <= s <= int(mega_max)})
            eff_cap = min(max(dm[0], n_dev),
                          mega_rung(int(mega_max), n_dev, int(mega_max)))
            runtime = sorted({mega_rung(n, n_dev, int(mega_max))
                              for n in range(2, eff_cap + 1)})
            rungs[str(n_dev)] = {"warmed": warm, "runtime": runtime}
        out["mega_rungs_by_device_floor"] = rungs
    return out
