"""KT005 — broad ``except Exception`` that neither re-raises nor logs.

A reconcile loop that swallows everything hides real solver/cloud failures
behind silent retries.  Broad handlers are legitimate at fan-out boundaries
(a batch leader publishing per-request errors) and in best-effort epilogues —
but each one must either re-raise, produce a structured log/warning, or be
annotated ``# ktlint: allow[KT005] <reason>`` so the breadth is a recorded
decision, not an accident.  ``except BaseException`` and bare ``except:``
are held to the same bar.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, file_nodes

ID = "KT005"
TITLE = "broad except without re-raise, log, or suppression"
HINT = ("narrow the exception type, re-raise, log via logger/warnings, or "
        "annotate `# ktlint: allow[KT005] <reason>` on the except line")

BROAD_NAMES = {"Exception", "BaseException"}
LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical",
               "log", "warn"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name) and t.id in BROAD_NAMES:
        return True
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD_NAMES
                   for e in t.elts)
    return False


def _handled(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in LOG_METHODS):
            return True
    return False


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        for n in file_nodes(f):
            if not isinstance(n, ast.Try):
                continue
            for handler in n.handlers:
                if not _is_broad(handler) or _handled(handler):
                    continue
                what = (ast.unparse(handler.type)
                        if handler.type is not None else "bare except")
                out.append(Finding(
                    ID, f.path, handler.lineno,
                    f"broad `except {what}` neither re-raises nor logs",
                    hint=HINT,
                ))
    return out
