"""KT002 — raw wall/monotonic clock reads outside ``utils/clock.py``.

Every controller takes an injectable :class:`karpenter_tpu.utils.clock.Clock`
(the reference injects a clock everywhere for testability); a raw
``time.time()`` / ``time.monotonic()`` bypasses it, making the behavior
untestable with ``FakeClock`` — the warm-failure backoff in ``solver/tpu.py``
was exactly this (untestable without sleeping out a 300 s backoff).
``time.perf_counter()`` is exempt: duration *measurement* is not scheduling
*time* and fake-advancing it would falsify metrics.

Aliases are tracked, not pattern-matched: ``import time as t`` flags
``t.time()``, and ``from time import monotonic`` is flagged AT THE IMPORT —
once the bare name is loose in the module every call site looks like any
other function call, so the import line is where the leak is stopped.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..ktlint import Finding, file_nodes

ID = "KT002"
TITLE = "raw time.time()/time.monotonic() outside utils/clock.py"
HINT = ("inject karpenter_tpu.utils.clock.Clock and call clock.now() "
        "(tests drive it with FakeClock)")

EXEMPT_SUFFIX = "utils/clock.py"
CLOCK_CALLS = {"time", "monotonic"}


def _time_aliases(f) -> Set[str]:
    """Every name the ``time`` module is bound to in this file."""
    aliases: Set[str] = set()
    for n in file_nodes(f):
        if isinstance(n, ast.Import):
            for alias in n.names:
                if alias.name == "time":
                    aliases.add(alias.asname or "time")
    return aliases


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if f.path.endswith(EXEMPT_SUFFIX):
            continue
        aliases = _time_aliases(f)
        for n in file_nodes(f):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in CLOCK_CALLS
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in aliases):
                out.append(Finding(
                    ID, f.path, n.lineno,
                    f"raw `{n.func.value.id}.{n.func.attr}()` outside "
                    "utils/clock.py",
                    hint=HINT,
                ))
            elif isinstance(n, ast.ImportFrom) and n.module == "time":
                for alias in n.names:
                    if alias.name in CLOCK_CALLS:
                        out.append(Finding(
                            ID, f.path, n.lineno,
                            f"`from time import {alias.name}` smuggles a raw "
                            "clock read past the injectable Clock (flagged "
                            "at the import: call sites are indistinguishable "
                            "once the bare name is bound)",
                            hint=HINT,
                        ))
    return out
