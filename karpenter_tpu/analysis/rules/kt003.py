"""KT003 — labeled counter series never zero-inited.

A Prometheus counter series that first appears at its first increment loses
that increment to ``rate()`` / ``increase()`` (no prior sample to diff
against) — the exact ADVICE-r5 bug: ``SOLVER_DEGRADED_SOLVES`` /
``SOLVER_COLD_FALLBACKS`` counted their first degraded/cold solve into the
void.  Generalized: any metric constant used with a labels argument via
``registry.counter(NAME).inc(labels)`` anywhere in the package must also
have a zero-init registration (``.inc(..., value=0.0)``) somewhere, so the
series exists from process start.

Series whose label *values* are runtime data (provisioner names) cannot be
pre-created; those sites carry an explicit ``ktlint allow[KT003]``
suppression with the reason, keeping the exemption visible in the diff
instead of implicit in the rule.

Known limit (by design): matching is per metric NAME, not per label set —
zero-init sites and use sites both commonly carry loop variables
(``for b in ("native", "oracle"): inc({"backend": b}, value=0.0)``), so the
exact series population is not statically decidable.  The rule catches the
"metric never zero-inited at all" class; label-set EXACTNESS (every backend,
every tier, surviving into ``expose()``) is pinned at runtime by
``tests/test_metrics_init.py`` — deleting one backend's zero-init passes
this rule but fails that test.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..ktlint import Finding, file_nodes

ID = "KT003"
TITLE = "labeled counter series never zero-inited"
HINT = ("register the series at construction with "
        "`registry.counter(NAME).inc(labels, value=0.0)` — inc(0) creates "
        "the sample, merely constructing the Counter does not")


def _metric_of_counter_call(node: ast.AST) -> Optional[str]:
    """``<expr>.counter(METRIC)`` -> metric name (Name id or str const)."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "counter" and node.args):
        arg = node.args[0]
        if isinstance(arg, ast.Name):
            return arg.id
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
    return None


def _inc_call(n: ast.AST) -> Optional[Tuple[ast.Call, ast.expr]]:
    if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "inc"):
        return n, n.func.value
    return None


def _is_zero(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool) and node.value == 0)


def check(files) -> List[Finding]:
    zero_inited: set = set()
    uses: List[Tuple[str, str, int]] = []  # (metric, path, line)
    for f in files:
        # counters bound to locals: name -> metric (file-scoped, conservative)
        varmap: Dict[str, str] = {}
        for n in file_nodes(f):
            if isinstance(n, ast.Assign):
                metric = _metric_of_counter_call(n.value)
                if metric is not None:
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            varmap[t.id] = metric
        for n in file_nodes(f):
            hit = _inc_call(n)
            if hit is None:
                continue
            call, recv = hit
            metric = _metric_of_counter_call(recv)
            if metric is None and isinstance(recv, ast.Name):
                metric = varmap.get(recv.id)
            if metric is None:
                continue
            labels = call.args[0] if call.args else None
            if labels is not None and isinstance(labels, ast.Constant) \
                    and labels.value is None:
                labels = None
            value = call.args[1] if len(call.args) > 1 else None
            for kw in call.keywords:
                if kw.arg == "labels":
                    labels = kw.value
                elif kw.arg == "value":
                    value = kw.value
            if value is not None and _is_zero(value):
                zero_inited.add(metric)
            elif labels is not None:
                uses.append((metric, f.path, n.lineno))
    return [
        Finding(
            ID, path, line,
            f"labeled counter series for `{metric}` is incremented here but "
            "the metric is never zero-inited anywhere in the package — "
            "Prometheus rate()/increase() will lose its first increment",
            hint=HINT,
        )
        for metric, path, line in uses if metric not in zero_inited
    ]
