"""KT022 — knob-inventory drift between code and the README knob table.

The README's serving-knob table is the package's ONLY complete operator
surface — deploy manifests, runbooks, and the chaos harness all copy env
names out of it.  It drifts in both directions:

- a PR adds a ``KT_*`` read and forgets the row: the knob exists, ships,
  and nobody can discover it;
- a PR renames or deletes a read and leaves the row: operators set an
  env var the code no longer looks at, silently.

The rule extracts every ``KT_*`` environment READ package-wide from the
call-graph summaries (``FileSummary.env_reads`` — direct
``environ.get``/``getenv``/``setdefault`` calls, Load-context
subscripts, one-hop module-constant indirection, ``env``-named wrapper
helpers, and f-string keys as ``KT_FOO_*`` wildcard patterns) and diffs
the set against the README table's env column.  Matching is
wildcard-aware in both directions (``fnmatch``): a documented
``KT_ADMIT_*`` family row covers every per-class quota read, and a
wildcard READ pattern is covered by any documented member.

Whole-program: the extraction rides the same cached
:class:`~karpenter_tpu.analysis.callgraph.Project` build every other
interprocedural pass shares — no second AST walk.
"""

from __future__ import annotations

from fnmatch import fnmatchcase
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..ktlint import Finding, package_root

ID = "KT022"
TITLE = "KT_* knob read/documentation drift against the README knob table"
HINT = ("every KT_* env read needs a row in README.md's knob table (env "
        "column; `KT_FOO_*` family rows cover dynamic keys), and every "
        "documented knob needs a live read — delete stale rows when a "
        "knob is removed")

WHOLE_PROGRAM = True

#: knobs the analysis toolchain itself reads — still documented, but a
#: fixture run linting ONE file must not demand the whole package's reads
_TABLE_HEADER_TOKEN = "env"


def readme_knobs(text: str) -> List[Tuple[int, str]]:
    """``(lineno, env_name)`` for every ``KT_*`` token in the env column
    of the README's knob table (first markdown table whose header names an
    ``env`` column).  Compound cells (``KT_RPC_RETRIES /
    KT_RPC_BACKOFF_MS``) yield one entry per token."""
    out: List[Tuple[int, str]] = []
    env_col: Optional[int] = None
    for i, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            env_col = None
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if env_col is None:
            heads = [c.strip("`* ").lower() for c in cells]
            if _TABLE_HEADER_TOKEN in heads:
                env_col = heads.index(_TABLE_HEADER_TOKEN)
            continue
        if all(set(c) <= {"-", ":", " "} for c in cells):
            continue  # the |---|---| separator row
        if env_col >= len(cells):
            continue
        for token in cells[env_col].replace("/", " ").split():
            token = token.strip("`,")
            if token.startswith("KT_"):
                out.append((i, token))
    return out


def _covered(pattern: str, others) -> bool:
    return any(fnmatchcase(pattern, o) or fnmatchcase(o, pattern)
               for o in others)


def check(files, project=None, readme: Optional[str] = None,
          ) -> List[Finding]:
    if project is None:
        from ..callgraph import build_project

        project = build_project(files)
    reads: Dict[str, Tuple[str, int]] = {}  # pattern -> first site
    for summ in project.summaries:
        for lineno, pattern in summ.env_reads:
            if pattern not in reads:
                reads[pattern] = (summ.path, lineno)
    # the documented-not-read direction needs the WHOLE package's read
    # set: a fixture run over a handful of files (or one file with a
    # stray env read) must not accuse every documented knob of being
    # dead.  Explicitly-passed readme text (the rule's own fixtures)
    # always diffs both ways.
    whole_package = readme is not None or len(files) > 20
    if readme is None:
        readme_path = package_root().parent / "README.md"
        try:
            readme = readme_path.read_text()
        except OSError:
            return []  # no README (vendored subset): nothing to diff
    knobs = readme_knobs(readme)
    documented = [k for _, k in knobs]
    out: List[Finding] = []
    for pattern in sorted(reads):
        if not _covered(pattern, documented):
            path, lineno = reads[pattern]
            out.append(Finding(
                ID, path, lineno,
                f"`{pattern}` is read here but has no row in the README "
                "knob table — the knob is undiscoverable",
                hint=HINT,
            ))
    if not whole_package:
        return out
    read_patterns = list(reads)
    seen = set()
    for lineno, knob in knobs:
        if knob in seen:
            continue
        seen.add(knob)
        if not _covered(knob, read_patterns):
            out.append(Finding(
                ID, "README.md", lineno,
                f"`{knob}` is documented in the knob table but no code "
                "reads it — operators setting it change nothing",
                hint=HINT,
            ))
    return out
