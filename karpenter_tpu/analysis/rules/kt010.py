"""KT010 — Python-loop-of-device-dispatch on controller paths.

The repo's structural perf rule: a controller that calls the solver once
per candidate inside a Python loop pays one device round trip (dispatch +
fence + host prep) PER ITERATION — the exact shape PR 6 removed from the
deprovisioning controller's consolidation sweep, where N sequential
what-ifs became slots of ONE vmapped dispatch
(solver/consolidation.sweep_what_ifs, ``DeprovisioningController
._simulate_batch``).  Re-introducing a per-candidate ``solve`` /
``_solve_what_if`` / ``_simulate`` call inside a ``for``/``while`` — or a
comprehension/generator expression, the same N dispatches spelled on one
line — in ``controllers/`` silently regresses a reconcile pass from one
fence back to N.

Loops that are GENUINELY sequential — each iteration's input depends on
the previous iteration's solver answer (binary search, invalidate-and-
retry) — cannot batch and carry ``# ktlint: allow[KT010] <reason>`` on the
loop (or call) line, keeping the exemption visible in the diff instead of
implicit in the rule.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, _is_suppressed, dotted_name, file_nodes, file_parents

ID = "KT010"
TITLE = "per-candidate solver call inside a controller loop"
HINT = ("batch the candidates through one dispatch — "
        "solver/consolidation.sweep_what_ifs or "
        "DeprovisioningController._simulate_batch (one vmapped program, "
        "one fence) — or, when iterations are sequentially dependent, "
        "annotate the loop with `# ktlint: allow[KT010] <reason>`")

#: callee names whose per-iteration invocation is a device round trip
SOLVE_CALLS = {"solve", "_solve_what_if", "_simulate"}
#: scoped package (path substring)
SCOPE = ("/controllers/",)


def _in_scope(path: str) -> bool:
    return any(s in path for s in SCOPE)


def _callee(call: ast.Call):
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


#: comprehensions are loops too — ``[self._simulate([c]) for c in cands]``
#: is the for-loop-of-dispatch spelled on one line
_LOOPS = (ast.For, ast.While,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _enclosing_loop(node: ast.AST, parents):
    """The innermost loop (for/while/comprehension) containing ``node``
    (lambdas/defs between the call and the loop break containment — the
    loop body is then a deferred callable, not a per-iteration
    dispatch)."""
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        if isinstance(cur, _LOOPS):
            return cur
    return None


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        parents = file_parents(f)
        for n in file_nodes(f):
            if not isinstance(n, ast.Call):
                continue
            name = _callee(n)
            if name not in SOLVE_CALLS:
                continue
            loop = _enclosing_loop(n, parents)
            if loop is None:
                continue
            # the loop header is the natural annotation point: honor a
            # suppression on it (or the comment block above it) in
            # addition to the call line, which analyze_files checks —
            # probed with a synthetic finding at the loop line so the
            # shared suppression walk stays the single source of truth
            if _is_suppressed(f, Finding(ID, f.path, loop.lineno, "")):
                continue
            where = dotted_name(n.func) or name
            out.append(Finding(
                ID, f.path, n.lineno,
                f"`{where}(...)` runs once per iteration of the "
                f"enclosing loop (line {loop.lineno}) — a device round "
                "trip per candidate where one batched dispatch serves "
                "them all",
                hint=HINT,
            ))
    return out
