"""KT015 — delta-session table discipline + counted delta-path full solves.

Delta serving (docs/ARCHITECTURE.md round 14) holds mutable cross-RPC
state — the per-session warm-start chains in
``service/delta.DeltaSessionTable._sessions`` — behind one declared lock,
and makes one observability promise: a session-routed request that ends
up paying a FULL solve (guard trip, reseed, establishment) is never
invisible — ``karpenter_solver_delta_rpc_total{outcome}`` partitions
every session RPC.  Two bug classes follow, both pinned here:

1. **Unlocked table access.**  Any ``._sessions`` attribute access in the
   service package outside a ``with <...lock>:`` block (``__init__``
   exempt — construction is single-threaded by Python semantics).  This
   deliberately goes beyond KT004's guarded-by check: KT004 stops at the
   declaring class, while the table is reachable from the pipeline and
   the service layer too — a drive-by ``pipe._delta_tab._sessions``
   read from an RPC thread is exactly the race the lock exists for.

2. **Uncounted delta-path solve.**  A delta-path function (name contains
   ``delta``, in ``service/``) that calls a full solve or tensorize
   (``solve`` / ``solve_delta`` / ``tensorize``) without incrementing the
   delta-RPC outcome counter in the same function — the KT009 precedent:
   a fallback that never lands in
   ``karpenter_solver_delta_rpc_total{outcome="fallback_full"}`` turns
   "steady state is sub-ms" dashboards into fiction while every RPC
   quietly re-solves the cluster.

Deliberate exceptions carry ``# ktlint: allow[KT015] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, dotted_name, file_nodes, file_parents

ID = "KT015"
TITLE = "delta-session discipline (unlocked table / uncounted full solve)"
HINT = ("wrap `_sessions` access in `with self._lock:` (service/delta.py's "
        "declared lock), and make every delta-path solve/tensorize land in "
        "karpenter_solver_delta_rpc_total — "
        "`registry.counter(DELTA_RPC).inc({'outcome': ...})` (or the "
        "_counted funnel) in the same function; a deliberate exception "
        "needs `# ktlint: allow[KT015] <reason>`")

#: scoped package (path substring): the serving layer owns every session
SCOPE = ("/service/",)
#: the guarded table attribute
TABLE_ATTR = "_sessions"
#: callee names that pay a full host build / solve on the delta path
SOLVE_CALLS = {"solve", "solve_delta", "tensorize"}
#: metric identifiers accepted as "the delta-RPC outcome counter"
DELTA_METRICS = {"DELTA_RPC", "karpenter_solver_delta_rpc_total"}
#: counting funnels that inc on the caller's behalf
DELTA_HELPERS = {"_counted"}


def _in_scope(path: str) -> bool:
    return any(s in path for s in SCOPE)


def _under_lock(node: ast.AST, parents) -> bool:
    """Lexically inside ``with <something named like a lock>:`` — the
    KT004 shape, widened to any lock-ish context name so helpers that
    take the table's lock through an alias still count."""
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, ast.With):
            for item in cur.items:
                name = dotted_name(item.context_expr) or ""
                leaf = name.rsplit(".", 1)[-1]
                if "lock" in leaf.lower() or leaf == "_cond":
                    return True
    return False


def _enclosing_function(node: ast.AST, parents):
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
    return None


def _counts_delta(func: ast.AST) -> bool:
    """Does this function inc the delta-RPC counter (directly or via a
    counting funnel, nested defs included)?"""
    for n in ast.walk(func):
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute):
            if n.func.attr in DELTA_HELPERS:
                return True
            if n.func.attr == "inc":
                recv = n.func.value
                if (isinstance(recv, ast.Call)
                        and isinstance(recv.func, ast.Attribute)
                        and recv.func.attr == "counter" and recv.args):
                    arg = recv.args[0]
                    if isinstance(arg, ast.Name) and arg.id in DELTA_METRICS:
                        return True
                    if (isinstance(arg, ast.Constant)
                            and arg.value in DELTA_METRICS):
                        return True
        elif isinstance(n.func, ast.Name) and n.func.id in DELTA_HELPERS:
            return True
    return False


def _callee(call: ast.Call):
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        parents = file_parents(f)
        for n in file_nodes(f):
            # ---- part 1: unlocked session-table access ------------------
            if isinstance(n, ast.Attribute) and n.attr == TABLE_ATTR:
                func = _enclosing_function(n, parents)
                if func is not None and func.name == "__init__":
                    continue  # construction is single-threaded
                if func is not None and func.name.endswith("_locked"):
                    # the repo's caller-holds-the-lock convention: the
                    # suffix IS the contract, and every caller must sit
                    # under the `with` itself — the sanitizer's runtime
                    # watcher covers the dynamic side
                    continue
                if _under_lock(n, parents):
                    continue
                out.append(Finding(
                    ID, f.path, n.lineno,
                    f"`{dotted_name(n) or TABLE_ATTR}` accessed outside "
                    "the session table's lock — the table is shared "
                    "between the pipeline dispatcher and shutdown, and "
                    "an unlocked peek races eviction",
                    hint=HINT,
                ))
                continue
            # ---- part 2: uncounted delta-path full solve ----------------
            if not isinstance(n, ast.Call):
                continue
            name = _callee(n)
            if name not in SOLVE_CALLS:
                continue
            func = _enclosing_function(n, parents)
            if func is None or "delta" not in func.name.lower():
                continue
            if _counts_delta(func):
                continue
            where = dotted_name(n.func) or name
            out.append(Finding(
                ID, f.path, n.lineno,
                f"`{where}(...)` runs a full solve/tensorize on the "
                f"delta path but `{func.name}` never lands an outcome in "
                "karpenter_solver_delta_rpc_total — an uncounted "
                "fallback makes every steady-state dashboard lie",
                hint=HINT,
            ))
    return out
