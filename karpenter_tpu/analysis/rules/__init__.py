"""ktlint rule modules.  Each module exposes ``ID``, ``TITLE``, ``HINT`` and
``check(files) -> list[Finding]``; the catalog lives in docs/ANALYSIS.md."""

from . import (kt001, kt002, kt003, kt004, kt005, kt006, kt007, kt008, kt009,
               kt010, kt011)

ALL_RULES = (kt001, kt002, kt003, kt004, kt005, kt006, kt007, kt008, kt009,
             kt010, kt011)

__all__ = ["ALL_RULES", "kt001", "kt002", "kt003", "kt004", "kt005", "kt006",
           "kt007", "kt008", "kt009", "kt010", "kt011"]
