"""ktlint rule modules.  Each module exposes ``ID``, ``TITLE``, ``HINT`` and
``check(files) -> list[Finding]``; whole-program rules additionally set
``WHOLE_PROGRAM = True`` and accept ``check(files, project=None)`` — the
driver builds one :class:`~karpenter_tpu.analysis.callgraph.Project` per
run and shares it.  The catalog lives in docs/ANALYSIS.md."""

from . import (kt001, kt002, kt003, kt004, kt005, kt006, kt007, kt008, kt009,
               kt010, kt011, kt012, kt013, kt014, kt015, kt016, kt017,
               kt018, kt019, kt020, kt021, kt022, kt023, kt024, kt025)

ALL_RULES = (kt001, kt002, kt003, kt004, kt005, kt006, kt007, kt008, kt009,
             kt010, kt011, kt012, kt013, kt014, kt015, kt016, kt017, kt018,
             kt019, kt020, kt021, kt022, kt023, kt024, kt025)

__all__ = ["ALL_RULES", "kt001", "kt002", "kt003", "kt004", "kt005", "kt006",
           "kt007", "kt008", "kt009", "kt010", "kt011", "kt012", "kt013",
           "kt014", "kt015", "kt016", "kt017", "kt018", "kt019", "kt020",
           "kt021", "kt022", "kt023", "kt024", "kt025"]
