"""KT012 — whole-program lock-order deadlock detection.

The serving stack holds ~10 declared locks (batcher, admission
queue/breaker/facade, SolvePipeline, SolverService, scheduler, solver,
guard, operator).  Two threads acquiring two locks in opposite orders is a
deadlock waiting for load to find it — and the nesting that creates the
order is usually *interprocedural*: a method holds its own lock while
calling through a facade into a component that takes another.

This pass extracts every ``with <lock>:`` nesting, propagates lock-held
sets across the project call graph (``analysis/callgraph.py``), and builds
the global lock-acquisition-order graph:

- edge ``A -> B``: some path acquires ``B`` while holding ``A`` — either
  lexically (``with A: with B:``) or through a call chain (``with A:
  f()`` where ``f`` transitively acquires ``B``).
- **any cycle is a finding**, reported once with the witness path for each
  edge in the cycle (file:line of the outer acquisition plus the call
  chain that reaches the inner one).
- a **self-edge on a non-reentrant lock** (``threading.Lock``) is also a
  finding: the same thread re-acquiring it is a self-deadlock.  RLock /
  Condition self-edges are legal and skipped (the admission queue's
  ``_bump`` re-acquires its own Condition by design).

Known limits (by design, covered dynamically by the sanitizer's runtime
lock-order watcher — analysis/sanitize.py, KT_SANITIZE=1): acquisitions
inside closures/lambdas run where they are *called*, not where they are
written, so closure bodies contribute no static edges; callback
indirection (future done-callbacks, ``on_*`` hooks) is invisible here.
The acquisition order the pass derives is exported via :func:`lock_graph`
/ :func:`lock_order`; ``sanitize.LOCK_ORDER`` must stay a linear extension
of it (tests/test_lint.py cross-validates the two).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import FuncNode, Project, build_project
from ..ktlint import Finding

ID = "KT012"
TITLE = "lock-order deadlock (cycle in the global acquisition-order graph)"
#: the driver builds ONE Project per run and hands it to every
#: whole-program rule (KT012-KT014) instead of each re-linking the world
WHOLE_PROGRAM = True
HINT = ("pick ONE global order for the locks in the cycle and acquire them "
        "in it everywhere (docs/ANALYSIS.md holds the current table), or "
        "restructure so the inner acquisition happens outside the outer "
        "critical section; allow[KT012] only with a reason that names why "
        "the inversion cannot deadlock")


class _Edge:
    __slots__ = ("src", "dst", "path", "line", "chain")

    def __init__(self, src: str, dst: str, path: str, line: int,
                 chain: List[str]):
        self.src = src          #: held lock
        self.dst = dst          #: acquired lock
        self.path = path        #: file of the outer acquisition
        self.line = line        #: line of the outer acquisition
        self.chain = chain      #: call chain from holder to acquirer

    def witness(self) -> str:
        via = " -> ".join(self.chain)
        route = f" via {via}" if via else " (lexical nesting)"
        return (f"`{self.src}` held at {self.path}:{self.line}, "
                f"`{self.dst}` acquired{route}")


def _direct_acquisitions(
    project: Project,
) -> Dict[str, List[Tuple[str, Optional[str], int, int, int]]]:
    """fid -> [(lock id, kind, with-line, span start, span end)]."""
    out: Dict[str, List[Tuple[str, Optional[str], int, int, int]]] = {}
    for fid, node in project.funcs.items():
        acq = []
        for lineno, end, ref in node.summary.locks:
            lock = project.lock_id(node, ref)
            if lock is None:
                continue  # unresolvable receiver: no node, no edge
            acq.append((lock, project.lock_kind(node, ref), lineno, lineno,
                        end))
        if acq:
            out[fid] = acq
    return out


def _transitive_locks(
    project: Project,
    direct: Dict[str, List[Tuple[str, Optional[str], int, int, int]]],
) -> Dict[str, Dict[str, Tuple[str, int, Optional[str]]]]:
    """fid -> {lock id: how it is first reached}.

    The "how" is ``("direct", line, None)`` for an own acquisition or
    ``("call", line, callee fid)`` for one reached through a call edge —
    enough to reconstruct a witness chain without storing every path.
    Fixpoint iteration, so recursion (direct or mutual) terminates."""
    acq: Dict[str, Dict[str, Tuple[str, int, Optional[str]]]] = {}
    for fid, node in project.funcs.items():
        acq[fid] = {}
        for lock, _kind, line, _s, _e in direct.get(fid, []):
            acq[fid].setdefault(lock, ("direct", line, None))
    changed = True
    while changed:
        changed = False
        for fid, node in project.funcs.items():
            mine = acq[fid]
            for line, callee, in_closure in node.edges:
                if in_closure or callee == fid:
                    continue
                for lock in acq.get(callee, ()):
                    if lock not in mine:
                        mine[lock] = ("call", line, callee)
                        changed = True
    return acq


def _chain_to(project: Project, acq, fid: str, lock: str,
              limit: int = 12) -> List[str]:
    """Reconstruct one call chain from ``fid`` to the function that
    directly acquires ``lock`` by following the "how" pointers."""
    chain: List[str] = []
    seen: Set[str] = set()
    cur = fid
    while cur is not None and cur not in seen and len(chain) < limit:
        seen.add(cur)
        chain.append(_pretty(project, cur))
        how = acq.get(cur, {}).get(lock)
        if how is None or how[0] == "direct":
            break
        cur = how[2]
    return chain


def _pretty(project: Project, fid: str) -> str:
    node = project.funcs[fid]
    return node.summary.qual


def lock_graph(files, project: Optional[Project] = None):
    """The global lock-acquisition-order graph over ``files``.

    Returns ``(nodes, edges, kinds)``: ``nodes`` is the sorted set of lock
    ids seen acquired, ``edges`` a dict ``(src, dst) -> _Edge`` holding one
    witness per ordered pair, ``kinds`` a dict ``lock id -> kind name`` (or
    None when the declaration was not found)."""
    project = project if project is not None else build_project(files)
    direct = _direct_acquisitions(project)
    trans = _transitive_locks(project, direct)
    nodes: Set[str] = set()
    kinds: Dict[str, Optional[str]] = {}
    edges: Dict[Tuple[str, str], _Edge] = {}

    for fid, acqs in direct.items():
        node = project.funcs[fid]
        for lock, kind, line, _s, _e in acqs:
            nodes.add(lock)
            if kinds.get(lock) is None:
                kinds[lock] = kind

    def add_edge(src: str, dst: str, path: str, line: int,
                 chain: List[str]) -> None:
        key = (src, dst)
        if key not in edges:
            edges[key] = _Edge(src, dst, path, line, chain)

    for fid, acqs in direct.items():
        node = project.funcs[fid]
        for i, (lock, _kind, line, start, end) in enumerate(acqs):
            # lexical nesting: a later acquisition inside this with-span.
            # Same-line entries (`with self._a, self._b:`, one-line nested
            # withs) share start/end; extraction order is source order, so
            # a later list index at the same line is the INNER acquisition.
            for j, (lock2, _k2, line2, _s2, _e2) in enumerate(acqs):
                if start < line2 <= end or (line2 == start and j > i):
                    add_edge(lock, lock2, node.path, line,
                             [_pretty(project, fid)])
            # call-propagated: every lock a callee transitively acquires.
            # `start <= cline` (not <): a one-line body `with self._lock:
            # self.callee()` puts the call on the with's own line.
            for cline, callee, in_closure in node.edges:
                if in_closure or not (start <= cline <= end):
                    continue
                for lock2 in trans.get(callee, ()):
                    chain = [_pretty(project, fid)] + _chain_to(
                        project, trans, callee, lock2)
                    add_edge(lock, lock2, node.path, line, chain)

    return sorted(nodes), edges, kinds


def lock_order(files, project: Optional[Project] = None,
               graph=None) -> List[str]:
    """One global acquisition order consistent with every observed edge
    (topological order of the graph; cycles — which are findings — are
    broken arbitrarily so the table stays printable).  Pass ``graph`` (a
    prior :func:`lock_graph` result) to skip recomputing it."""
    nodes, edges, _kinds = graph if graph is not None \
        else lock_graph(files, project)
    out_edges: Dict[str, Set[str]] = {n: set() for n in nodes}
    indeg: Dict[str, int] = {n: 0 for n in nodes}
    for (src, dst) in edges:
        if src != dst and dst not in out_edges[src]:
            out_edges[src].add(dst)
            indeg[dst] += 1
    order: List[str] = []
    ready = sorted(n for n in nodes if indeg[n] == 0)
    while ready:
        n = ready.pop(0)
        order.append(n)
        for m in sorted(out_edges[n]):
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
        ready.sort()
    for n in nodes:  # cycle remnants: append so the table is total
        if n not in order:
            order.append(n)
    return order


def _find_cycles(nodes: List[str],
                 edges: Dict[Tuple[str, str], _Edge]) -> List[List[str]]:
    """Elementary cycles, deduped by node set (one finding per deadlock,
    not one per rotation)."""
    adj: Dict[str, List[str]] = {n: [] for n in nodes}
    for (src, dst) in edges:
        if src != dst:
            adj[src].append(dst)
    cycles: List[List[str]] = []
    seen_sets: Set[frozenset] = set()

    def dfs(start: str, cur: str, path: List[str], visited: Set[str]) -> None:
        for nxt in sorted(adj.get(cur, ())):
            if nxt == start and len(path) >= 2:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in visited and nxt > start:
                # only walk nodes ordered after start: each cycle is found
                # exactly once, from its smallest node
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(nodes):
        dfs(n, n, [n], {n})
    return cycles


def check(files, project: Optional[Project] = None) -> List[Finding]:
    project = project if project is not None else build_project(files)
    nodes, edges, kinds = lock_graph(files, project)
    out: List[Finding] = []

    # self-deadlock: nested acquisition of a non-reentrant lock
    for (src, dst), edge in sorted(edges.items()):
        if src == dst and kinds.get(src) == "Lock":
            out.append(Finding(
                ID, edge.path, edge.line,
                f"nested acquisition of non-reentrant lock `{src}`: the "
                "holding thread re-acquiring a threading.Lock deadlocks "
                f"itself ({edge.witness()})",
                hint="use threading.RLock if re-entry is intended, or lift "
                     "the inner acquisition out of the critical section",
            ))

    for cycle in _find_cycles(nodes, edges):
        pairs = [(cycle[i], cycle[(i + 1) % len(cycle)])
                 for i in range(len(cycle))]
        witnesses = "; ".join(
            f"witness {edges[p].src} -> {edges[p].dst}: {edges[p].witness()}"
            for p in pairs if p in edges)
        anchor = edges[pairs[0]]
        out.append(Finding(
            ID, anchor.path, anchor.line,
            "lock-order cycle "
            + " -> ".join(f"`{n}`" for n in cycle + [cycle[0]])
            + f" — two threads taking opposite routes deadlock; {witnesses}",
            hint=HINT,
        ))
    return out
