"""KT004 — lock discipline for ``# guarded-by:``-declared attributes.

The PR 1 scheduler re-entrancy race happened because shared state grew more
reader/writer threads than its lock discipline was written for.  Attributes
that ARE cross-thread are now declared at their initialization site::

    self._compiling: set = set()  # guarded-by: _lock

and this rule enforces that every other read/write of ``self._compiling``
inside the declaring class sits lexically within a ``with self._lock:``
block.  ``__init__`` is exempt (construction is single-threaded by Python
semantics); every other method is assumed reachable from both the dispatcher
thread and the RPC path — reachability is not computed, because a method
that is single-threaded *today* is one refactor away from not being, which
is exactly how the PR 1 race was born.

Known limits (documented, not silent): aliasing (``q = self._queued``) and
access from outside the declaring class are not tracked — the runtime
sanitizer (``analysis/sanitize.py``, ``KT_SANITIZE=1``) covers those
dynamically.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..ktlint import Finding, GUARDED_RE, file_nodes, file_parents

ID = "KT004"
TITLE = "guarded-by attribute accessed outside its lock"
HINT = ("wrap the access in `with self.<lock>:` (or move it into __init__); "
        "deliberately lock-free access needs `# ktlint: allow[KT004] <why>`")

_DECL_RE = re.compile(r"self\.(?P<attr>\w+)\s*(?::[^=]*)?=")


def _declarations(f) -> List[Tuple[int, str, str]]:
    """(lineno, attr, lock) for every `self.x = ... # guarded-by: lock`."""
    out = []
    for i, line in enumerate(f.lines, 1):
        g = GUARDED_RE.search(line)
        if g is None:
            continue
        d = _DECL_RE.search(line)
        if d is not None:
            out.append((i, d.group("attr"), g.group("lock")))
    return out


def _enclosing_class(f, lineno: int) -> Optional[ast.ClassDef]:
    best = None
    for node in file_nodes(f):
        if isinstance(node, ast.ClassDef) and \
                node.lineno <= lineno <= (node.end_lineno or node.lineno):
            if best is None or node.lineno > best.lineno:  # innermost
                best = node
    return best


def _under_lock(node: ast.AST, parents, lock: str) -> bool:
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, ast.With):
            for item in cur.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Attribute) and ce.attr == lock
                        and isinstance(ce.value, ast.Name)
                        and ce.value.id == "self"):
                    return True
    return False


def _enclosing_funcname(node: ast.AST, parents) -> Optional[str]:
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
    return None


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        decls = _declarations(f)
        if not decls:
            continue
        by_class: Dict[ast.ClassDef, Dict[str, str]] = {}
        decl_lines = set()
        for lineno, attr, lock in decls:
            cls = _enclosing_class(f, lineno)
            if cls is None:
                continue  # module-level guarded-by: nothing to scope to
            by_class.setdefault(cls, {})[attr] = lock
            decl_lines.add((attr, lineno))
        for cls, attrs in by_class.items():
            parents = file_parents(f)
            for n in ast.walk(cls):
                if not (isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self" and n.attr in attrs):
                    continue
                if (n.attr, n.lineno) in decl_lines:
                    continue  # the declaration itself
                fname = _enclosing_funcname(n, parents)
                if fname in ("__init__", "__new__"):
                    continue
                lock = attrs[n.attr]
                if _under_lock(n, parents, lock):
                    continue
                # nearest innermost method name for the message
                out.append(Finding(
                    ID, f.path, n.lineno,
                    f"`self.{n.attr}` is declared `# guarded-by: {lock}` but "
                    f"accessed outside `with self.{lock}:` in "
                    f"`{cls.name}.{fname or '?'}`",
                    hint=HINT,
                ))
    return out
