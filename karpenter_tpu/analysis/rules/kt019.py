"""KT019 — wire-crossing trace context: forwarded on send, adopted via
the facade on receive.

ISSUE 15 made one request = ONE trace across the fleet: SolveRequest
carries ``trace_id``/``parent_span``, and every server hop adopts the
remote parent so cross-replica journeys (session failover, drain
re-homes, forwarded megabatch slots) render as one tree in ``/fleetz``.
The guarantee is only as good as its weakest hop — ONE send site that
encodes a request without the context (a new retry path, a fresh
forwarding shim) silently orphans every downstream hop, and one server
entry that decodes the context but opens its trace with a bare
``tracer.start`` drops the parent link it just read.  Both bugs are
invisible in single-replica tests, which is exactly why they are pinned
statically:

- **Send half** (``service/client.py``, ``parallel/forward.py`` — the
  wire-crossing client layer): every ``codec.encode_request(...)`` call
  must pass a ``trace_id=`` keyword.  ``encode_warm_request`` (warmup is
  fire-and-forget, never part of a request tree) is out of scope.
- **Receive half** (``service/server.py``): any function that calls
  ``decode_trace_fields(...)`` must open its trace through the
  ``Tracer.start_remote`` facade — the one place the adopt-vs-local
  decision, sampling bypass, and remote-parent stamping live.

Scripts, tests, and bench drivers are out of scope (they drive the
facades, which already comply).  Deliberate exceptions carry
``# ktlint: allow[KT019] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, dotted_name, file_nodes

ID = "KT019"
TITLE = "wire-crossing send/receive without trace-context discipline"
HINT = ("send sites pass trace_id=/parent_span= (trace.wire_context()) "
        "into codec.encode_request; server entries that decode_trace_fields "
        "must open their trace via tracer.start_remote(...) — a deliberate "
        "exception needs `# ktlint: allow[KT019] <reason>`")

#: the wire-crossing CLIENT layer: every request encoded here rides a
#: transport another replica serves
SEND_SCOPE = ("service/client.py", "parallel/forward.py")
#: the serving entries that decode remote parents
SERVE_SCOPE = ("service/server.py",)
ENCODER = "encode_request"
DECODER = "decode_trace_fields"
FACADE = "start_remote"


def _ends_with(path: str, suffixes) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(s) for s in suffixes)


def _leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _check_send(f) -> List[Finding]:
    out: List[Finding] = []
    for n in file_nodes(f):
        if not isinstance(n, ast.Call) or _leaf(n) != ENCODER:
            continue
        if any(kw.arg == "trace_id" for kw in n.keywords):
            continue
        where = dotted_name(n.func) or ENCODER
        out.append(Finding(
            ID, f.path, n.lineno,
            f"`{where}(...)` encodes a wire-crossing request without "
            "forwarding the trace context (no trace_id= keyword) — every "
            "hop this request takes downstream becomes an orphan tree in "
            "/fleetz",
            hint=HINT,
        ))
    return out


def _check_serve(f) -> List[Finding]:
    out: List[Finding] = []
    for fn in file_nodes(f):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        decodes = [n for n in ast.walk(fn)
                   if isinstance(n, ast.Call) and _leaf(n) == DECODER]
        if not decodes:
            continue
        if any(isinstance(n, ast.Call) and _leaf(n) == FACADE
               for n in ast.walk(fn)):
            continue
        for n in decodes:
            out.append(Finding(
                ID, f.path, n.lineno,
                f"`{fn.name}` decodes a remote trace context "
                f"({DECODER}) but never opens its trace through the "
                f"Tracer.{FACADE} facade — the parent link it just read "
                "is dropped and the hop roots as an orphan",
                hint=HINT,
            ))
    return out


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if _ends_with(f.path, SEND_SCOPE):
            out.extend(_check_send(f))
        if _ends_with(f.path, SERVE_SCOPE):
            out.extend(_check_serve(f))
    return out
