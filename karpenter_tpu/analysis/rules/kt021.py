"""KT021 — wire-compatibility gate for the solver proto schema.

The gRPC boundary (``service/solver.proto``) is the one surface a
rolling upgrade cannot atomically change: old clients talk to new
servers and vice versa for the whole deploy window.  Three edits are
silently wire-breaking even though every test on ONE side still passes:

- **field-number reuse** — rebinding a number to a new name/meaning
  makes old payloads decode into the wrong field, no error anywhere;
- **type/label change** — ``int64 -> string`` or ``optional ->
  repeated`` on a live number changes the wire type; old messages
  decode garbage or drop the field;
- **removal without a tombstone** — deleting a field frees its number
  for accidental reuse next quarter; proto requires a ``reserved N;``
  tombstone to keep it burned.

The rule parses the CURRENT ``solver.proto`` with a pure-stdlib textual
parser and diffs it against the committed golden descriptor snapshot
(``analysis/solver_descriptor.golden.json`` — fields, numbers, types,
labels, reserved ranges).  Legitimate schema growth refreshes the golden
explicitly (``python -m karpenter_tpu.analysis --proto-golden``), so the
diff shows up in review as a one-line JSON change next to the .proto
edit.  It also cross-checks ``solver_pb2.py`` staleness: every live
field name must appear in the generated module's serialized descriptor
(regenerate with ``python scripts/gen_proto.py`` — the image has no
protoc).
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional

from ..ktlint import Finding, package_root

ID = "KT021"
TITLE = "wire-breaking solver.proto change vs the golden descriptor"
HINT = ("never rebind or retype a live field number; removals must leave "
        "`reserved N;` tombstones.  Additive changes: add the field, run "
        "`python scripts/gen_proto.py`, then refresh the golden with "
        "`python -m karpenter_tpu.analysis --proto-golden`")

PROTO_PATH = "karpenter_tpu/service/solver.proto"
GOLDEN_NAME = "solver_descriptor.golden.json"

_MSG_RE = re.compile(r"^message\s+(\w+)\s*\{")
_RESERVED_RE = re.compile(r"^reserved\s+(.+);")
_FIELD_RE = re.compile(
    r"^(?:(repeated|optional|required)\s+)?"
    r"(map<[^>]+>|[\w.]+)\s+(\w+)\s*=\s*(\d+)\s*(?:;|\[)")


def parse_proto(text: str) -> Dict[str, dict]:
    """``{message: {"line", "fields": {number: {"name","type","label",
    "line"}}, "reserved": [numbers]}}`` — messages keyed by their dotted
    nesting path.  Textual and deliberately narrow: it parses THIS
    repo's proto dialect (proto3, no oneofs/enums/extensions), and
    anything it cannot parse it skips rather than misreads."""
    out: Dict[str, dict] = {}
    stack: List[str] = []
    for i, raw in enumerate(text.splitlines(), 1):
        line = raw.split("//", 1)[0].strip()
        if not line:
            continue
        m = _MSG_RE.match(line)
        if m:
            stack.append(m.group(1))
            out[".".join(stack)] = {"line": i, "fields": {}, "reserved": []}
            continue
        if line.startswith("}"):
            if stack:
                stack.pop()
            continue
        if not stack:
            continue
        cur = out[".".join(stack)]
        m = _RESERVED_RE.match(line)
        if m:
            for part in m.group(1).split(","):
                toks = part.split()
                if len(toks) == 3 and toks[1] == "to":
                    cur["reserved"].extend(
                        range(int(toks[0]), int(toks[2]) + 1))
                elif part.strip().isdigit():
                    cur["reserved"].append(int(part.strip()))
            continue
        m = _FIELD_RE.match(line)
        if m:
            label, ftype, name, number = m.groups()
            cur["fields"][int(number)] = {
                "name": name, "type": ftype, "label": label or "",
                "line": i}
    return out


def golden_path() -> Path:
    return package_root() / "analysis" / GOLDEN_NAME


def snapshot(proto: Dict[str, dict]) -> dict:
    """The golden's JSON shape: line numbers stripped (they churn with
    comments; the WIRE facts are fields/numbers/types/labels/reserved)."""
    return {
        msg: {
            "fields": {
                str(num): {k: v for k, v in f.items() if k != "line"}
                for num, f in sorted(m["fields"].items())},
            "reserved": sorted(m["reserved"]),
        }
        for msg, m in sorted(proto.items())
    }


def write_golden(path: Optional[Path] = None) -> Path:
    """(Re)write the golden from the live proto — the explicit, reviewed
    step that blesses a schema change."""
    proto = parse_proto(
        (package_root().parent / PROTO_PATH).read_text())
    out = path or golden_path()
    out.write_text(json.dumps(snapshot(proto), indent=2, sort_keys=True)
                   + "\n")
    return out


def check(files, proto_text: Optional[str] = None,
          golden: Optional[dict] = None,
          pb2_text: Optional[str] = None) -> List[Finding]:
    fixture = proto_text is not None
    if not fixture and not any("karpenter_tpu/service/" in f.path
                               for f in files):
        return []  # per-file run outside the wire surface
    if proto_text is None:
        try:
            proto_text = (package_root().parent / PROTO_PATH).read_text()
        except OSError:
            return []
    live = parse_proto(proto_text)
    if golden is None:
        try:
            golden = json.loads(golden_path().read_text())
        except (OSError, ValueError):
            return [Finding(
                ID, PROTO_PATH, 1,
                "no readable golden descriptor snapshot "
                f"(analysis/{GOLDEN_NAME}) — the wire-compat gate has "
                "nothing to diff against",
                hint=HINT)]
    out: List[Finding] = []
    for msg, gm in sorted(golden.items()):
        lm = live.get(msg)
        if lm is None:
            out.append(Finding(
                ID, PROTO_PATH, 1,
                f"message `{msg}` was removed from the schema — old "
                "peers still send/expect it",
                hint=HINT))
            continue
        live_reserved = set(lm["reserved"])
        for num_s, gf in sorted(gm["fields"].items(), key=lambda kv:
                                int(kv[0])):
            num = int(num_s)
            lf = lm["fields"].get(num)
            if lf is None:
                if num not in live_reserved:
                    out.append(Finding(
                        ID, PROTO_PATH, lm["line"],
                        f"`{msg}.{gf['name']}` (field {num}) was removed "
                        f"without a `reserved {num};` tombstone — the "
                        "number is free for silent reuse",
                        hint=HINT))
                continue
            if lf["name"] != gf["name"]:
                out.append(Finding(
                    ID, PROTO_PATH, lf["line"],
                    f"field number {num} of `{msg}` was re-bound: "
                    f"`{gf['name']}` -> `{lf['name']}` — old payloads "
                    "decode into the wrong field",
                    hint=HINT))
            elif (lf["type"] != gf["type"]
                  or lf["label"] != gf["label"]):
                was = f"{gf['label']} {gf['type']}".strip()
                now = f"{lf['label']} {lf['type']}".strip()
                out.append(Finding(
                    ID, PROTO_PATH, lf["line"],
                    f"`{msg}.{lf['name']}` (field {num}) changed wire "
                    f"shape: `{was}` -> `{now}`",
                    hint=HINT))
        golden_reserved = set(gm.get("reserved", []))
        for num, lf in sorted(lm["fields"].items()):
            if str(num) in gm["fields"]:
                continue
            if num in golden_reserved:
                out.append(Finding(
                    ID, PROTO_PATH, lf["line"],
                    f"`{msg}.{lf['name']}` re-uses field number {num}, "
                    "which is a reserved tombstone of a removed field",
                    hint=HINT))
            else:
                out.append(Finding(
                    ID, PROTO_PATH, lf["line"],
                    f"`{msg}.{lf['name']}` (field {num}) is not in the "
                    "golden descriptor — refresh it so the addition is "
                    "an explicit, reviewed wire change",
                    hint=HINT))
    # ---- generated-module staleness ------------------------------------
    if pb2_text is None and not fixture:
        try:
            pb2_text = (package_root() / "service"
                        / "solver_pb2.py").read_text()
        except OSError:
            pb2_text = None
    if pb2_text is not None:
        for msg, lm in sorted(live.items()):
            for num, lf in sorted(lm["fields"].items()):
                # the serialized FileDescriptorProto embeds every field
                # name as plain bytes — absence means the module predates
                # the .proto edit
                if lf["name"] not in pb2_text:
                    out.append(Finding(
                        ID, PROTO_PATH, lf["line"],
                        f"`{msg}.{lf['name']}` is in solver.proto but "
                        "solver_pb2.py has never heard of it — "
                        "regenerate with `python scripts/gen_proto.py`",
                        hint=HINT))
    return out
