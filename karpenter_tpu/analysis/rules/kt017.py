"""KT017 — session-spool facade discipline (the lease API stays home).

ISSUE 13 made the session spool the FLEET's handoff medium: per-session
record files guarded by ownership leases under ``KT_SESSION_DIR``
(``service/snapshot.py``), with ``service/delta.DeltaSessionTable`` as the
one consumer (snapshot / restore / adopt / handoff / own).  The protocol's
whole guarantee — two replicas can never both adopt a chain — rests on
every record and lease operation flowing through those two files: a
drive-by ``snap.read_record(...)`` from the server layer, or an
``open()`` of a lease path from a handler, reads state the lease does not
cover (or writes state the lease protects), and the exactly-one-owner
proof quietly stops being one.

So: any call to the spool/lease primitive surface (the names in
:data:`SPOOL_PRIMITIVES`) in ``karpenter_tpu/service/`` OUTSIDE
``service/snapshot.py`` (the API home) and ``service/delta.py`` (the
table facade) is a finding — the KT016 "sanctioned home" precedent.
Scripts, tests, and other packages are out of scope (the chaos harness
peeks deliberately).

Deliberate exceptions carry ``# ktlint: allow[KT017] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, dotted_name, file_nodes

ID = "KT017"
TITLE = "session-spool access outside the snapshot.py lease API"
HINT = ("route record/lease operations through DeltaSessionTable "
        "(snapshot/restore/adopt/handoff/own) — service/snapshot.py owns "
        "the primitives and service/delta.py is the one facade; a "
        "deliberate exception needs `# ktlint: allow[KT017] <reason>`")

#: the scoped package (path substring)
SCOPE = ("/service/",)
#: the sanctioned homes: the primitive API itself + the table facade
HOMES = ("/service/snapshot.py", "/service/delta.py")
#: the record/lease primitive surface (service/snapshot.py) — calling any
#: of these outside the homes bypasses the exactly-one-owner protocol
SPOOL_PRIMITIVES = {
    "claim_lease", "release_lease", "lease_state", "lease_path",
    "write_record", "read_record", "remove_record", "list_sessions",
    "session_path", "spool_path", "write_atomic",
}


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(s in p for s in SCOPE) and not any(h in p for h in HOMES)


def _leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        for n in file_nodes(f):
            if not isinstance(n, ast.Call):
                continue
            name = _leaf(n)
            if name not in SPOOL_PRIMITIVES:
                continue
            where = dotted_name(n.func) or name
            out.append(Finding(
                ID, f.path, n.lineno,
                f"`{where}(...)` touches the session spool/lease "
                "primitives outside service/snapshot.py's lease API — "
                "record and lease state is guarded by the exactly-one-"
                "owner protocol, and only the DeltaSessionTable facade "
                "(service/delta.py) may drive it",
                hint=HINT,
            ))
    return out
