"""KT011 — sharding/layout objects constructed on the per-call serving path.

The KT008 precedent, applied to device LAYOUT: ``jax.sharding.Mesh`` /
``NamedSharding`` construction and raw ``device_put`` calls belong at
program-BUILD time, not inside per-flush serving functions.  A sharding
object rebuilt per solve is re-hashed into every ``device_put`` and every
jit-cache lookup on the hot path, and — worse — makes it easy to drift the
layout between the program that compiled and the flush that dispatches
(two ``NamedSharding(mesh, P(...))`` built at different sites are equal
today and silently diverge the day one spec changes).  PR 7's sharded
megabatch made layout part of the compile signature, so the construction
sites must be as disciplined as the jit sites KT008 pinned.

``parallel/`` is the sanctioned home: ``parallel/mesh.py`` owns the cached
factories (``slot_mesh`` / ``slot_sharding`` / ``axis_sharding`` — built
once per (mesh, spec), hashable-mesh-keyed) and ``parallel/distributed.py``
owns the multi-process-safe ``put_sharded``.  Serving code imports those;
it never constructs layout inline.

Scope: the serving-path packages (``solver/``, ``ops/``, ``service/``)
plus ``batcher.py``.  Module-level construction (a constant layout next to
a module-level jit) is fine; genuinely per-call uses off the steady-state
path (measurement branches, dryrun validation) carry
``# ktlint: allow[KT011] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, dotted_name, file_functions

ID = "KT011"
TITLE = "sharding/layout construction on the per-call serving path"
HINT = ("build layout once: use the cached factories in parallel/mesh.py "
        "(slot_mesh / slot_sharding / axis_sharding) and "
        "parallel/distributed.put_sharded instead of constructing "
        "Mesh/NamedSharding or calling device_put inside a serving "
        "function; sharding objects are program-build-time state, exactly "
        "like the module-level jits KT008 pins")

#: serving-path scope (package-relative path prefixes / exact files);
#: parallel/ is deliberately absent — it is the sanctioned construction home
SERVING_DIRS = (
    "karpenter_tpu/solver/",
    "karpenter_tpu/ops/",
    "karpenter_tpu/service/",
)
SERVING_FILES = ("karpenter_tpu/batcher.py",)

#: layout-object constructors whose per-call invocation the rule flags
LAYOUT_CTORS = frozenset({
    "Mesh", "NamedSharding", "PositionalSharding", "GSPMDSharding",
    "SingleDeviceSharding",
})
#: raw placement calls (the helpers in parallel/ wrap these once)
PLACEMENT_CALLS = frozenset({"device_put"})


def _in_scope(path: str) -> bool:
    return (any(path.startswith(d) for d in SERVING_DIRS)
            or path in SERVING_FILES)


def _offender(node: ast.AST) -> Optional[str]:
    """The flagged callee name if ``node`` is a layout construction or a
    raw placement call, else None."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if leaf in LAYOUT_CTORS or leaf in PLACEMENT_CALLS:
        return name
    return None


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        for qual, fn, nested in file_functions(f):
            if nested:
                continue  # closures walk with their enclosing function
            for stmt in fn.body:
                for n in ast.walk(stmt):
                    name = _offender(n)
                    if name is None:
                        continue
                    kind = ("raw device_put"
                            if name.rsplit(".", 1)[-1] in PLACEMENT_CALLS
                            else f"`{name}` construction")
                    out.append(Finding(
                        ID, f.path, n.lineno,
                        f"{kind} inside `{qual}` — layout objects are "
                        "rebuilt (and re-hashed) per call on the serving "
                        "path; build them once via the parallel/ factories",
                        hint=HINT))
    return out
