"""KT007 — traces/spans must be opened via a ``with`` context manager.

A ``Tracer.start()`` (or ``Trace.span()``) whose result is not immediately
the context expression of a ``with`` leaks an open trace/span on ANY
exception path between start and close: the trace never reaches the flight
recorder, its spans never land in the duration histograms, and — worse —
the per-thread open-span stack keeps nesting later spans under a corpse.
The obs API is built so the context-managed form is always available
(cross-thread phases use ``Trace.record``, which returns a span born
closed), so a bare start is a bug, not a style choice.

Scope: calls to ``.start(...)`` on a receiver whose final name segment is
``trace``/``tracer`` (e.g. ``tracer.start``, ``self._tracer.start``), and
``.span(...)`` on a ``trace``-named receiver; ``.start_span(...)`` /
``.start_trace(...)`` anywhere.  Thread/server ``.start()`` calls never
match (their receivers are threads, timers, servers).  A deliberate manual
lifecycle needs ``# ktlint: allow[KT007] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, dotted_name, file_nodes, file_parents

ID = "KT007"
TITLE = "trace/span started without a `with` context manager"
HINT = ("write `with tracer.start(...) as trace:` / `with trace.span(...)"
        " as sp:`; for cross-thread phases use `trace.record(name, t0, t1)` "
        "(born closed); a deliberate manual lifecycle needs "
        "`# ktlint: allow[KT007] <reason>`")

#: method names that always indicate a span/trace opening, any receiver
#: (start_remote is the KT019 server-entry facade — its result is a live
#: trace and leaks exactly like a bare start)
ALWAYS = {"start_span", "start_trace", "start_remote"}
#: receiver-gated method names: only when the receiver's final segment is a
#: trace/tracer (so `thread.start()` / `server.start()` never match)
GATED = {"start", "span"}


def _tracer_receiver(recv: str) -> bool:
    seg = recv.split(".")[-1].strip("_").lower()
    return seg in ("trace", "tracer") or seg.endswith("tracer") \
        or seg.endswith("_trace")


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        parents = file_parents(f)
        for n in file_nodes(f):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                continue
            name = n.func.attr
            if name in ALWAYS:
                hit = True
            elif name in GATED:
                recv = dotted_name(n.func.value)
                hit = recv is not None and _tracer_receiver(recv)
            else:
                hit = False
            if not hit:
                continue
            if isinstance(parents.get(n), ast.withitem):
                continue  # `with tracer.start(...) [as x]:` — the blessed form
            out.append(Finding(
                ID, f.path, n.lineno,
                f"`{ast.unparse(n.func)}(...)` opens a trace/span outside a "
                "`with` — it leaks open on any exception path",
                hint=HINT,
            ))
    return out
