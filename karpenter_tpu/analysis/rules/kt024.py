"""KT024 — call-time knob env read outside the tuning registry.

ISSUE 19 moved the serving-path knobs (megabatch wait/slots, inline-delta
routing, brownout ladder, relax iterations, hierarchical threshold)
behind the live ``karpenter_tpu.tuning`` registry: the dispatcher
snapshots the registry atomically per flush/decision point, so a
controller update can never tear a megabatch flush or brownout
evaluation, and ``/tunez`` shows one authoritative value per knob.  A
serving-path function that reads the knob's env var directly at call
time re-opens the hole — it sees the construction-time env, not the
tuned value, and its read is invisible to the snapshot/trace surface.

Flagged: reads of a registry-owned env name (``tuning.knobs.KNOB_ENVS``)
via ``os.environ.get``/``os.environ[...]``/``os.getenv`` or an
``_env_*`` helper, inside a function in a serving-path file.

Exempt: construction scopes (module level, class bodies, ``__init__``/
``__new__``/``from_env``/``main``) — env values ARE the lattice
defaults there by design; the ``karpenter_tpu/tuning/`` package itself
(the registry's own from-env fallback is the one sanctioned read); and
dynamic names the rule cannot resolve (skipped, not flagged).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, file_nodes, file_parents

ID = "KT024"
TITLE = "call-time knob env read outside the tuning registry"
HINT = ("read the knob through karpenter_tpu.tuning "
        "(`global_knobs().get(name)` for one value, `.snapshot()` at a "
        "flush/decision point) — direct env reads see the boot-time "
        "value, not the tuned one, and tear-freedom only holds through "
        "the registry's atomic snapshot")

#: package-relative path fragments that make a file serving-path
SERVING_PARTS = ("karpenter_tpu/service/", "karpenter_tpu/admission/",
                 "karpenter_tpu/solver/")
#: the registry package — its from-env fallback is the sanctioned read
EXEMPT_PARTS = ("karpenter_tpu/tuning/",)
#: construction scopes: env defaults are read here by design
EXEMPT_SCOPES = ("__init__", "__new__", "from_env", "main")


def _knob_envs() -> frozenset:
    from ...tuning.knobs import KNOB_ENVS

    return KNOB_ENVS


def _in_scope(path: str) -> bool:
    if any(part in path for part in EXEMPT_PARTS):
        return False
    return any(part in path for part in SERVING_PARTS)


def _env_name(node: ast.AST) -> Optional[str]:
    """The knob env name this node reads, or None.

    Matches ``os.environ.get("KT_X", ...)``, ``os.environ["KT_X"]``,
    ``os.getenv("KT_X")``, and ``_env_*("KT_X", ...)`` helper calls
    (policy's ``_env_float``/``_env_int``/... family).  Only string
    literals resolve — a dynamic name is skipped, not flagged.
    """
    if isinstance(node, ast.Subscript):
        if (isinstance(node.value, ast.Attribute)
                and node.value.attr == "environ"
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            return node.slice.value
        return None
    if not (isinstance(node, ast.Call) and node.args):
        return None
    arg = node.args[0]
    if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
        return None
    func = node.func
    if isinstance(func, ast.Attribute):
        # os.environ.get("KT_X") / os.getenv("KT_X") / mod._env_float(...)
        if func.attr == "get" and isinstance(func.value, ast.Attribute) \
                and func.value.attr == "environ":
            return arg.value
        if func.attr == "getenv" or func.attr.startswith("_env"):
            return arg.value
        return None
    if isinstance(func, ast.Name):
        if func.id == "getenv" or func.id.startswith("_env"):
            return arg.value
    return None


def _construction_scope(node: ast.AST, parents) -> bool:
    """True when the read executes at construction time: module level,
    a class body, or the nearest enclosing function is an exempt scope."""
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name in EXEMPT_SCOPES
        cur = parents.get(cur)
    return True  # module level / class body


def check(files) -> List[Finding]:
    knob_envs = _knob_envs()
    findings: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        parents = file_parents(f)
        for n in file_nodes(f):
            env = _env_name(n)
            if env is None or env not in knob_envs:
                continue
            if _construction_scope(n, parents):
                continue
            findings.append(Finding(
                ID, f.path, n.lineno,
                f"serving-path call-time read of knob env `{env}` "
                "bypasses the tuning registry — it sees the boot-time "
                "value, not the tuned one, and escapes the atomic "
                "snapshot that keeps flushes/brownout decisions untorn",
                hint=HINT,
            ))
    return findings
