"""KT020 — per-block dispatch loops / unpacked feasibility on the
hierarchical path.

The million-pod decomposition's perf contract (ISSUE 16) has two
structural invariants in ``solver/hierarchy.py``:

1. **One dispatch per block wave.**  Every block solves as a SLOT of one
   vmapped megabatch dispatch (``solve_many_prepared``); a ``solve`` /
   ``prepare`` / ``wave`` / ``delta_solve`` call inside a ``for``/``while``
   (or a comprehension — the same N dispatches spelled on one line) pays a
   device round trip PER BLOCK, the exact shape KT010 polices on
   controller paths.  The price-ascent loop is GENUINELY sequential (each
   dual update needs the previous wave's usage) and carries
   ``# ktlint: allow[KT020] <reason>`` — the exemption stays visible in
   the diff, not implicit in the rule.

2. **Packed feasibility.**  The hot loop scores int8 feasibility with
   bf16 prices (``pack_feasibility`` / ``pack_scores`` — ~4x fewer HBM
   bytes than the float32 layout the relax rung materializes).
   Constructing a float32 feasibility tensor on this path silently
   quadruples the hot loop's memory traffic.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, _is_suppressed, dotted_name, file_nodes, file_parents

ID = "KT020"
TITLE = "per-block dispatch loop / unpacked feasibility on the hierarchical path"
HINT = ("batch the blocks as slots of ONE solve_many_prepared dispatch and "
        "keep feasibility packed (pack_feasibility -> int8, pack_scores -> "
        "bf16); when waves are sequentially dependent (the price-ascent "
        "loop), annotate with `# ktlint: allow[KT020] <reason>`")

#: callee names whose per-iteration invocation is a device round trip on
#: the hierarchical path (``wave`` is hierarchy.py's dispatch wrapper)
SOLVE_CALLS = {"solve", "prepare", "solve_many_prepared", "wave",
               "delta_solve", "_solve_once"}
#: scoped file (path substring — the decomposition lives in one module)
SCOPE = ("solver/hierarchy.py",)

#: dtype spellings that mark an UNPACKED feasibility tensor
_F32_NAMES = {"float32"}
#: numpy/jnp constructors whose ``dtype=float32`` builds the tensor wide
_CTORS = {"zeros", "ones", "empty", "full", "asarray", "array"}


def _in_scope(path: str) -> bool:
    return any(s in path for s in SCOPE)


def _callee(call: ast.Call):
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


#: comprehensions are loops too — ``[wave([e]) for e in entries]`` is the
#: for-loop-of-dispatch spelled on one line
_LOOPS = (ast.For, ast.While,
          ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _enclosing_loop(node: ast.AST, parents):
    """The innermost loop (for/while/comprehension) containing ``node``
    (lambdas/defs between the call and the loop break containment — the
    loop body is then a deferred callable, not a per-iteration
    dispatch)."""
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return None
        if isinstance(cur, _LOOPS):
            return cur
    return None


def _is_f32(node: Optional[ast.AST]) -> bool:
    """``np.float32`` / ``jnp.float32`` / ``"float32"`` / bare float32."""
    if node is None:
        return False
    if isinstance(node, ast.Constant):
        return node.value in _F32_NAMES
    name = dotted_name(node)
    return bool(name) and name.split(".")[-1] in _F32_NAMES


def _mentions_feas(node: ast.AST) -> bool:
    """Any Name/Attribute/callee in the subtree naming feasibility."""
    for n in ast.walk(node):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        if ident and "feas" in ident.lower():
            return True
    return False


def _f32_construction(call: ast.Call) -> bool:
    """Does this call BUILD a float32 array?  Either ``x.astype(float32)``
    or a numpy/jnp constructor with ``dtype=float32``."""
    name = _callee(call)
    if name == "astype":
        return any(_is_f32(a) for a in call.args) or any(
            kw.arg == "dtype" and _is_f32(kw.value) for kw in call.keywords)
    if name in _CTORS:
        return any(kw.arg == "dtype" and _is_f32(kw.value)
                   for kw in call.keywords)
    return False


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        parents = file_parents(f)
        for n in file_nodes(f):
            if not isinstance(n, ast.Call):
                continue
            name = _callee(n)
            # ---- (1) per-block dispatch inside a Python loop -----------
            if name in SOLVE_CALLS:
                loop = _enclosing_loop(n, parents)
                if loop is None:
                    continue
                # honor a suppression on the loop header (or the comment
                # block above it) in addition to the call line, which
                # analyze_files checks — probed with a synthetic finding
                # at the loop line so the shared suppression walk stays
                # the single source of truth
                if _is_suppressed(f, Finding(ID, f.path, loop.lineno, "")):
                    continue
                where = dotted_name(n.func) or name
                out.append(Finding(
                    ID, f.path, n.lineno,
                    f"`{where}(...)` runs once per iteration of the "
                    f"enclosing loop (line {loop.lineno}) — a device "
                    "dispatch per block where one block-wave slot batch "
                    "serves them all",
                    hint=HINT,
                ))
                continue
            # ---- (2) unpacked float32 feasibility tensor ---------------
            # feasibility is named either in the expression itself
            # (``_host_feasibility(st).astype(np.float32)``) or on the
            # assignment target (``feas = np.zeros(..., dtype=float32)``)
            feasy = _mentions_feas(n)
            if not feasy:
                parent = parents.get(n)
                if isinstance(parent, ast.Assign):
                    feasy = any(_mentions_feas(t) for t in parent.targets)
                elif isinstance(parent, ast.AnnAssign):
                    feasy = _mentions_feas(parent.target)
            if _f32_construction(n) and feasy:
                out.append(Finding(
                    ID, f.path, n.lineno,
                    "float32 feasibility tensor on the hierarchical path "
                    "— the packed hot loop scores int8 feasibility "
                    "(pack_feasibility), 4x fewer HBM bytes",
                    hint=HINT,
                ))
    return out
