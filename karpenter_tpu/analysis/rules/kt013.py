"""KT013 — interprocedural fence reachability from the serving entry points.

KT001 checks sync discipline *per function* in the two hot-path files; this
pass upgrades the invariant to what the pipeline actually needs: **every
call path from a serving entry point that reaches a blocking host<->device
sync must pass through a ``# ktlint: fence``-annotated function.**  A sync
two facades away from ``SolverService.Solve`` re-serializes the pipeline
exactly as hard as one written inline — sync-point drift is a whole-program
property (the PR 6/7 review rounds caught exactly this class by hand).

Mechanism: walk the project call graph (``analysis/callgraph.py``) from
:data:`ENTRY_POINTS`.  Fence-annotated functions are *absorbing* — the
walk does not descend into them (their body IS the sanctioned sync point,
and everything they call executes inside the fence's latency budget by
declaration).  Constructors (``__init__``) are skipped: serving-path
construction is lazy one-time setup, not steady-state.  Any visited
function containing a blocking sync is a finding, anchored at the sync
line, with the full offending call chain in the message.

Sync constructs: ``.block_until_ready()`` / ``jax.block_until_ready()`` /
``jax.device_get()`` always; ``.item()`` / ``float()`` / ``np.asarray()``
only on device-tainted values (KT001's taint, extended so a call to a
module-level jitted function taints — ``np.asarray(kernel(*args))`` is a
D2H read).  Host-side numpy therefore stays quiet, exactly like KT001.

An entry point that no longer resolves is itself a finding: a renamed
entry would otherwise silently shrink the audited surface to nothing.
Unresolvable *calls* (dynamic dispatch, callbacks) contribute no edge —
graceful degradation, pinned by tests/test_lint.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..callgraph import Project, build_project
from ..ktlint import Finding

ID = "KT013"
TITLE = "blocking sync reachable from a serving entry point without a fence"
WHOLE_PROGRAM = True
HINT = ("route the sync through a `# ktlint: fence <why>`-annotated "
        "function (the fence set lives in the source, next to the code it "
        "exempts), or break the call edge; allow[KT013] on the sync line "
        "only with a reason that names why this path tolerates the stall")

#: the serving surface: (path suffix, qualname).  These are the functions
#: whose latency the system promises to bound — RPC entry, the pipeline
#: dispatcher (covers _flush/_dispatch_single/_finalize/_finalize_mega),
#: the scheduler's dispatch entries, and the controller ticks the operator
#: loop drives.
ENTRY_POINTS: Tuple[Tuple[str, str], ...] = (
    ("service/server.py", "SolverService.Solve"),
    ("service/server.py", "SolvePipeline.solve"),
    ("service/server.py", "SolvePipeline._loop"),
    ("solver/scheduler.py", "BatchScheduler.solve"),
    ("solver/scheduler.py", "BatchScheduler.submit"),
    ("solver/scheduler.py", "BatchScheduler.submit_many"),
    ("solver/scheduler.py", "BatchScheduler.solve_delta"),
    ("controllers/provisioning.py", "ProvisioningController.reconcile"),
    ("controllers/deprovisioning.py", "DeprovisioningController.reconcile"),
    ("controllers/garbagecollect.py", "GarbageCollectController.reconcile"),
    ("controllers/interruption.py", "InterruptionController.reconcile"),
    ("controllers/termination.py", "TerminationController.reconcile"),
    ("operator.py", "Operator.tick"),
)


def _reachable(project: Project, roots: List[str]) -> Dict[str, List[str]]:
    """fid -> call chain (entry ... fid) for every function reachable from
    ``roots`` without passing through a fence.  BFS, so the recorded chain
    is a shortest one; cycles terminate via the visited set."""
    chains: Dict[str, List[str]] = {}
    queue: List[str] = []
    for fid in roots:
        if fid not in chains:
            chains[fid] = [project.funcs[fid].summary.qual]
            queue.append(fid)
    while queue:
        fid = queue.pop(0)
        node = project.funcs[fid]
        for _line, callee, _closure in node.edges:
            if callee in chains:
                continue
            target = project.funcs.get(callee)
            if target is None:
                continue
            if target.summary.fence:
                continue  # absorbing: the fence owns everything below it
            if target.summary.qual.split(".")[-1] == "__init__":
                continue  # lazy construction is not the steady state
            chains[callee] = chains[fid] + [target.summary.qual]
            queue.append(callee)
    return chains


def check(files, project: Optional[Project] = None) -> List[Finding]:
    project = project if project is not None else build_project(files)
    out: List[Finding] = []
    roots: List[str] = []
    by_suffix_present = {s.path for s in project.summaries}
    for suffix, qual in ENTRY_POINTS:
        if not any(p.endswith(suffix) for p in by_suffix_present):
            continue  # file not in this run (single-file CLI, fixtures)
        fid = project.find_function(suffix, qual)
        if fid is None:
            # staleness guard: fire only when the declaring CLASS is there
            # but NONE of its listed entries resolve (a rename under the
            # rule's feet).  A file that lacks the class entirely — or a
            # fixture that carries only one of a class's entries — stays
            # quiet; tests/test_lint.py separately pins that every entry
            # resolves against the real package, so neither a class-level
            # rename nor a partial one can silently shrink the audited
            # surface.
            cls = qual.split(".")[0] if "." in qual else None
            owner = None
            for s in project.summaries:
                if s.path.endswith(suffix) and cls in s.classes:
                    owner = s
                    break
            if owner is None:
                continue
            siblings_resolve = any(
                project.find_function(sfx, q) is not None
                for sfx, q in ENTRY_POINTS
                if sfx == suffix and q.split(".")[0] == cls)
            if siblings_resolve:
                continue
            out.append(Finding(
                ID, owner.path, owner.classes[cls].lineno,
                f"serving entry point `{qual}` not found in {suffix} — "
                "KT013's audited surface went stale (renamed or moved "
                "entry); update ENTRY_POINTS in analysis/rules/kt013.py",
                hint="the entry-point list must track the serving surface",
            ))
            continue
        if not project.funcs[fid].summary.fence:
            roots.append(fid)
    seen: set = set()
    for fid, chain in sorted(_reachable(project, roots).items()):
        node = project.funcs[fid]
        for lineno, kind in node.summary.syncs:
            key = (node.path, lineno)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                ID, node.path, lineno,
                f"{kind} reachable from serving entry `{chain[0]}` with no "
                "fence on the path — the sync re-serializes the pipeline "
                "for every request behind it; call chain: "
                + " -> ".join(chain),
                hint=HINT,
            ))
    return out
