"""KT001 — implicit host↔device sync in solver hot paths.

JAX dispatch is asynchronous; the pipelined solve path (PR 1) depends on the
host staying free between dispatch and fence so batch N+1 tensorizes while
batch N executes.  A stray ``.block_until_ready()`` / ``float()`` / ``.item()``
/ ``np.asarray()`` on a device value silently re-serializes the pipeline —
sync-point drift, the round-5 advisor's third bug class.  Sync constructs in
the hot-path files are therefore only allowed inside the *fence allowlist*:
the functions whose entire job is to fence (``TpuSolver.solve``,
``PendingTpuSolve.result``, extraction/retry epilogues), or any function
annotated ``# ktlint: fence <why>`` on its ``def`` line.

Device values are tracked with a light intra-function taint: names bound from
``run(...)`` calls (the prepared device program) or from ``jnp.*``
expressions, plus parameters named ``carry``/``ys`` (the solver's device
carry convention).  Host-side numpy (``np.asarray(st.counts)``) stays
untainted, so the rule does not cry wolf on tensorize code.

The fence set lives IN THE SOURCE, not here: each allowed sync point carries
``# ktlint: fence <why>`` on (or directly above) its ``def`` line, so the
exemption and its reason sit next to the code they exempt and cannot go
stale when a method is renamed or split.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, SourceFile, dotted_name, file_functions

ID = "KT001"
TITLE = "implicit host↔device sync outside the fence set"
HINT = ("move the sync into a fence function, or annotate the def with "
        "`# ktlint: fence <why>` if its body IS the sync point")

#: files whose functions are solver hot paths (package-relative suffixes)
HOT_SUFFIXES = ("solver/tpu.py", "solver/scheduler.py")

#: parameter names treated as device-resident by convention
TAINT_PARAMS = {"carry", "ys"}


def _hot_suffix(path: str):
    for s in HOT_SUFFIXES:
        if path.endswith(s):
            return s
    return None


def _expr_tainted(node: ast.AST, tainted: set) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
        if isinstance(n, ast.Attribute):
            d = dotted_name(n)
            if d is not None and d.split(".", 1)[0] == "jnp":
                return True
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "run"):
            return True
    return False


def _collect_taint(fn: ast.AST) -> set:
    tainted = set()
    for arg in getattr(fn, "args", None).args if hasattr(fn, "args") else ():
        if arg.arg in TAINT_PARAMS:
            tainted.add(arg.arg)
    changed = True
    while changed:
        changed = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and _expr_tainted(n.value, tainted):
                for t in n.targets:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name) and nm.id not in tainted:
                            tainted.add(nm.id)
                            changed = True
    return tainted


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if _hot_suffix(f.path) is None:
            continue
        for qual, fn, nested in file_functions(f):
            if nested:
                continue  # closures scan with their enclosing method
            if fn.lineno in f.fence_lines:
                continue
            out.extend(_scan(fn, f))
    return out


def _scan(fn: ast.AST, f: SourceFile) -> List[Finding]:
    tainted = _collect_taint(fn)
    out: List[Finding] = []

    def finding(node: ast.AST, what: str) -> None:
        out.append(Finding(
            ID, f.path, node.lineno,
            f"{what} is an implicit host↔device sync in a solver hot path "
            "outside the fence allowlist", hint=HINT,
        ))

    for n in ast.walk(fn):
        if not isinstance(n, ast.Call):
            continue
        func = n.func
        if isinstance(func, ast.Attribute):
            if func.attr == "block_until_ready":
                finding(n, "`.block_until_ready()`")
            elif func.attr == "item" and _expr_tainted(func.value, tainted):
                finding(n, "`.item()` on a device value")
            elif func.attr == "asarray":
                root = dotted_name(func.value)
                if (root in ("np", "numpy") and n.args
                        and _expr_tainted(n.args[0], tainted)):
                    finding(n, "`np.asarray()` on a device value")
        elif (isinstance(func, ast.Name) and func.id == "float"
              and n.args and _expr_tainted(n.args[0], tainted)):
            finding(n, "`float()` on a device value")
    return out
