"""KT009 — RPC-path rejections must record a shed metric.

Admission control's whole value is *observable* load shedding: a request
refused under overload that never lands in
``karpenter_admission_shed_total{class,reason}`` is a silent availability
loss — dashboards show healthy traffic while callers see
RESOURCE_EXHAUSTED.  This rule pins the accounting contract statically:
in the RPC-path packages (``karpenter_tpu/admission/``,
``karpenter_tpu/service/``), every function that raises OR constructs a
:class:`SolveShedError` / :class:`SolveDeadlineError` (construction
covers the dispatcher resolving a future with the error instead of
raising) must, in the same function, increment the shed counter —
``<registry>.counter(ADMISSION_SHED).inc(...)`` (or the literal metric
name) or delegate to an ``AdmissionControl`` accounting helper
(``_count_shed`` / ``_shed``).

A site that genuinely must not count (e.g. the client re-mapping a shed
the SERVING side already counted) carries
``# ktlint: allow[KT009] <reason>`` — the exemption stays visible in the
diff instead of implicit in the rule.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, dotted_name, file_nodes, file_parents

ID = "KT009"
TITLE = "RPC-path rejection without a shed-metric increment"
HINT = ("increment karpenter_admission_shed_total{class,reason} in the "
        "same function — `registry.counter(ADMISSION_SHED).inc({...})` or "
        "the AdmissionControl._count_shed helper; a deliberate no-count "
        "site needs `# ktlint: allow[KT009] <reason>`")

#: exception names whose raise/construction marks an RPC-path rejection
SHED_ERRORS = {"SolveShedError", "SolveDeadlineError"}
#: metric identifiers accepted as "the shed counter"
SHED_METRICS = {"ADMISSION_SHED", "karpenter_admission_shed_total"}
#: accounting helpers that inc the counter on the caller's behalf
SHED_HELPERS = {"_count_shed", "_shed"}
#: scoped packages (path substrings)
SCOPE = ("/admission/", "/service/")


def _in_scope(path: str) -> bool:
    return any(s in path for s in SCOPE)


def _is_shed_ctor(call: ast.Call) -> bool:
    name = None
    if isinstance(call.func, ast.Name):
        name = call.func.id
    elif isinstance(call.func, ast.Attribute):
        name = call.func.attr
    return name in SHED_ERRORS


def _counts_shed(func: ast.AST) -> bool:
    """Does this function inc the shed counter (directly or via helper)?"""
    for n in ast.walk(func):
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute):
            if n.func.attr in SHED_HELPERS:
                return True
            if n.func.attr == "inc":
                # `<expr>.counter(ADMISSION_SHED).inc(...)` — receiver is a
                # counter(...) call over one of the accepted identifiers
                recv = n.func.value
                if (isinstance(recv, ast.Call)
                        and isinstance(recv.func, ast.Attribute)
                        and recv.func.attr == "counter" and recv.args):
                    arg = recv.args[0]
                    if (isinstance(arg, ast.Name)
                            and arg.id in SHED_METRICS):
                        return True
                    if (isinstance(arg, ast.Constant)
                            and arg.value in SHED_METRICS):
                        return True
        elif isinstance(n.func, ast.Name) and n.func.id in SHED_HELPERS:
            return True
    return False


def _enclosing_function(node: ast.AST, parents):
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
    return None


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        parents = file_parents(f)
        for n in file_nodes(f):
            if not (isinstance(n, ast.Call) and _is_shed_ctor(n)):
                continue
            func = _enclosing_function(n, parents)
            if func is None:
                continue  # module-level construction: not an RPC path
            if _counts_shed(func):
                continue
            where = dotted_name(n.func) or "?"
            out.append(Finding(
                ID, f.path, n.lineno,
                f"`{where}(...)` rejects an RPC here but "
                f"`{func.name}` never increments "
                "karpenter_admission_shed_total — the shed is invisible "
                "to dashboards and the overload SLO",
                hint=HINT,
            ))
    return out
