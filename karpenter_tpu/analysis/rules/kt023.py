"""KT023 — metric family constructed on a Registry but missing from the
metrics INVENTORY.

``metrics.INVENTORY`` is the single source of truth for the metric
surface: exposition emits ``# HELP``/``# TYPE`` from it, ``docs/METRICS.md``
is generated from it (``karpenter-tpu metrics-doc --check`` gates drift),
and the zero-init suite (tests/test_metrics_init.py) walks it.  A family
constructed via ``registry.counter(...)`` / ``.gauge(...)`` /
``.histogram(...)`` whose name never made it into the INVENTORY is
invisible to all three — it scrapes without HELP text, misses the docs,
and silently escapes the KT003 zero-init convention's runtime pin.  The
ISSUE-18 SLO/time-series families tripled the construction sites, which
is exactly when one slips through.

Resolution is conservative: the argument must be a ``karpenter_``-prefixed
string literal, a Name that resolves to one (module-level assignment in
the scanned files, or a constant on ``karpenter_tpu.metrics``), or an
``<mod>.CONST`` attribute resolving on the metrics module.  A dynamic
name (loop variable over the INVENTORY itself, helper parameters) cannot
be checked statically and is skipped, not flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..ktlint import Finding, file_nodes

ID = "KT023"
TITLE = "metric family missing from the metrics INVENTORY"
HINT = ("add the family to karpenter_tpu/metrics.py INVENTORY "
        "(name -> (type, labels, help)) and regenerate docs/METRICS.md "
        "with `karpenter-tpu metrics-doc` — exposition HELP text, the "
        "generated docs, and the zero-init suite all walk the INVENTORY")

_CTORS = ("counter", "gauge", "histogram")


def _inventory() -> dict:
    from ... import metrics

    return metrics.INVENTORY


def _module_constants() -> Dict[str, str]:
    """Every ``karpenter_``-string constant on the real metrics module —
    the names ``from ..metrics import X`` / ``metrics.X`` resolve to."""
    from ... import metrics

    out: Dict[str, str] = {}
    for attr in dir(metrics):
        if attr.startswith("_"):
            continue
        val = getattr(metrics, attr, None)
        if isinstance(val, str) and val.startswith("karpenter_"):
            out[attr] = val
    return out


def _resolve(arg: ast.AST, assigns: Dict[str, str],
             mod_consts: Dict[str, str]) -> Optional[str]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value if arg.value.startswith("karpenter_") else None
    if isinstance(arg, ast.Name):
        return assigns.get(arg.id) or mod_consts.get(arg.id)
    if isinstance(arg, ast.Attribute):
        # metrics.X / M.X — the attribute name is the constant's name
        return mod_consts.get(arg.attr)
    return None


def check(files) -> List[Finding]:
    inventory = _inventory()
    mod_consts = _module_constants()
    findings: List[Finding] = []
    # module-level NAME = "karpenter_..." assigns across the scanned files
    # (metrics.py itself plus any module declaring a local family name)
    assigns: Dict[str, str] = {}
    for f in files:
        for n in file_nodes(f):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Constant) \
                    and isinstance(n.value.value, str) \
                    and n.value.value.startswith("karpenter_"):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        assigns[t.id] = n.value.value
    for f in files:
        for n in file_nodes(f):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _CTORS and n.args):
                continue
            name = _resolve(n.args[0], assigns, mod_consts)
            if name is None or name in inventory:
                continue
            findings.append(Finding(
                ID, f.path, n.lineno,
                f"metric family `{name}` is constructed on a Registry "
                "here but missing from metrics.INVENTORY — it will "
                "scrape without HELP/TYPE, miss docs/METRICS.md, and "
                "escape the zero-init suite",
                hint=HINT,
            ))
    return findings
