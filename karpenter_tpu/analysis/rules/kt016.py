"""KT016 — fault-plane facade discipline + counted recovery outcomes.

ISSUE 12 threads a seeded fault-injection plane (``karpenter_tpu/faults/``)
through the serving stack's choke points, and makes one observability
promise: every recovery from a faultable operation is COUNTED
(``karpenter_faults_recovered_total{site,outcome}``), injected or organic.
Two bug classes follow, both pinned here:

1. **Raw nondeterminism / fault probes in serving code.**  Serving-path
   code (``solver/``, ``service/``) may consult faults only via the
   ``FaultPlane`` facade: any stdlib ``random`` import/use outside
   ``karpenter_tpu/faults/`` (the KT011 "sanctioned home" precedent —
   jitter and seeded draws belong to the facade so chaos runs replay), and
   any ``os.environ`` probe of a ``KT_FAULT``-prefixed key (a component
   that reads the schedule directly bypasses the plane's deterministic
   site counters and metric funnel).  ``numpy``'s seeded generators are
   out of scope — they are numeric tooling, not fault randomness.

2. **Uncounted recovery.**  A function in the serving scope whose ``try``
   body contains a FAULTABLE operation (a plane ``fire``/``mangle`` call,
   a transport stub call, a delta-step apply, a spool pack/unpack/write)
   and whose ``except`` handler RECOVERS (does not end in a bare
   ``raise``) must land a recovery outcome in
   ``karpenter_faults_recovered_total`` somewhere in the same function —
   ``faults.count_recovery(...)`` or a direct
   ``counter(FAULTS_RECOVERED).inc(...)``.  A recovery that vanishes from
   the partition turns every chaos run's scoreboard into fiction: the
   harness asserts "N faults injected, N recoveries observed", and an
   uncounted path is exactly where a silent divergence hides.

Deliberate exceptions carry ``# ktlint: allow[KT016] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, dotted_name, file_nodes, file_parents

ID = "KT016"
TITLE = "fault-plane discipline (raw random / uncounted recovery)"
HINT = ("route randomness through karpenter_tpu/faults (faults.jitter(), "
        "the plane's seeded rng) and fault probes through faults.plane(); "
        "recovering excepts on faultable paths must call "
        "faults.count_recovery(registry, site, outcome) (or inc "
        "FAULTS_RECOVERED) in the same function; a deliberate exception "
        "needs `# ktlint: allow[KT016] <reason>`")

#: serving scope (path substrings) — the dirs the plane threads through
SCOPE = ("/solver/", "/service/")
#: the one sanctioned home for serving-path randomness + fault probes
HOME = "/faults/"
#: leaf callee names that ARE the faultable operations (part 2's trigger):
#: plane choke points, the transport stub, the delta-step apply, and the
#: snapshot spool surface
FAULTABLE_CALLS = {"fire", "mangle", "_apply_delta_step", "_solve",
                   "solve_raw", "_rpc", "pack", "unpack", "write_atomic"}
#: identifiers accepted as "the recovery-outcome counter"
RECOVERY_METRICS = {"FAULTS_RECOVERED", "karpenter_faults_recovered_total"}
RECOVERY_HELPERS = {"count_recovery"}


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(s in p for s in SCOPE) and HOME not in p


def _enclosing_function(node: ast.AST, parents):
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
    return None


def _counts_recovery(func: ast.AST) -> bool:
    """Does this function land a recovery outcome (helper or direct
    counter inc), nested defs included?"""
    for n in ast.walk(func):
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute):
            if n.func.attr in RECOVERY_HELPERS:
                return True
            if n.func.attr == "inc":
                recv = n.func.value
                if (isinstance(recv, ast.Call)
                        and isinstance(recv.func, ast.Attribute)
                        and recv.func.attr == "counter" and recv.args):
                    arg = recv.args[0]
                    if isinstance(arg, ast.Name) \
                            and arg.id in RECOVERY_METRICS:
                        return True
                    if (isinstance(arg, ast.Constant)
                            and arg.value in RECOVERY_METRICS):
                        return True
        elif isinstance(n.func, ast.Name) and n.func.id in RECOVERY_HELPERS:
            return True
    return False


def _leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _has_faultable_call(body) -> bool:
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call) and _leaf(n) in FAULTABLE_CALLS:
                return True
    return False


def _handler_recovers(handler: ast.ExceptHandler) -> bool:
    """A handler that does NOT end in a bare ``raise`` recovers (it keeps
    the process on some path) — re-raise-with-bookkeeping still counts as
    recovery handling for part 2, because the bookkeeping is exactly what
    must include the recovery counter when it swallows.  Only the pure
    re-raise tail (``raise`` as the LAST statement) is exempt here when
    the body is just cleanup+raise — conservatively: exempt iff the final
    statement is a bare ``raise`` AND the handler performs no other calls
    besides logging?  Too clever; keep the simple contract: a handler
    whose last statement is a bare ``raise`` is a re-raise (the error
    still surfaces typed), anything else recovers."""
    if not handler.body:
        return False
    last = handler.body[-1]
    return not (isinstance(last, ast.Raise) and last.exc is None)


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        path = f.path.replace("\\", "/")
        if HOME in path:
            continue
        in_scope = _in_scope(f.path)
        parents = file_parents(f)
        for n in file_nodes(f):
            # ---- part 1: raw random / fault-env probes ------------------
            if in_scope and isinstance(n, ast.Import):
                for alias in n.names:
                    if alias.name == "random" or \
                            alias.name.startswith("random."):
                        out.append(Finding(
                            ID, f.path, n.lineno,
                            "stdlib `random` imported in serving-path "
                            "code — nondeterminism belongs to the "
                            "karpenter_tpu/faults facade (seeded, so "
                            "chaos runs replay)",
                            hint=HINT,
                        ))
            elif in_scope and isinstance(n, ast.ImportFrom):
                if n.module == "random":
                    out.append(Finding(
                        ID, f.path, n.lineno,
                        "`from random import ...` in serving-path code — "
                        "use the faults facade (faults.jitter(), the "
                        "plane's seeded rng)",
                        hint=HINT,
                    ))
            elif in_scope and isinstance(n, ast.Call):
                name = dotted_name(n.func) or ""
                if name.startswith("random."):
                    out.append(Finding(
                        ID, f.path, n.lineno,
                        f"`{name}(...)` in serving-path code — raw "
                        "randomness breaks seeded-chaos replay; use the "
                        "faults facade",
                        hint=HINT,
                    ))
                elif name in ("os.environ.get", "os.getenv") and n.args:
                    arg = n.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, str)
                            and arg.value.startswith("KT_FAULT")):
                        out.append(Finding(
                            ID, f.path, n.lineno,
                            f"raw {arg.value} env probe in serving-path "
                            "code — consult faults.plane() so the "
                            "schedule's site counters and metric funnel "
                            "stay deterministic",
                            hint=HINT,
                        ))
            # ---- part 2: uncounted recovery -----------------------------
            if not in_scope or not isinstance(n, ast.Try):
                continue
            if not _has_faultable_call(n.body):
                continue
            recovering = [h for h in n.handlers if _handler_recovers(h)]
            if not recovering:
                continue
            func = _enclosing_function(n, parents)
            if func is None or _counts_recovery(func):
                continue
            out.append(Finding(
                ID, f.path, recovering[0].lineno,
                f"`{func.name}` recovers from a faultable operation but "
                "never lands an outcome in karpenter_faults_recovered_"
                "total — an uncounted recovery is where silent "
                "divergence hides (docs/RESILIENCE.md)",
                hint=HINT,
            ))
    return out
