"""KT018 — whole-batch readback of a mesh-sharded megabatch carry.

ISSUE 14 made megabatch fences PER-HOST: on a multi-process mesh each
serving process reads back only its ``jax.process_index()``-addressable
slot shards (``solver/tpu.read_slot_rows`` — the sanctioned accessor) and
demuxes exactly the slots it owns.  The bug class this rule pins is the
one that round removed: a ``.results()``/extraction path calling
``np.asarray`` / ``np.array`` / ``jax.device_get`` directly on the
slot-stacked carry — on a multi-host mesh that is a WHOLE-batch D2H, so
every host pays DCN latency (and memory) for slots it does not own, and
on arrays with non-addressable shards it simply crashes.

Mechanics (a lexical convention rule, the KT002/KT016 precedent): in the
serving-path files, any call to the readback functions whose argument
expression references the stacked-carry naming convention —
``carry_b`` / ``ys_b`` (names, attributes, or subscripts of either) — is
a finding, except inside ``read_slot_rows`` itself (the accessor owns
its raw reads, annotated ``allow[KT018]`` line-by-line anyway).  The
single-solve ``carry`` (no ``_b``) is out of scope: its result is
genuinely global.

Deliberate exceptions carry ``# ktlint: allow[KT018] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, dotted_name

ID = "KT018"
TITLE = "whole-batch readback of a mesh-sharded megabatch carry"
HINT = ("route stacked-carry reads through solver/tpu.read_slot_rows "
        "(the addressable-shard accessor): a raw np.asarray/device_get "
        "on carry_b/ys_b reads the WHOLE batch — every host pays DCN for "
        "slots it does not own; a deliberate exception needs "
        "`# ktlint: allow[KT018] <reason>`")

#: serving-path scope (the KT011 file set: where megabatch carries live)
SCOPE_FILES = (
    "solver/tpu.py", "solver/scheduler.py", "solver/consolidation.py",
    "service/server.py", "batcher.py",
)
#: the readback callables
READBACKS = {"asarray", "array", "device_get"}
#: the slot-stacked carry naming convention (dim 0 = request slot)
STACKED_NAMES = {"carry_b", "ys_b"}
#: the sanctioned accessor — its own raw reads are the implementation
ACCESSOR = "read_slot_rows"


def _in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return any(p.endswith(s) for s in SCOPE_FILES)


def _leaf(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _mentions_stacked(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in STACKED_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in STACKED_NAMES:
            return True
    return False


def _walk_outside_accessor(tree: ast.AST):
    """Yield Call nodes, skipping the body of the accessor function."""
    stack = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == ACCESSOR:
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        for call in _walk_outside_accessor(f.tree):
            name = _leaf(call)
            if name not in READBACKS:
                continue
            if not any(_mentions_stacked(a) for a in call.args):
                continue
            where = dotted_name(call.func) or name
            out.append(Finding(
                ID, f.path, call.lineno,
                f"`{where}(...)` reads a slot-stacked megabatch carry "
                "(carry_b/ys_b) whole — on a multi-host mesh that pays "
                "DCN for every foreign slot (or crashes on "
                "non-addressable shards); use the addressable-shard "
                "accessor read_slot_rows",
                hint=HINT,
            ))
    return out
