"""KT008 — jitted callable off the registered bucket grid.

The serving path's no-compile contract (compile-behind + AOT bucket
precompile) only holds while every XLA program it can reach is
*precompilable*: module-level jit wrappers whose compile signatures are
drawn from the rung-bucketed dims ``solve_dims`` produces.  Two ways code
silently breaks that contract, both caught here:

1. **Per-call jit wrappers** — ``jax.jit(fn)`` (or ``partial(jax.jit, ...)``)
   applied *inside a function body* builds a FRESH wrapper, with a fresh
   compile cache, on every call: the program recompiles per solve no matter
   how warm the process is.  This was live in ``TpuSolver.prepare``'s
   multi-process branch until this rule's round (hoisted to the module-level
   ``feasibility_jit``).
2. **Off-grid static shape args** — ``static_argnames`` entries are compile-
   signature axes; a name outside the registered bucket-grid vocabulary
   (:data:`BUCKET_GRID_STATICS` — the ``solve_dims`` dims keys plus the
   kernel statics) means a program keyed on shapes no rung ladder bounds,
   so warmup can never cover it and the serving path eats the compile.

Scope: the serving-path packages (``solver/``, ``ops/``, ``parallel/``,
``service/``).  Suppress genuinely-off-path uses with
``# ktlint: allow[KT008] <reason>``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..ktlint import Finding, file_functions, file_nodes

ID = "KT008"
TITLE = "jitted callable off the registered bucket grid"
HINT = ("hoist the jit to module level (a per-call wrapper owns a fresh "
        "compile cache = silent recompile every solve) and draw "
        "static_argnames only from the bucket-grid vocabulary "
        "(solve_dims keys + kernel statics), so every reachable program "
        "sits on a precompilable rung ladder")

#: serving-path file prefixes (package-relative paths)
SERVING_DIRS = (
    "karpenter_tpu/solver/",
    "karpenter_tpu/ops/",
    "karpenter_tpu/parallel/",
    "karpenter_tpu/service/",
)

#: the registered bucket grid: exactly the dims keys ``solver/tpu.py
#: solve_dims`` emits (the single source of the rung-bucketing math) plus
#: the vmapped kernel's vocab-position statics.  A static shape arg outside
#: this set keys compiles on shapes no rung ladder bounds —
#: tests/test_lint.py pins this list against solve_dims at runtime.
BUCKET_GRID_STATICS = frozenset({
    "G", "C", "NR", "NE_pad", "S", "P", "D", "R", "Z", "K", "W",
    "track", "a", "b",
    "zone_key", "ct_key",
    # the relax rung's iteration budget (solver/relax.py): bucketed onto
    # RELAX_ITER_RUNGS, so the program ladder stays log-bounded — KT014
    # audits the rung ladder and the key-tail single-sourcing
    "relax_iters",
})


def _is_jit_name(node: ast.AST) -> bool:
    """`jit` / `jax.jit` (the bare callable, not an application)."""
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    return isinstance(node, ast.Attribute) and node.attr == "jit"


def _jit_application(node: ast.AST) -> Optional[ast.Call]:
    """The Call that APPLIES jit to a function, if ``node`` is one:
    ``jax.jit(fn, ...)``, ``partial(jax.jit, ...)`` (the partial itself is
    the application — it carries the kwargs), or
    ``partial(jax.jit, ...)(fn)``."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if _is_jit_name(f):
        return node
    if isinstance(f, ast.Name) and f.id == "partial" and node.args \
            and _is_jit_name(node.args[0]):
        return node
    return None


def _static_argnames(call: ast.Call):
    """String constants named by a jit application's static_argnames."""
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            yield v.value, kw.value.lineno
        elif isinstance(v, (ast.Tuple, ast.List)):
            for el in v.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    yield el.value, el.lineno


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        if not any(f.path.startswith(d) for d in SERVING_DIRS):
            continue
        # (1) jit applications inside function bodies = per-call wrappers
        for qual, fn, _nested in file_functions(f):
            for stmt in fn.body:
                for n in ast.walk(stmt):
                    if isinstance(n, ast.FunctionDef):
                        # a nested def's own decorators: @jax.jit there is a
                        # per-enclosing-call wrapper too
                        for dec in n.decorator_list:
                            if _is_jit_name(dec) or \
                                    _jit_application(dec) is not None:
                                out.append(Finding(
                                    ID, f.path, n.lineno,
                                    f"`{qual}` jit-decorates the nested "
                                    f"function `{n.name}` — a fresh wrapper "
                                    "(and compile cache) per enclosing "
                                    "call: silent recompile on the serving "
                                    "path", hint=HINT))
                        continue
                    app = _jit_application(n)
                    if app is not None:
                        out.append(Finding(
                            ID, f.path, n.lineno,
                            f"jit applied inside `{qual}` — a fresh wrapper "
                            "(and compile cache) per call: silent recompile "
                            "on the serving path", hint=HINT))
        # (2) off-grid static shape args, anywhere in the file
        for n in file_nodes(f):
            app = _jit_application(n)
            if app is None:
                continue
            for name, lineno in _static_argnames(app):
                if name not in BUCKET_GRID_STATICS:
                    out.append(Finding(
                        ID, f.path, lineno,
                        f"static_argnames entry `{name}` is outside the "
                        "registered bucket-grid vocabulary — its compile "
                        "signatures sit on no rung ladder, so AOT warmup "
                        "can never cover them", hint=HINT))
    return out
