"""KT006 — float64 / ``random`` nondeterminism inside jitted solver code.

The device solver's parity contract with the CPU oracle (``tests/
test_fuzz_parity.py``) is bit-honest only while the jitted programs stay
float32 and deterministic: a float64 constant silently upcasts a whole
lattice of intermediates (and TPUs demote to bf16/f32 anyway, so the CPU
test and the device diverge), and Python/numpy ``random`` inside traced code
is a tracer-time constant — it *looks* random and is baked in at compile,
the worst kind of nondeterminism.  Scope: functions decorated with
``jax.jit`` (including ``partial(jax.jit, ...)``), functions wrapped via
``jax.jit(fn)``, and the kernel library files (``ops/masks.py``,
``ops/feasibility.py``) whose every function is scan-body code.
``jax.random`` is exempt — key-threaded randomness is deterministic by
construction.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..ktlint import Finding, dotted_name, file_nodes

ID = "KT006"
TITLE = "float64/random nondeterminism in jitted solver code"
HINT = ("keep jitted code float32 (the TPU demotes anyway and parity tests "
        "compare against the oracle) and thread jax.random keys explicitly "
        "instead of host randomness")

KERNEL_SUFFIXES = ("ops/masks.py", "ops/feasibility.py")
RANDOM_ROOTS = ("random.", "np.random", "numpy.random")


def _is_jit_expr(node: ast.AST) -> bool:
    """True for `jit`, `jax.jit`, `partial(jax.jit, ...)`, `jax.jit(...)`."""
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return _is_jit_expr(f)
    return False


def _jit_scopes(f) -> List[ast.AST]:
    jit_wrapped_names: Set[str] = set()
    for n in file_nodes(f):
        # jax.jit(fn)(...) / run = jax.jit(fn, ...) — fn becomes jitted
        if (isinstance(n, ast.Call) and _is_jit_expr(n.func)
                and not isinstance(n.func, ast.Call) and n.args
                and isinstance(n.args[0], ast.Name)):
            jit_wrapped_names.add(n.args[0].id)
    scopes = []
    for n in file_nodes(f):
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(_is_jit_expr(d) for d in n.decorator_list):
            scopes.append(n)
        elif n.name in jit_wrapped_names:
            scopes.append(n)
    return scopes


def _scan_scope(nodes, f, seen: set, out: List[Finding]) -> None:
    for n in nodes:
        key = None
        if isinstance(n, ast.Attribute):
            d = dotted_name(n)
            if n.attr == "float64":
                key = (n.lineno, "float64")
                msg = "float64 dtype in jitted solver code"
            elif d is not None and (
                d.startswith("random.") or "np.random" in d
                or "numpy.random" in d
            ) and not d.startswith("jax."):
                key = (n.lineno, "random")
                msg = (f"host randomness `{d}` in jitted solver code "
                       "(baked in at trace time)")
        elif isinstance(n, ast.Constant) and n.value == "float64":
            key = (n.lineno, "float64")
            msg = "float64 dtype in jitted solver code"
        if key is not None and key not in seen:
            seen.add(key)
            out.append(Finding(ID, f.path, key[0], msg, hint=HINT))


def check(files) -> List[Finding]:
    out: List[Finding] = []
    for f in files:
        seen: set = set()
        if any(f.path.endswith(s) for s in KERNEL_SUFFIXES):
            _scan_scope(file_nodes(f), f, seen, out)
            continue
        for scope in _jit_scopes(f):
            _scan_scope(ast.walk(scope), f, seen, out)
    return out
