"""KT025 — per-member gang-identity access outside the gang package.

ISSUE 20's gang contract (docs/GANGS.md) holds only if every layer
treats a gang as ONE unit: one admission ticket, one delta
perturbation, one all-or-nothing placement decision.  The moment an
admission or solver path reads a member's ``gang_id``/``gang_size``
directly, it is re-deriving group semantics locally — and local
derivations drift (a host fast path that seats "just this member", a
shed that drops half a roster, an accounting loop that counts members
as units).  All group logic lives in ``karpenter_tpu/gang/``: membership
(``gang_of``/``has_gangs``/``gang_members``), placement discipline
(``gang_fixed``/``run_epilogue``), unit accounting (``admission_units``)
and delta widening (``expand_gang_removals``) are the sanctioned entry
points, and they are the ONLY code that touches the raw fields.

Flagged: any ``.gang_id`` / ``.gang_size`` attribute access in a file
under ``karpenter_tpu/admission/`` or ``karpenter_tpu/solver/`` (reads
and writes alike — a solver path has no business minting membership
either).

Exempt: ``karpenter_tpu/gang/`` itself (outside the scanned dirs by
construction), and everything outside the two scoped packages —
``models/pod.py`` declares the fields and ``service/codec.py`` moves
them on/off the wire; both are data plumbing, not group decisions.
"""

from __future__ import annotations

import ast
from typing import List

from ..ktlint import Finding, file_nodes

ID = "KT025"
TITLE = "per-member gang-identity access outside the gang package"
HINT = ("route group semantics through karpenter_tpu.gang — "
        "`gang_of(pod)`/`has_gangs`/`gang_members` for membership, "
        "`gang_fixed` for placement-path gating, `admission_units` for "
        "ticket accounting, `expand_gang_removals` for delta widening; "
        "a local read of the raw fields re-derives the all-or-nothing "
        "contract and will drift from it")

#: the fields whose direct access re-derives group semantics locally
GANG_FIELDS = ("gang_id", "gang_size")
#: packages where gang decisions must route through the gang package
SCOPED_PARTS = ("karpenter_tpu/admission/", "karpenter_tpu/solver/")


def _in_scope(path: str) -> bool:
    return any(part in path for part in SCOPED_PARTS)


def check(files) -> List[Finding]:
    findings: List[Finding] = []
    for f in files:
        if not _in_scope(f.path):
            continue
        for n in file_nodes(f):
            if not (isinstance(n, ast.Attribute) and n.attr in GANG_FIELDS):
                continue
            findings.append(Finding(
                ID, f.path, n.lineno,
                f"direct `.{n.attr}` access re-derives gang semantics "
                "locally — admission/solver paths must treat a gang as "
                "one unit through the karpenter_tpu.gang entry points, "
                "or the all-or-nothing contract drifts",
                hint=HINT,
            ))
    return findings
