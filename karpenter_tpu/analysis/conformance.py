"""Runtime trace conformance against the model-checked automaton.

``analysis/model.py`` proves the protocol MODELS correct; this module
closes the loop with the implementation: the serving stack emits
transition events (``obs/protocol.py``), and :func:`check_events`
asserts every observed per-session sequence is a path of
:data:`~karpenter_tpu.analysis.model.SESSION_AUTOMATON` — which the
model checker itself validates against the lease model by a simulation
relation, so a conformance PASS here is transitively a PASS against the
explored state space.

Two checks run per session:

1. **Automaton membership** — subset simulation with epsilon closure
   (crashes and reaps are invisible, so the checker tracks the SET of
   lifecycle states the session could be in; an event with no outgoing
   edge from any of them is a violation).
2. **The drainer rule** — per-replica teeth the global automaton cannot
   carry: after replica R hands a session off (``handoff``), R must not
   serve that chain again (commit/claim) unless it re-acquired it
   (establish/adopt/steal at R).  A violation here is exactly the
   "drained session served by the drainer" invariant, observed live.

Wired into ``scripts/chaos_drive.py`` (all five fleet scenarios) and the
replay harness, strict by default: an unexplainable event sequence fails
the run with the offending session's full event log in the report.

Pure stdlib, imports only sibling analysis code.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .model import (AUTOMATON_STATES, SESSION_AUTOMATON, automaton_step,
                    epsilon_closure)

#: events that mean "replica R is serving / has acquired this chain"
_ACQUIRE = ("establish", "adopt", "steal")
#: events that mean "replica R advanced or claimed the chain"
_SERVE = ("commit", "claim")


@dataclass(frozen=True)
class ConformanceViolation:
    session_id: str
    index: int          # offset of the offending event in the sequence
    event: str
    reason: str
    events: Tuple[str, ...]  # the full observed sequence, for the report

    def format(self) -> str:
        marked = ", ".join(
            (f">>{e}<<" if i == self.index else e)
            for i, e in enumerate(self.events))
        return (f"session {self.session_id}: {self.reason}\n"
                f"  observed: [{marked}]")


@dataclass
class ConformanceReport:
    sessions: int
    events: int
    violations: List[ConformanceViolation]

    @property
    def ok(self) -> bool:
        return not self.violations

    def format(self) -> str:
        head = (f"conformance: {self.sessions} sessions, "
                f"{self.events} events, "
                f"{len(self.violations)} violations")
        if not self.violations:
            return head + " — every observed sequence is a model path"
        return head + "\n" + "\n".join(v.format()
                                       for v in self.violations)

    def to_json(self) -> dict:
        return {
            "sessions": self.sessions,
            "events": self.events,
            "ok": self.ok,
            "violations": [
                {"session_id": v.session_id, "index": v.index,
                 "event": v.event, "reason": v.reason,
                 "events": list(v.events)}
                for v in self.violations],
        }


def _check_automaton(sid: str, events: Sequence[Tuple[str, dict]]
                     ) -> Optional[ConformanceViolation]:
    names = tuple(e for e, _ in events)
    cur = epsilon_closure(frozenset(AUTOMATON_STATES))
    for i, (ev, _attrs) in enumerate(events):
        if ev not in SESSION_AUTOMATON:
            return ConformanceViolation(
                sid, i, ev,
                f"event `{ev}` is not in the model's vocabulary",
                names)
        cur = automaton_step(cur, ev)
        if not cur:
            return ConformanceViolation(
                sid, i, ev,
                f"event `{ev}` has no transition from any lifecycle "
                "state the session could be in — the observed sequence "
                "left the model's language", names)
    return None


def _check_drainer(sid: str, events: Sequence[Tuple[str, dict]]
                   ) -> Optional[ConformanceViolation]:
    """After `handoff` from replica R, R must re-acquire before serving
    the chain again.  Events missing a replica attribute (emitted before
    the table knows its identity — none today) are skipped, never
    guessed."""
    names = tuple(e for e, _ in events)
    handed_by = None
    for i, (ev, attrs) in enumerate(events):
        replica = attrs.get("replica")
        if ev == "handoff" and replica is not None:
            handed_by = replica
        elif handed_by is not None and replica == handed_by:
            if ev in _ACQUIRE:
                handed_by = None
            elif ev in _SERVE:
                return ConformanceViolation(
                    sid, i, ev,
                    f"replica {replica} emitted `{ev}` for a chain it "
                    "handed off without re-acquiring it — a drained "
                    "session served by its drainer", names)
        elif handed_by is not None and replica is not None \
                and ev in _ACQUIRE:
            # acquired elsewhere: the handoff is resolved; the drainer
            # may later adopt it back legitimately
            handed_by = None
    return None


def check_events(events_by_session: Dict[str, List[Tuple[str, dict]]]
                 ) -> ConformanceReport:
    """Check every observed session's event sequence against the
    model-checked automaton plus the drainer rule.  Reports EVERY
    violating session (first offending event each), not just the
    first."""
    violations: List[ConformanceViolation] = []
    n_events = 0
    for sid in sorted(events_by_session):
        events = events_by_session[sid]
        n_events += len(events)
        v = _check_automaton(sid, events)
        if v is None:
            v = _check_drainer(sid, events)
        if v is not None:
            violations.append(v)
    return ConformanceReport(len(events_by_session), n_events,
                             violations)


def check_recorder(recorder) -> ConformanceReport:
    """Convenience: check a live ``obs.protocol.TransitionRecorder``."""
    return check_events(recorder.events_by_session())
