"""ktlint — AST-level solver-invariant analyzer for karpenter_tpu.

The vectorized solver only counts as fast if it stays *correct*: PR 1's
threaded ``SolvePipeline`` + ``TensorizeCache`` introduced exactly the bug
classes the round-5 advisor caught by hand (a scheduler re-entrancy race, a
missed metric-label zero-init, sync-point drift).  This package encodes those
invariants as machine-checked rules so every future perf PR is gated by
``make lint`` / ``tests/test_lint.py`` instead of advisor archaeology.

Rules (each lives in ``analysis/rules/kt00X.py``; catalog in
``docs/ANALYSIS.md``):

- **KT001** implicit host↔device sync in solver hot paths outside the fence
  allowlist
- **KT002** raw ``time.time()`` / ``time.monotonic()`` outside
  ``utils/clock.py`` (must use the injectable clock)
- **KT003** labeled counter series incremented somewhere but never
  zero-inited (Prometheus ``rate()``/``increase()`` lose the first increment
  of a series born at its first ``inc``)
- **KT004** lock discipline: ``# guarded-by: <lock>``-declared attributes
  accessed outside ``with self.<lock>:``
- **KT005** broad ``except Exception`` that neither re-raises nor logs
- **KT006** float64 / ``random`` nondeterminism inside jitted solver code

Annotation grammar (shared by the rules):

- suppression — ``# ktlint: allow[KT00X] <reason>`` on the finding line or
  anywhere in the contiguous pure-comment block directly above it.  The
  reason is mandatory; a bare ``allow[...]`` is itself reported (KT000) and
  does not suppress.
- fence — ``# ktlint: fence <why>`` on a ``def`` line (or anywhere in the
  contiguous pure-comment block directly above it) marks the function as an
  allowlisted host↔device sync point for KT001.
- guarded-by — ``self._attr = ...  # guarded-by: _lock`` in a class body
  declares that ``self._attr`` may only be touched inside
  ``with self._lock:`` (KT004).

This module is pure stdlib (``ast`` + ``re``) — importing it must never pull
jax, so ``make lint`` stays sub-second and runs anywhere.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*ktlint:\s*allow\[(?P<rule>KT\d{3})\](?:\s+(?P<reason>\S.*))?"
)
FENCE_RE = re.compile(r"#\s*ktlint:\s*fence\b")
GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>\w+)")

#: generated files are not ours to lint
EXCLUDED_SUFFIXES = ("_pb2.py",)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str
    hint: str = ""

    def format(self) -> str:
        out = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_json(self) -> dict:
        """The ``--format json`` shape (schema: docs/ANALYSIS.md)."""
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint}


@dataclasses.dataclass
class SourceFile:
    """One parsed source file plus its ktlint annotations."""

    path: str                  # slash-normalized, package-relative
    text: str
    lines: List[str]
    tree: ast.AST
    #: line -> {rule: reason} for well-formed suppressions
    suppressions: Dict[int, Dict[str, str]]
    #: lines carrying a malformed (reason-less) suppression
    malformed: List[int]
    #: ``def`` linenos annotated as KT001 fences
    fence_lines: set
    #: lazily cached whole-tree artifacts (file_nodes/file_parents): every
    #: rule iterates the package's ASTs, and 20+ rules each re-running
    #: ``ast.walk``/``parents_map`` over 110 files was ~70% of the cold
    #: package lint's wall — the speed gate's budget is shared by ALL rules
    _nodes: Optional[List[ast.AST]] = dataclasses.field(
        default=None, repr=False, compare=False)
    _parents: Optional[Dict[ast.AST, ast.AST]] = dataclasses.field(
        default=None, repr=False, compare=False)


def load_source(text: str, path: str) -> SourceFile:
    tree = ast.parse(text, filename=path)
    lines = text.splitlines()
    suppressions: Dict[int, Dict[str, str]] = {}
    malformed: List[int] = []
    fence_comment_lines = set()
    for i, line in enumerate(lines, 1):
        m = SUPPRESS_RE.search(line)
        if m:
            if m.group("reason"):
                suppressions.setdefault(i, {})[m.group("rule")] = m.group("reason")
            else:
                malformed.append(i)
        if FENCE_RE.search(line):
            fence_comment_lines.add(i)
    # resolve fence comments to the def they annotate: same line as the def,
    # or anywhere in the contiguous pure-comment block directly above it
    # (fence reasons routinely wrap onto a second line)
    fence_lines = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.lineno in fence_comment_lines:
                fence_lines.add(node.lineno)
                continue
            line = node.lineno - 1
            while _comment_only(lines, line):
                if line in fence_comment_lines:
                    fence_lines.add(node.lineno)
                    break
                line -= 1
    return SourceFile(
        path=path.replace("\\", "/"), text=text, lines=lines, tree=tree,
        suppressions=suppressions, malformed=malformed,
        fence_lines=fence_lines,
    )


def _comment_only(lines: List[str], lineno: int) -> bool:
    if not 1 <= lineno <= len(lines):
        return False
    return lines[lineno - 1].lstrip().startswith("#")


# ---- shared AST utilities ------------------------------------------------

def parents_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def file_nodes(f: SourceFile) -> List[ast.AST]:
    """The file's whole-tree preorder walk, computed once and shared by
    every rule (use instead of ``ast.walk(f.tree)`` for root walks;
    subtree walks still call ``ast.walk`` directly)."""
    if f._nodes is None:
        f._nodes = list(ast.walk(f.tree))
    return f._nodes


def file_parents(f: SourceFile) -> Dict[ast.AST, ast.AST]:
    """The file's child->parent map, computed once and shared by every
    rule (use instead of ``parents_map(f.tree)``)."""
    if f._parents is None:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in file_nodes(f):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        f._parents = parents
    return f._parents


def file_functions(f: SourceFile):
    """Cached :func:`iter_functions` over the file's whole tree."""
    funcs = getattr(f, "_functions", None)
    if funcs is None:
        funcs = f._functions = iter_functions(f.tree)
    return funcs


def iter_functions(tree: ast.AST):
    """Yield ``(qualname, node, nested)`` for every function; ``nested`` is
    True when the function is defined inside another function (closures
    belong to their enclosing method's scan)."""
    out = []

    def visit(node: ast.AST, prefix: str, in_func: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child, in_func))
                visit(child, q + ".", True)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", in_func)
            else:
                visit(child, prefix, in_func)

    visit(tree, "", False)
    return out


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---- driver --------------------------------------------------------------

def all_rules():
    from .rules import ALL_RULES

    return ALL_RULES


def analyze_files(
    files: Sequence[SourceFile], rules=None, cache=None, project=None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run every rule over ``files``; returns ``(active, suppressed)``.

    Whole-program rules (``WHOLE_PROGRAM = True``) share ONE linked
    :class:`~karpenter_tpu.analysis.callgraph.Project`, built lazily and —
    when ``cache`` is a :class:`~karpenter_tpu.analysis.callgraph
    .SummaryCache` — from content-hash-cached per-file summaries.  A
    caller that already built a project for the same files (the
    ``--lock-order`` driver path, tests) passes it in; no second walk."""
    raw: List[Finding] = []
    for rule in rules if rules is not None else all_rules():
        if getattr(rule, "WHOLE_PROGRAM", False):
            if project is None:
                from .callgraph import Project

                project = Project.build(files, cache=cache)
            raw.extend(rule.check(files, project=project))
        else:
            raw.extend(rule.check(files))
    by_path = {f.path: f for f in files}
    for f in files:
        for line in f.malformed:
            raw.append(Finding(
                "KT000", f.path, line,
                "malformed suppression: `# ktlint: allow[KT00X]` requires a "
                "reason and does not suppress without one",
                hint="write `# ktlint: allow[KT00X] <reason>`",
            ))
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for fi in raw:
        f = by_path.get(fi.path)
        (suppressed if f is not None and _is_suppressed(f, fi) else
         active).append(fi)
    key = lambda fi: (fi.path, fi.line, fi.rule)  # noqa: E731
    return sorted(active, key=key), sorted(suppressed, key=key)


def _is_suppressed(f: SourceFile, finding: Finding) -> bool:
    if finding.rule == "KT000":
        return False  # the malformed-suppression report is not suppressible
    if finding.rule in f.suppressions.get(finding.line, {}):
        return True
    # or anywhere in the contiguous pure-comment block directly above
    line = finding.line - 1
    while _comment_only(f.lines, line):
        if finding.rule in f.suppressions.get(line, {}):
            return True
        line -= 1
    return False


def analyze_source(text: str, path: str, rules=None) -> List[Finding]:
    """Fixture/test helper: analyze one in-memory source; active findings."""
    active, _ = analyze_files([load_source(text, path)], rules=rules)
    return active


def package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def collect_package_files(root: Optional[Path] = None) -> List[SourceFile]:
    root = Path(root) if root is not None else package_root()
    files: List[SourceFile] = []
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        if any(str(p).endswith(s) for s in EXCLUDED_SUFFIXES):
            continue
        rel = f"{root.name}/{p.relative_to(root).as_posix()}"
        files.append(load_source(p.read_text(), rel))
    return files


def analyze_package(
    root: Optional[Path] = None, rules=None, cache=None,
) -> Tuple[List[Finding], List[Finding], int]:
    """Analyze the whole package; ``(active, suppressed, n_files)``.

    Package runs default to the persistent summary cache (``KT_LINT_CACHE``
    to relocate, ``KT_LINT_CACHE=0`` to disable) so the warm whole-program
    run stays inside the tests/test_lint.py speed gate."""
    if cache is None:
        from .callgraph import SummaryCache

        cache = SummaryCache.default()
    files = collect_package_files(root)
    active, suppressed = analyze_files(files, rules=rules, cache=cache)
    return active, suppressed, len(files)


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="ktlint",
        description="repo-specific AST analyzer (rule catalog: docs/ANALYSIS.md)",
    )
    parser.add_argument("paths", nargs="*",
                        help="files or directories (default: the package)")
    parser.add_argument("--select", action="append", default=None,
                        metavar="KT00X", help="run only these rule IDs")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--format", choices=["text", "json"], default="text",
                        help="output format (json schema: docs/ANALYSIS.md)")
    parser.add_argument("--lock-order", action="store_true",
                        help="print the KT012-derived global lock-"
                             "acquisition order and exit")
    parser.add_argument("--model", action="store_true",
                        help="model-check the delta-epoch and lease-"
                             "failover protocols (bounded exhaustive "
                             "exploration; exits 1 on violation)")
    parser.add_argument("--max-states", type=int, default=500_000,
                        help="state budget per model for --model")
    parser.add_argument("--proto-golden", action="store_true",
                        help="refresh the KT021 golden descriptor snapshot "
                             "from the live solver.proto and exit")
    args = parser.parse_args(argv)

    if args.proto_golden:
        from .rules import kt021

        out = kt021.write_golden()
        print(f"wrote {out}")
        return 0

    if args.model:
        from . import model

        return model.main(fmt=args.format, max_states=args.max_states)

    rules = all_rules()
    if args.select:
        want = set(args.select)
        rules = [r for r in rules if r.ID in want]
        unknown = want - {r.ID for r in rules}
        if unknown:
            parser.error(f"unknown rule id(s): {sorted(unknown)}")

    if args.paths:
        files = []
        for raw in args.paths:
            p = Path(raw)
            if p.is_dir():
                files.extend(collect_package_files(p))
            else:
                files.append(load_source(p.read_text(), str(p)))
    else:
        files = collect_package_files()

    # ONE summary cache and ONE project build per invocation: the
    # whole-program rules (KT012/KT013/KT014/KT022) and the --lock-order
    # path all share it — explicit-path runs included, now that cache
    # entries are keyed on (module, content-hash) rather than raw path.
    from .callgraph import Project, SummaryCache

    cache = SummaryCache.default()
    project = None
    if args.lock_order or any(getattr(r, "WHOLE_PROGRAM", False)
                              for r in rules):
        project = Project.build(files, cache=cache)

    if args.lock_order:
        from .rules import kt012

        graph = kt012.lock_graph(files, project)
        _nodes, edges, kinds = graph
        order = kt012.lock_order(files, project, graph=graph)
        if args.format == "json":
            import json

            print(json.dumps({
                "order": order,
                "kinds": {k: v for k, v in sorted(kinds.items())},
                "edges": sorted(f"{s} -> {d}" for (s, d) in edges),
            }, indent=2))
        else:
            print("global lock-acquisition order (outer first; "
                  "sanitize.LOCK_ORDER must stay a linear extension):")
            for i, lock in enumerate(order, 1):
                print(f"  {i:2d}. {lock}  [{kinds.get(lock) or 'unknown'}]")
            for (s, d), e in sorted(edges.items()):
                print(f"  edge {s} -> {d}: {e.witness()}")
        return 0

    active, suppressed = analyze_files(files, rules=rules, cache=cache,
                                       project=project)
    n_files = len(files)

    if args.format == "json":
        import json

        print(json.dumps({
            "findings": [fi.to_json() for fi in active],
            "suppressed": [fi.to_json() for fi in suppressed],
            "files": n_files,
        }, indent=2))
        return 1 if active else 0
    for fi in active:
        print(fi.format())
    if args.show_suppressed:
        for fi in suppressed:
            print(f"[suppressed] {fi.format()}")
    print(f"ktlint: {len(active)} finding(s), {len(suppressed)} suppressed, "
          f"{n_files} file(s)")
    return 1 if active else 0
