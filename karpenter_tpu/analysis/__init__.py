"""Machine-checked solver invariants: the ktlint static analyzer — the
function-local rules KT001-KT011 plus the whole-program call-graph passes
KT012-KT014 (``analysis/callgraph.py``) — and the runtime lock-discipline
+ lock-order sanitizer (``KT_SANITIZE=1``).

Run the analyzer: ``python -m karpenter_tpu.analysis`` (``make lint``);
``--format json`` for machine-readable findings, ``--lock-order`` for the
KT012-derived global lock-acquisition order.
Rule catalog and annotation grammar: docs/ANALYSIS.md.

``sanitize`` is deliberately NOT imported here — the analyzer is pure stdlib
and must stay importable (and fast) without jax/grpc; the sanitizer pulls in
the solver stack and is loaded on demand by ``karpenter_tpu.__init__`` when
``KT_SANITIZE=1``.
"""

from .callgraph import (  # noqa: F401
    Project,
    SummaryCache,
    build_project,
)
from .ktlint import (  # noqa: F401
    Finding,
    analyze_files,
    analyze_package,
    analyze_source,
    load_source,
    main,
)
