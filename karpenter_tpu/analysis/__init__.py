"""Machine-checked solver invariants: the ktlint static analyzer (KT001-KT006)
plus the runtime lock-discipline sanitizer (``KT_SANITIZE=1``).

Run the analyzer: ``python -m karpenter_tpu.analysis`` (``make lint``).
Rule catalog and annotation grammar: docs/ANALYSIS.md.

``sanitize`` is deliberately NOT imported here — the analyzer is pure stdlib
and must stay importable (and fast) without jax/grpc; the sanitizer pulls in
the solver stack and is loaded on demand by ``karpenter_tpu.__init__`` when
``KT_SANITIZE=1``.
"""

from .ktlint import (  # noqa: F401
    Finding,
    analyze_files,
    analyze_package,
    analyze_source,
    load_source,
    main,
)
