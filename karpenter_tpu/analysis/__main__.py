"""``python -m karpenter_tpu.analysis`` — run ktlint over the package.

Exits non-zero when any unsuppressed finding remains (``make lint`` /
tier-1's ``tests/test_lint.py`` both gate on this).
"""

from .ktlint import main

if __name__ == "__main__":
    raise SystemExit(main())
