"""Explicit-state model checking for the serving protocols (ISSUE 17).

The repo now carries three distributed protocols whose correctness was
enforced by hand across review rounds — the delta-session epoch protocol
(PR 10), the lease/claim/steal/drain failover state machine (PR 13), and
the spool durability rules threaded through both (PR 12).  Every one of
them shipped at least one race that only multi-round human review caught
(zombie-writer, lease livelock, unacked-removal divergence).  This module
replaces that review burden with a machine: hand-written MODELS of both
protocols, explored by bounded exhaustive DFS over every interleaving of
client sends, server steps, crashes, lease expiries, steals, drains and
spool rollbacks, checking the invariants the reviews enforced informally:

- **exactly-one lease winner** — a spool record is adopted at most once;
  concurrent adopters race through the lease and exactly one wins;
- **epoch monotonicity** — a table never re-issues an epoch it has ever
  seen (the ``next_epoch`` floor), and across replicas the session nonce
  refuses a superseded incarnation's state;
- **no serve from a half-mutated chain** — a mid-step chain is never
  snapshotted (``in_step`` guard) and never serves;
- **a drained session is never served by the drainer** — after a drain
  handoff the draining replica never commits another epoch of that chain
  (the client re-homes on the ``draining`` hint and fleet routing avoids
  draining replicas);
- **cumulative-retry convergence** — whatever is lost, shed, crashed or
  rolled back, an applied step is applied onto exactly the base the
  client believes in: divergence is impossible, only typed re-establishes.

Every invariant has a *seeded-violation twin*: a config flag that removes
the guard the implementation actually has (``use_nonce=False``,
``owner_checked_drop=False``, ...), under which the DFS must FIND a
counterexample — proving the checker has teeth, and pinning the two real
divergences this PR fixed (the cross-replica epoch-collision closed by
the session nonce, and the zombie ``drop("error")`` clobbering the
adopter's spool record).

Like the rest of ``analysis/``, this module is pure stdlib — it must
import neither jax nor anything that transitively does, so the checker
runs anywhere the linter does (pre-commit, CI, a laptop).

Conformance (``analysis/conformance.py``) closes the loop with reality:
the implementation emits transition events (``obs/protocol.py``) and the
checker asserts every OBSERVED per-session event sequence is a path of
:data:`SESSION_AUTOMATON` — which is itself validated against the lease
model here by an edge-wise simulation relation (``simulate_automaton``),
so model, automaton and implementation stay mutually consistent.

CLI: ``python -m karpenter_tpu.analysis --model [--format json]`` /
``make modelcheck`` — prints states, transitions, invariants and (on
violation) a minimal counterexample trace; the state-space size is
published so a silently shrinking exploration is visible in review.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Violation:
    """One invariant violation with its minimal-ish counterexample: the
    action labels from the initial state to the violating state (DFS
    parent chain — not guaranteed shortest, but complete and replayable
    by hand against the model's action semantics)."""

    invariant: str
    message: str
    trace: Tuple[str, ...]

    def format(self) -> str:
        steps = "\n".join(f"  {i + 1:2d}. {a}"
                          for i, a in enumerate(self.trace))
        return (f"invariant violated: {self.invariant}\n"
                f"  {self.message}\ncounterexample "
                f"({len(self.trace)} steps):\n{steps}")


@dataclass
class Result:
    """One bounded-exhaustive exploration: how much was explored and the
    first violation found (None = every reachable state satisfies every
    invariant)."""

    model: str
    states: int
    transitions: int
    violation: Optional[Violation]
    elapsed_s: float
    truncated: bool = False  # state cap hit: NOT exhaustive

    @property
    def ok(self) -> bool:
        return self.violation is None and not self.truncated

    def to_json(self) -> dict:
        out = {
            "model": self.model,
            "states": self.states,
            "transitions": self.transitions,
            "exhaustive": not self.truncated,
            "ok": self.ok,
            "elapsed_ms": round(self.elapsed_s * 1000.0, 1),
        }
        if self.violation is not None:
            out["violation"] = {
                "invariant": self.violation.invariant,
                "message": self.violation.message,
                "trace": list(self.violation.trace),
            }
        return out


def explore(model, max_states: int = 500_000) -> Result:
    """Bounded exhaustive DFS over ``model``'s reachable state space.

    ``model`` supplies ``name``, ``init() -> state``, ``actions(state) ->
    iterable[(label, state)]`` and ``invariants: [(name, predicate)]``
    where a predicate returns an error message (violated) or None.
    States must be hashable values; the search memoizes parents for
    counterexample reconstruction.  Exceeding ``max_states`` marks the
    result truncated — callers gating on ``ok`` treat that as a failure,
    never as a silently smaller proof."""
    t0 = time.perf_counter()
    init = model.init()
    parents: Dict[object, Optional[Tuple[object, str]]] = {init: None}
    stack = [init]
    transitions = 0
    truncated = False

    def _trace(state) -> Tuple[str, ...]:
        labels: List[str] = []
        cur = state
        while True:
            link = parents[cur]
            if link is None:
                break
            cur, label = link
            labels.append(label)
        return tuple(reversed(labels))

    while stack:
        s = stack.pop()
        for inv_name, pred in model.invariants:
            msg = pred(s)
            if msg is not None:
                return Result(model.name, len(parents), transitions,
                              Violation(inv_name, msg, _trace(s)),
                              time.perf_counter() - t0, truncated)
        for label, s2 in model.actions(s):
            transitions += 1
            if s2 not in parents:
                if len(parents) >= max_states:
                    truncated = True
                    continue
                parents[s2] = (s, label)
                stack.append(s2)
    return Result(model.name, len(parents), transitions, None,
                  time.perf_counter() - t0, truncated)


# ---------------------------------------------------------------------------
# toy model — a deliberately broken protocol proving the DFS finds bugs
# ---------------------------------------------------------------------------


class BrokenCounterModel:
    """Two clients increment a shared counter read-modify-write with no
    compare-and-swap: the classic lost update.  Exists so the test suite
    can prove the ENGINE finds counterexamples — a checker that passes
    everything proves nothing."""

    name = "toy-broken-counter"

    def init(self):
        # (counter, done_writes, (c1_local, c2_local))  local=None: idle
        return (0, 0, (None, None))

    def actions(self, s):
        counter, done, locals_ = s
        for i in (0, 1):
            if locals_[i] is None and done + sum(
                    1 for v in locals_ if v is not None) < 2:
                held = list(locals_)
                held[i] = counter
                yield (f"c{i}_read", (counter, done, tuple(held)))
            elif locals_[i] is not None:
                held = list(locals_)
                held[i] = None
                yield (f"c{i}_write",
                       (locals_[i] + 1, done + 1, tuple(held)))

    invariants = (
        ("no-lost-update",
         lambda s: (None if s[0] == s[1]
                    else f"counter={s[0]} after {s[1]} completed "
                         "increments — an update was lost")),
    )


# ---------------------------------------------------------------------------
# model A — the delta-session epoch protocol (PR 10 + PR 12 spool)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class EpochConfig:
    """Bounds and guard switches for :class:`EpochModel`.

    The default config models the implementation AS SHIPPED (all guards
    on); each ``False`` switch removes one real guard so the matching
    invariant's seeded-violation fixture can prove the DFS finds the
    historical bug:

    - ``use_nonce=False`` — PRE-FIX wire protocol (no session nonce):
      the cross-replica epoch collision (a rolled-back spool record whose
      epoch coincides with a new incarnation's ack) silently diverges.
      This is the real divergence ISSUE 17's checker found; the nonce
      fields on the wire close it.
    - ``use_floor=False`` — establishment epochs restart at 1 instead of
      the ``next_epoch`` floor: epoch monotonicity per table breaks.
    - ``snapshot_guard=False`` — the spool writer ignores ``in_step``: a
      half-mutated chain lands on disk.
    """

    sends: int = 3        # client perturbations issued
    losses: int = 1       # replies the network may drop
    crashes: int = 2      # replica crashes / client re-homes (floor lost)
    rollbacks: int = 1    # PVC-restore adversary re-installing a record
    evicts: int = 1       # TTL/capacity eviction (floor kept)
    fails: int = 1        # mid-step failures (drop-with-reason-error)
    archives: int = 1     # backup copies the rollback adversary may take
    use_nonce: bool = True
    use_floor: bool = True
    snapshot_guard: bool = True


@dataclass(frozen=True)
class EpochState:
    """The composed client+server+spool state for ONE session.

    The chain's applied perturbations are a tuple of client-issued pid
    ints — equality of ``entry.applied`` and the client's ``view`` IS the
    convergence invariant.  ``wire`` is the single in-flight RPC (the
    client facade is synchronous by contract).  Flags latch an invariant
    violation at the action that commits it, so invariants stay plain
    state predicates."""

    entry: Optional[tuple]    # (epoch, nonce, applied, in_step, staged)
    record: Optional[tuple]   # (epoch, nonce, applied)
    archived: Optional[tuple]  # the PVC-backup adversary's copy: any one
                               # record version, restorable by rollback
    floor: int                # table's next_epoch floor (crash resets)
    max_issued: int           # highest epoch this table issued/observed
    ack: int
    cnonce: int
    view: tuple
    pending: tuple
    next_pid: int
    next_nonce: int
    wire: Optional[tuple]     # ("req",b,n,pids)|("ok",e,n,pids)|
                              # ("unknown",)|("error",)
    sends: int
    losses: int
    crashes: int
    rollbacks: int
    evicts: int
    fails: int
    archives: int
    diverged: str = ""
    torn: str = ""
    non_monotone: str = ""


class EpochModel:
    """Delta-session epochs: cumulative client retry, exact-match epoch
    check, ``next_epoch`` floor, epoch-atomic spool snapshot, adopt-once
    record consumption, and (post-fix) the per-incarnation session nonce.

    Establishment is modeled atomically (unknown reply -> re-established
    entry) — a full solve is idempotent from the client's ground-truth
    ledger, so interleaving its own RPC adds states without adding
    behaviors.  A crash models both a replica restart and a fleet
    re-home: either way the chain lands on a table whose in-memory epoch
    floor never saw this session's history, which is exactly the gap the
    session nonce closes."""

    name = "delta-epoch"

    #: conformance projection — which transition events (obs/protocol.py
    #: vocabulary) each action label's real counterpart emits
    EVENTS = {
        "establish": ("establish", "claim"),
        "commit": ("commit",),
        "serve_unknown": ("serve_unknown",),
        "serve_adopt_unknown": ("adopt", "serve_unknown"),
        "step_fail": ("drop:error",),
        "snapshot": ("spool",),
        "evict": ("evict:ttl",),
    }

    def __init__(self, cfg: EpochConfig = EpochConfig()):
        self.cfg = cfg
        self.invariants = (
            ("cumulative-retry-convergence",
             lambda s: s.diverged or None),
            ("no-half-mutated-snapshot",
             lambda s: s.torn or None),
            ("epoch-monotonicity",
             lambda s: s.non_monotone or None),
        )

    def init(self) -> EpochState:
        cfg = self.cfg
        # established session at epoch 1, nonce 1, empty chain
        return EpochState(
            entry=(1, 1, (), False, ()), record=None, archived=None,
            floor=2, max_issued=1, ack=1, cnonce=1, view=(), pending=(),
            next_pid=1, next_nonce=2, wire=None, sends=cfg.sends,
            losses=cfg.losses, crashes=cfg.crashes,
            rollbacks=cfg.rollbacks, evicts=cfg.evicts, fails=cfg.fails,
            archives=cfg.archives)

    # -- helpers ---------------------------------------------------------
    def _issue(self, s: EpochState, epoch: int, **kw) -> dict:
        """The ``next_epoch`` contract check: an ESTABLISHMENT epoch must
        be strictly above every epoch this table lifetime ever issued or
        observed.  (Commits may legitimately re-reach an epoch number by
        adopt-replay of the same chain after a lost reply — same lineage,
        same content — so only establishment is checked; commits and
        adoptions still RAISE the observed-epoch watermark.)"""
        out = dict(kw)
        if epoch <= s.max_issued:
            out["non_monotone"] = (
                f"establishment issued epoch {epoch} (max epoch ever "
                f"seen by this table lifetime: {s.max_issued}) — a "
                "stale exact-match check can now pass against old state")
        out["max_issued"] = max(s.max_issued, epoch)
        return out

    def actions(self, s: EpochState) -> Iterable[Tuple[str, EpochState]]:
        cfg = self.cfg
        # ---- client ----------------------------------------------------
        if s.wire is None:
            if s.sends > 0:
                pid = s.next_pid
                pend = s.pending + (pid,)
                yield (f"send(p{pid})", replace(
                    s, pending=pend, next_pid=pid + 1, sends=s.sends - 1,
                    wire=("req", s.ack, s.cnonce, pend)))
            if s.pending:
                # cumulative retry after a lost/errored reply: the SAME
                # unacked perturbation set, never a new pid
                yield ("resend", replace(
                    s, wire=("req", s.ack, s.cnonce, s.pending)))
        elif s.wire[0] == "ok":
            _, epoch, nonce, pids = s.wire
            yield ("recv_ok", replace(
                s, ack=epoch, cnonce=nonce, view=s.view + pids,
                pending=(), wire=None))
        elif s.wire[0] == "error":
            # typed step failure / transport error: session + pending
            # kept (service/client.DeltaSession._rpc contract)
            yield ("recv_error", replace(s, wire=None))
        elif s.wire[0] == "unknown":
            # exactly-one transparent re-establish: full solve from the
            # client's ground truth; own() force-claims and removes the
            # obsolete record; next_epoch() sweeps live entries into the
            # floor before issuing (delta.DeltaSessionTable.next_epoch)
            epoch0 = (max(s.floor, s.entry[0] + 1 if s.entry else 0)
                      if cfg.use_floor else 1)
            nonce = s.next_nonce
            full = s.view + s.pending
            yield ("establish", replace(
                s, entry=(epoch0, nonce, full, False, ()), record=None,
                floor=max(s.floor, epoch0 + 1), ack=epoch0, cnonce=nonce,
                view=full, pending=(), next_nonce=nonce + 1, wire=None,
                **self._issue(s, epoch0)))
        # ---- server ----------------------------------------------------
        if s.wire is not None and s.wire[0] == "req" and (
                s.entry is None or not s.entry[3]):
            _, base, rnonce, pids = s.wire
            entry, record, floor, label = s.entry, s.record, s.floor, ""
            adopted = False
            if entry is None and record is not None:
                # adopt-on-miss precedes the unknown answer, always
                # (server._serve_delta); the record is CONSUMED
                entry = (record[0], record[1], record[2], False, ())
                floor = max(floor, record[0] + 1)
                record, adopted = None, True
            nonce_ok = (not cfg.use_nonce or entry is None
                        or not (entry[1] and rnonce)
                        or entry[1] == rnonce)
            if entry is None or entry[0] != base or not nonce_ok:
                label = ("serve_adopt_unknown" if adopted
                         else "serve_unknown")
                yield (label, replace(
                    s, entry=entry, record=record, floor=floor,
                    wire=("unknown",),
                    max_issued=max(s.max_issued,
                                   entry[0] if entry else 0)))
            else:
                # epoch (and nonce) matched: begin the step.  The
                # convergence invariant latches HERE if the base the
                # server is about to mutate is not the base the client
                # believes in — the silent-divergence class every guard
                # in the protocol exists to prevent.
                div = s.diverged
                if entry[2] != s.view:
                    div = div or (
                        f"step applied onto base {entry[2]} while the "
                        f"client's view is {s.view} (epoch {base}"
                        f"{' after adopt' if adopted else ''}) — "
                        "silent divergence")
                yield (("serve_adopt_step" if adopted else "serve_step"),
                       replace(s, entry=(entry[0], entry[1], entry[2],
                                         True, pids),
                               record=record, floor=floor, diverged=div))
        if s.entry is not None and s.entry[3]:
            epoch, nonce, applied, _, staged = s.entry
            new_epoch = epoch + 1
            yield ("commit", replace(
                s, entry=(new_epoch, nonce, applied + staged, False, ()),
                wire=("ok", new_epoch, nonce, staged),
                max_issued=max(s.max_issued, new_epoch)))
            if s.fails > 0:
                # mid-step failure: drop("error") — entry evicted (its
                # epoch NOTED into the floor, like every departure) and
                # the spool record removed (poisoned chains re-establish
                # from ground truth, never re-adopt); the client sees a
                # typed error
                yield ("step_fail", replace(
                    s, entry=None, record=None, fails=s.fails - 1,
                    floor=max(s.floor, epoch + 1), wire=("error",)))
        # ---- spool + adversaries --------------------------------------
        if s.entry is not None:
            epoch, nonce, applied, in_step, staged = s.entry
            if not in_step or not cfg.snapshot_guard:
                rec = ((epoch, nonce, applied) if not in_step
                       # guard off: the writer captures a half-applied
                       # chain — applied plus a PREFIX of the staged set
                       else (epoch, nonce, applied + staged[:1]))
                if rec != s.record:
                    torn = s.torn
                    if in_step:
                        torn = torn or (
                            f"spool record captured mid-step at epoch "
                            f"{epoch} (half-applied chain on disk)")
                    yield ("snapshot", replace(s, record=rec, torn=torn))
            if s.crashes > 0:
                # crash/restart (or a fleet re-home): in-memory table
                # state AND its epoch floor are gone; an unanswered
                # request surfaces as a transport error client-side
                yield ("crash", replace(
                    s, entry=None, floor=1, max_issued=0,
                    crashes=s.crashes - 1,
                    wire=(("error",) if s.wire
                          and s.wire[0] == "req" else s.wire)))
            if s.evicts > 0 and not in_step:
                # TTL/capacity eviction: entry gone, floor NOTED (same
                # table keeps living) — the monotonicity guard's case
                yield ("evict", replace(
                    s, entry=None, floor=max(s.floor, epoch + 1),
                    evicts=s.evicts - 1))
        if s.wire is not None and s.wire[0] in ("ok", "unknown", "error") \
                and s.losses > 0:
            yield ("lose_reply", replace(
                s, wire=None, losses=s.losses - 1))
        if s.archives > 0 and s.record is not None \
                and s.record != s.archived:
            # the PVC-backup adversary copies the current record aside
            yield ("archive", replace(
                s, archived=s.record, archives=s.archives - 1))
        if s.rollbacks > 0 and s.archived is not None \
                and s.archived != s.record:
            # ... and a restore re-installs it over whatever is (or is
            # not) in the spool now
            yield (f"rollback(e{s.archived[0]})", replace(
                s, record=s.archived, rollbacks=s.rollbacks - 1))


# ---------------------------------------------------------------------------
# model B — the lease/claim/steal/drain failover protocol (PR 13)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LeaseConfig:
    """Bounds and guard switches for :class:`LeaseModel`.

    Guard switches (each one's ``False`` is a seeded-violation fixture):

    - ``owner_checked_drop=False`` — PRE-FIX ``drop("error")``: the spool
      record is removed without checking lease ownership, so a zombie
      replica's failing step destroys the adopter's record (the second
      real divergence this PR fixed).
    - ``lease_required=False`` — adoption ignores the lease and does not
      consume the record: two adopters both win.
    - ``epoch_check=False`` + ``own_removes_record=False`` — the serving
      path skips the incarnation check while establishment leaves stale
      records behind: a superseded chain commits.
    - ``respect_drain=False`` — fleet routing ignores the draining hint:
      the drainer re-adopts and serves the chain it just handed off.
    """

    replicas: int = 2
    steps: int = 2        # step_begin budget (committed epochs)
    crashes: int = 1
    expires: int = 1      # lease-expiry events (the wedged-owner window)
    errors: int = 1       # mid-step failures
    drains: int = 1
    establishes: int = 1
    rehomes: int = 2
    adopts: int = 1       # client-routed adoption attempts
    handoffs: int = 1     # drain handshakes
    contends: int = 1     # direct (non-client-routed) adoption attempts
    owner_checked_drop: bool = True
    lease_required: bool = True
    epoch_check: bool = True
    own_removes_record: bool = True
    respect_drain: bool = True


@dataclass(frozen=True)
class LeaseState:
    """One session across R replicas sharing one spool.

    ``entries[r]`` is the replica's live chain ``(incarnation, in_step)``
    or None; a single ``lease`` mirrors the one lease file per session;
    ``record`` carries ``(writer, incarnation, generation)`` — the
    generation counts record WRITES so adopt-once is checkable;
    ``consumed`` is the set of generations already adopted."""

    entries: tuple                 # per replica: None | (inc, in_step)
    lease: Optional[tuple]         # (owner, fresh)
    record: Optional[tuple]        # (writer, inc, gen)
    consumed: frozenset            # record generations already adopted
    drained: tuple                 # per replica: bool (draining)
    handed: Optional[tuple]        # last handoff (replica, inc)
    home: int
    client_inc: int
    next_inc: int
    next_gen: int
    steps: int
    crashes: int
    expires: int
    errors: int
    drains: int
    establishes: int
    rehomes: int
    adopts: int
    handoffs: int
    contends: int
    clobbered: str = ""
    double_adopt: str = ""
    stale_commit: str = ""
    drained_served: str = ""


class LeaseModel:
    """Lease/claim/steal/drain across ``cfg.replicas`` replicas and one
    shared spool, composed with the client's fleet routing (re-home on
    transport failure or the draining hint, never onto a draining
    replica).  Atomic actions model the ``_LeaseMutex`` critical section:
    each claim-check-write is one transition, exactly the serialization
    the on-disk mutex provides."""

    name = "lease-failover"

    EVENTS = {
        "establish": ("establish", "claim"),
        "commit": ("commit",),
        "serve_unknown": ("serve_unknown",),
        "adopt": ("adopt",),
        "steal": ("steal",),
        "adopt_refused": ("adopt_refused", "serve_unknown"),
        "step_error": ("drop:error",),
        "lease_lost": ("drop:lease_lost",),
        "snapshot": ("spool",),
        "snapshot_renew": ("spool",),
        "handoff": ("handoff",),
        "drain_refused": ("drain_refused",),
    }

    def __init__(self, cfg: LeaseConfig = LeaseConfig()):
        self.cfg = cfg
        self.invariants = (
            ("exactly-one-lease-winner",
             lambda s: s.double_adopt or None),
            ("record-owner-safety",
             lambda s: s.clobbered or None),
            ("no-superseded-commit",
             lambda s: s.stale_commit or None),
            ("drained-never-served-by-drainer",
             lambda s: s.drained_served or None),
        )

    def init(self) -> LeaseState:
        cfg = self.cfg
        R = cfg.replicas
        # session established on replica 0, lease held, nothing spooled
        return LeaseState(
            entries=((1, False),) + (None,) * (R - 1), lease=(0, True),
            record=None, consumed=frozenset(), drained=(False,) * R,
            handed=None, home=0, client_inc=1, next_inc=2, next_gen=1,
            steps=cfg.steps, crashes=cfg.crashes, expires=cfg.expires,
            errors=cfg.errors, drains=cfg.drains,
            establishes=cfg.establishes, rehomes=cfg.rehomes,
            adopts=cfg.adopts, handoffs=cfg.handoffs,
            contends=cfg.contends)

    # -- helpers ---------------------------------------------------------
    def _set(self, s: LeaseState, r: int, val) -> tuple:
        es = list(s.entries)
        es[r] = val
        return tuple(es)

    def _adopt_at(self, s: LeaseState, r: int, label_prefix: str):
        """The shared adopt path (client-routed serve-miss or a direct
        contend): lease claim semantics + record consumption + the
        adopt-once generation check."""
        cfg = self.cfg
        if s.record is None:
            return
        writer, inc, gen = s.record
        if not cfg.lease_required:
            # seeded violation: no claim, no consume — every adopter wins
            dbl = s.double_adopt
            if gen in s.consumed:
                dbl = dbl or (
                    f"record generation {gen} adopted twice — two "
                    "replicas now serve the same chain")
            yield (f"{label_prefix}adopt(r{r})", replace(
                s, entries=self._set(s, r, (inc, False)),
                consumed=s.consumed | {gen}, double_adopt=dbl))
            return
        if s.lease is None or s.lease[0] == r:
            how = "adopt"
        elif not s.lease[1]:
            how = "steal"
        else:
            yield (f"{label_prefix}adopt_refused(r{r})", s)
            return
        dbl = s.double_adopt
        if gen in s.consumed:
            dbl = dbl or (f"record generation {gen} adopted twice")
        yield (f"{label_prefix}{how}(r{r})", replace(
            s, entries=self._set(s, r, (inc, False)), lease=(r, True),
            record=None, consumed=s.consumed | {gen}, double_adopt=dbl))

    def actions(self, s: LeaseState) -> Iterable[Tuple[str, LeaseState]]:
        cfg = self.cfg
        R = cfg.replicas
        home = s.home
        mid_step = any(e is not None and e[1] for e in s.entries)
        # ---- client-routed serving at the home replica -----------------
        e = s.entries[home]
        if not mid_step:
            if e is None:
                if s.record is not None:
                    if s.adopts > 0:
                        for label, s2 in self._adopt_at(s, home, ""):
                            yield (label,
                                   replace(s2, adopts=s.adopts - 1))
                else:
                    yield (f"serve_unknown(r{home})", s)
                if s.establishes > 0 and not (s.drained[home]):
                    inc = s.next_inc
                    yield (f"establish(r{home})", replace(
                        s, entries=self._set(s, home, (inc, False)),
                        lease=(home, True),
                        record=(None if cfg.own_removes_record
                                else s.record),
                        client_inc=inc, next_inc=inc + 1,
                        establishes=s.establishes - 1))
                elif s.establishes > 0 and s.drained[home]:
                    yield (f"drain_refused(r{home})", s)
            elif e[0] == s.client_inc or not cfg.epoch_check:
                if s.steps > 0:
                    yield (f"step_begin(r{home})", replace(
                        s, entries=self._set(s, home, (e[0], True)),
                        steps=s.steps - 1))
            else:
                # live entry from a superseded incarnation: the epoch/
                # nonce check answers unknown, the client re-establishes
                yield (f"serve_unknown(r{home})", s)
        # ---- the one mid-step chain commits or fails -------------------
        for r in range(R):
            er = s.entries[r]
            if er is None or not er[1]:
                continue
            inc = er[0]
            stale = s.stale_commit
            if inc != s.client_inc:
                stale = stale or (
                    f"replica {r} committed incarnation {inc} while the "
                    f"client's chain is incarnation {s.client_inc} — "
                    "a superseded chain advanced")
            served = s.drained_served
            if s.handed is not None and s.handed == (r, inc):
                served = served or (
                    f"replica {r} served incarnation {inc} AFTER "
                    "handing it off in a drain — the drained chain "
                    "came back to its drainer")
            yield (f"commit(r{r})", replace(
                s, entries=self._set(s, r, (inc, False)),
                stale_commit=stale, drained_served=served))
            if s.errors > 0:
                # drop("error"): entry evicted; spool cleanup is the
                # owner-checked part — the PRE-FIX code removed the
                # record unconditionally, destroying the adopter's
                # record when a zombie's step failed
                owner = s.lease is not None and s.lease[0] == r
                record, lease, clob = s.record, s.lease, s.clobbered
                if cfg.owner_checked_drop:
                    if owner:
                        record, lease = None, None
                else:
                    if record is not None and record[0] != r \
                            and not owner:
                        clob = clob or (
                            f"replica {r} (lease lost) removed the "
                            f"record replica {record[0]} wrote — the "
                            "adopter's durability destroyed by a "
                            "zombie's failing step")
                    record = None
                    if owner:
                        lease = None
                yield (f"step_error(r{r})", replace(
                    s, entries=self._set(s, r, None), record=record,
                    lease=lease, errors=s.errors - 1, clobbered=clob))
        # ---- snapshot pass on any replica with a live chain ------------
        for r in range(R):
            er = s.entries[r]
            if er is None or er[1] or mid_step:
                continue
            inc = er[0]
            if s.lease is not None and s.lease[0] != r and s.lease[1]:
                # renewal refused: the zombie-writer guard — drop the
                # chain, write NOTHING over the new owner's record
                yield (f"lease_lost(r{r})", replace(
                    s, entries=self._set(s, r, None)))
            elif s.record is not None and s.record[:2] == (r, inc):
                # content already on disk: a re-write is protocol-noise;
                # only a lease renewal (expired -> fresh) changes state
                if s.lease != (r, True):
                    yield (f"snapshot_renew(r{r})", replace(
                        s, lease=(r, True)))
            else:
                # claim-or-renew then write: one atomic mutex section
                yield (f"snapshot(r{r})", replace(
                    s, lease=(r, True), record=(r, inc, s.next_gen),
                    next_gen=s.next_gen + 1))
        # ---- drain handshake -------------------------------------------
        for r in range(R):
            er = s.entries[r]
            if s.drains > 0 and not s.drained[r]:
                yield (f"drain(r{r})", replace(
                    s, drained=tuple(d or (i == r)
                                     for i, d in enumerate(s.drained)),
                    drains=s.drains - 1))
            if s.drained[r] and er is not None and not er[1] \
                    and s.handoffs > 0 and er[0] == s.client_inc \
                    and s.home == r:
                # handoff rides the SERVE path (server._serve_delta):
                # it fires only where the client is routed and only after
                # a successful step, i.e. at the current incarnation
                # handoff: record at the committed epoch, lease RELEASED,
                # entry dropped; the client re-homes on the hint (fleet
                # routing avoids draining replicas when respected)
                inc = er[0]
                new_home = s.home
                if cfg.respect_drain and s.home == r:
                    alive = [i for i in range(R)
                             if not s.drained[i] and i != r]
                    new_home = alive[0] if alive else s.home
                yield (f"handoff(r{r})", replace(
                    s, entries=self._set(s, r, None), lease=None,
                    record=(r, inc, s.next_gen),
                    next_gen=s.next_gen + 1, handed=(r, inc),
                    home=new_home, handoffs=s.handoffs - 1))
        # ---- adversaries + fleet routing -------------------------------
        for r in range(R):
            if s.entries[r] is not None and s.crashes > 0:
                yield (f"crash(r{r})", replace(
                    s, entries=self._set(s, r, None),
                    crashes=s.crashes - 1))
        if s.lease is not None and s.lease[1] and s.expires > 0:
            yield ("lease_expire", replace(
                s, lease=(s.lease[0], False), expires=s.expires - 1))
        if s.rehomes > 0 and not mid_step:
            for k in range(R):
                if k == s.home:
                    continue
                if cfg.respect_drain and s.drained[k]:
                    continue
                yield (f"rehome(r{k})", replace(
                    s, home=k, rehomes=s.rehomes - 1))
        if s.contends > 0 and not mid_step and s.record is not None:
            for r in range(R):
                if s.entries[r] is None and r != s.home:
                    for label, s2 in self._adopt_at(s, r, "contend_"):
                        yield (label,
                               replace(s2, contends=s.contends - 1))


# ---------------------------------------------------------------------------
# the per-session lifecycle automaton (conformance ground truth)
# ---------------------------------------------------------------------------

#: Global-per-session lifecycle states: ``live`` — some replica holds the
#: chain; ``spooled`` — no live chain but an adoptable record may exist;
#: ``cold`` — neither.  Crashes are invisible to the event stream, so
#: ``EPSILON`` lets the checker assume live->spooled (a crash with a
#: record behind) and spooled->cold (record reaped/rolled away) at any
#: point; there is deliberately NO epsilon from cold back to spooled —
#: a record resurrected after ``drop:error`` removed it (the stale-spool
#: adversary) must show up as a conformance violation, not be absorbed.
AUTOMATON_STATES = ("live", "spooled", "cold")

#: event -> tuple of (src, dst) transitions.  Events not in this table
#: are conformance violations by definition (an implementation emitting
#: a vocabulary the model never heard of is not conforming).
SESSION_AUTOMATON: Dict[str, Tuple[Tuple[str, str], ...]] = {
    "establish": (("live", "live"), ("spooled", "live"),
                  ("cold", "live")),
    "claim": (("live", "live"),),
    "commit": (("live", "live"),),
    "adopt": (("spooled", "live"),),
    "steal": (("live", "live"), ("spooled", "live")),
    "adopt_refused": (("live", "live"), ("spooled", "spooled")),
    "serve_unknown": (("live", "live"), ("spooled", "spooled"),
                      ("cold", "cold")),
    "drain_refused": (("live", "live"), ("spooled", "spooled"),
                      ("cold", "cold")),
    # handoff normally leaves the chain only on disk (live->spooled); a
    # same-incarnation zombie at the handed-off epoch may legitimately
    # keep the session live elsewhere (live->live).  The drainer-specific
    # guarantee — the HANDING replica never serves that chain again
    # without re-acquiring it — is per-replica, so it is checked by the
    # dedicated drainer rule in conformance.py (events carry replica
    # identity), not by this global-state automaton.
    "handoff": (("live", "spooled"), ("live", "live")),
    # every spool record write is observable: the owner refreshing its
    # chain (live self-loop), or a superseded zombie that stole back an
    # expired lease re-spooling its stale chain (cold->spooled) — the
    # ONLY legal way spool state reappears without an establish/handoff,
    # which is what lets the automaton refuse silent resurrection
    "spool": (("live", "live"), ("spooled", "spooled"),
              ("cold", "spooled")),
    # drop:error from the OWNER removes record+lease (live->cold); from a
    # zombie whose lease was stolen the chain lives on at the new owner
    # (live->live), survives only as the owner's record (live->spooled),
    # or the zombie was the last remnant of a superseded incarnation
    # (spooled/cold self-loops).  Globally uninformative by necessity —
    # the conformance teeth live in handoff/adopt/commit instead.
    "drop:error": (("live", "cold"), ("live", "live"),
                   ("live", "spooled"), ("spooled", "spooled"),
                   ("cold", "cold")),
    "drop:lease_lost": (("live", "live"), ("spooled", "spooled"),
                        ("cold", "cold")),
    "evict:ttl": (("live", "spooled"),),
    "evict:capacity": (("live", "spooled"),),
    "clear:stop": (("live", "spooled"), ("live", "live")),
    "clear:fault": (("live", "spooled"), ("live", "live")),
    "reap": (("spooled", "cold"), ("live", "live")),
}

EPSILON: Tuple[Tuple[str, str], ...] = (("live", "spooled"),
                                        ("spooled", "cold"))


def epsilon_closure(states: frozenset) -> frozenset:
    out = set(states)
    changed = True
    while changed:
        changed = False
        for src, dst in EPSILON:
            if src in out and dst not in out:
                out.add(dst)
                changed = True
    return frozenset(out)


def automaton_step(states: frozenset, event: str) -> frozenset:
    """One subset-construction step: from every possible current state,
    follow ``event``; empty result = the observed sequence left the
    model's language."""
    edges = SESSION_AUTOMATON.get(event, ())
    nxt = {dst for src, dst in edges if src in states}
    return epsilon_closure(frozenset(nxt))


def accepts(events: Iterable[str]) -> Optional[int]:
    """None when the event sequence is a path of the automaton, else the
    index of the first non-conforming event."""
    cur = epsilon_closure(frozenset(AUTOMATON_STATES))
    for i, ev in enumerate(events):
        cur = automaton_step(cur, ev)
        if not cur:
            return i
    return None


def _abstract_lease(s: LeaseState) -> str:
    """The session's GLOBAL lifecycle state: live means the current
    incarnation's chain is held by some replica — superseded zombie
    entries are walking dead (their only observable events are
    self-loops) and do not count."""
    if any(e is not None and e[0] == s.client_inc for e in s.entries):
        return "live"
    if s.record is not None:
        return "spooled"
    return "cold"


def simulate_automaton(model: Optional[LeaseModel] = None,
                       max_states: int = 500_000) -> Result:
    """Edge-wise simulation relation between :class:`LeaseModel` and
    :data:`SESSION_AUTOMATON`: for every reachable model transition, the
    abstraction of the source state must be able to take the
    transition's projected events (or an epsilon path, when the action
    is invisible) and land on the abstraction of the target state.  By
    induction over paths, every event sequence the model can produce is
    then accepted by the automaton — so a conformance PASS against the
    automaton is a PASS against the model."""
    model = model or LeaseModel()

    class _Sim:
        name = "lease-automaton-simulation"
        invariants = ()

        def init(self):
            return model.init()

        def actions(self, s):
            return model.actions(s)

    base = _Sim()
    t0 = time.perf_counter()
    parents = {base.init(): None}
    stack = list(parents)
    transitions = 0
    while stack:
        s = stack.pop()
        a_src = epsilon_closure(frozenset({_abstract_lease(s)}))
        for label, s2 in base.actions(s):
            transitions += 1
            action = label.split("(")[0].replace("contend_", "")
            events = model.EVENTS.get(action, ())
            cur = a_src
            for ev in events:
                cur = automaton_step(cur, ev)
            if _abstract_lease(s2) not in cur:
                viol = Violation(
                    "automaton-simulates-model",
                    f"model action `{label}` takes abstraction "
                    f"{_abstract_lease(s)} -> {_abstract_lease(s2)} but "
                    f"the automaton (events {list(events)}) cannot",
                    ("<edge>", label))
                return Result("lease-automaton-simulation",
                              len(parents), transitions, viol,
                              time.perf_counter() - t0)
            if s2 not in parents and len(parents) < max_states:
                parents[s2] = (s, label)
                stack.append(s2)
    return Result("lease-automaton-simulation", len(parents),
                  transitions, None, time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# bounded tier-1 entry points
# ---------------------------------------------------------------------------

#: the shipped configuration of each protocol model (all guards ON) —
#: tier-1 and `make modelcheck` require ZERO violations here
VERIFIED_MODELS: Tuple[Callable[[], object], ...] = (
    lambda: EpochModel(EpochConfig()),
    lambda: LeaseModel(LeaseConfig()),
)

#: invariant name -> a config under which the DFS MUST find a
#: counterexample (the guard the invariant depends on, removed).  These
#: double as regression fixtures for the two real divergences fixed in
#: this PR: the pre-nonce epoch collision and the unchecked drop(error)
#: record removal.
SEEDED_VIOLATIONS: Dict[str, Callable[[], object]] = {
    "cumulative-retry-convergence":
        lambda: EpochModel(replace(EpochConfig(), use_nonce=False)),
    "no-half-mutated-snapshot":
        lambda: EpochModel(replace(EpochConfig(), snapshot_guard=False)),
    "epoch-monotonicity":
        lambda: EpochModel(replace(EpochConfig(), use_floor=False)),
    "exactly-one-lease-winner":
        lambda: LeaseModel(replace(LeaseConfig(), lease_required=False)),
    "record-owner-safety":
        lambda: LeaseModel(replace(LeaseConfig(),
                                   owner_checked_drop=False)),
    "no-superseded-commit":
        lambda: LeaseModel(replace(LeaseConfig(), epoch_check=False,
                                   own_removes_record=False)),
    "drained-never-served-by-drainer":
        lambda: LeaseModel(replace(LeaseConfig(), respect_drain=False)),
}


def check_all(max_states: int = 500_000) -> List[Result]:
    """The `make modelcheck` body: both shipped protocol models plus the
    automaton simulation relation, bounded-exhaustively."""
    results = [explore(mk(), max_states=max_states)
               for mk in VERIFIED_MODELS]
    results.append(simulate_automaton(max_states=max_states))
    return results


def main(fmt: str = "text", max_states: int = 500_000) -> int:
    """CLI body for ``python -m karpenter_tpu.analysis --model``."""
    import json as _json

    results = check_all(max_states=max_states)
    if fmt == "json":
        print(_json.dumps({
            "models": [r.to_json() for r in results],
            "ok": all(r.ok for r in results),
        }, indent=2, sort_keys=True))
    else:
        for r in results:
            status = "ok" if r.ok else (
                "TRUNCATED" if r.truncated else "VIOLATED")
            print(f"{r.model}: {status} — {r.states} states, "
                  f"{r.transitions} transitions explored exhaustively "
                  f"in {r.elapsed_s * 1000.0:.0f} ms")
            if r.violation is not None:
                print(r.violation.format())
        if all(r.ok for r in results):
            total = sum(r.states for r in results)
            print(f"all protocol invariants hold over {total} states")
    return 0 if all(r.ok for r in results) else 1
