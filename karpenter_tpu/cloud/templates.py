"""Node templates, image-family resolution, userdata bootstrap, and the
launch-template cache.

Re-creates the reference's L2 launch stack in provider-neutral form:

- ``NodeTemplate`` — the AWSNodeTemplate CRD analog
  (pkg/apis/v1alpha1/awsnodetemplate.go): image family + selectors, userdata,
  block devices, metadata options, tags; status carries discovered
  subnets/security-groups (filled by the nodetemplate controller).
- image families — strategy interface like amifamily/resolver.go:72-79:
  per-family default image aliases (SSM-alias analog), bootstrap script
  generation (MIME-merge for the eks-like family per
  bootstrap/eksbootstrap.go:165-263, TOML for the bottlerocket-like family),
  and per-(arch, accelerator) image variants (al2.go:37-45).
- ``LaunchTemplateProvider`` — one cached launch template per resolved
  (image, userdata, ...) hash with create-on-miss, eviction-deletes, and
  invalidate-on-not-found (launchtemplate.go:130-136, 291-305, 120-128).
"""

from __future__ import annotations

import base64
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..models import labels as L
from ..models.instancetype import InstanceType
from ..models.pod import Taint

# ---------------------------------------------------------------------------
# image families
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Image:
    image_id: str
    arch: str
    accelerated: bool = False
    created_at: float = 0.0
    family: str = "standard"


class ImageFamily:
    """Strategy interface (amifamily/resolver.go AMIFamily analog)."""

    name = "base"

    def default_images(self) -> List[Image]:
        raise NotImplementedError

    def bootstrap_script(
        self,
        cluster_name: str,
        labels: Dict[str, str],
        taints: Sequence[Taint],
        kubelet_flags: Dict[str, str],
        custom_userdata: str = "",
        cluster_endpoint: str = "",
    ) -> str:
        raise NotImplementedError


class StandardFamily(ImageFamily):
    """eks/AL2-like: shell bootstrap merged with custom userdata via MIME
    multipart (eksbootstrap.go:165-263 semantics)."""

    name = "standard"

    def default_images(self) -> List[Image]:
        return [
            Image("img-standard-amd64", L.ARCH_AMD64, created_at=2.0, family="standard"),
            Image("img-standard-arm64", L.ARCH_ARM64, created_at=2.0, family="standard"),
            Image("img-standard-gpu", L.ARCH_AMD64, accelerated=True, created_at=2.0, family="standard"),
        ]

    def bootstrap_script(self, cluster_name, labels, taints, kubelet_flags,
                         custom_userdata="", cluster_endpoint="") -> str:
        label_arg = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        taint_arg = ",".join(f"{t.key}={t.value}:{t.effect}" for t in taints)
        flags = " ".join(f"--{k}={v}" for k, v in sorted(kubelet_flags.items()))
        endpoint_arg = (
            f" --apiserver-endpoint '{cluster_endpoint}'" if cluster_endpoint else ""
        )
        script = (
            "#!/bin/bash\n"
            f"/etc/node/bootstrap.sh '{cluster_name}'{endpoint_arg} "
            f"--kubelet-extra-args '--node-labels={label_arg} "
            f"--register-with-taints={taint_arg} {flags}'\n"
        )
        if not custom_userdata:
            return script
        # MIME multipart merge: custom part first, bootstrap last
        boundary = "//"
        return (
            f'MIME-Version: 1.0\nContent-Type: multipart/mixed; boundary="{boundary}"\n\n'
            f"--{boundary}\nContent-Type: text/x-shellscript; charset=\"us-ascii\"\n\n"
            f"{custom_userdata}\n"
            f"--{boundary}\nContent-Type: text/x-shellscript; charset=\"us-ascii\"\n\n"
            f"{script}\n--{boundary}--\n"
        )


class TomlFamily(ImageFamily):
    """bottlerocket-like: structured TOML config; custom userdata must itself
    be TOML and is merged key-wise (bottlerocketsettings.go semantics)."""

    name = "toml"

    def default_images(self) -> List[Image]:
        return [
            Image("img-toml-amd64", L.ARCH_AMD64, created_at=1.0, family="toml"),
            Image("img-toml-arm64", L.ARCH_ARM64, created_at=1.0, family="toml"),
        ]

    def bootstrap_script(self, cluster_name, labels, taints, kubelet_flags,
                         custom_userdata="", cluster_endpoint="") -> str:
        lines = ["[settings.kubernetes]", f'cluster-name = "{cluster_name}"']
        if cluster_endpoint:
            lines.append(f'api-server = "{cluster_endpoint}"')
        if custom_userdata:
            lines.append(custom_userdata.strip())
        lines.append("[settings.kubernetes.node-labels]")
        for k, v in sorted(labels.items()):
            lines.append(f'"{k}" = "{v}"')
        if taints:
            lines.append("[settings.kubernetes.node-taints]")
            for t in taints:
                lines.append(f'"{t.key}" = "{t.value}:{t.effect}"')
        return "\n".join(lines) + "\n"


class CustomFamily(ImageFamily):
    """Pass-through userdata; requires explicit image selectors
    (amifamily/custom.go)."""

    name = "custom"

    def default_images(self) -> List[Image]:
        return []

    def bootstrap_script(self, cluster_name, labels, taints, kubelet_flags,
                         custom_userdata="", cluster_endpoint="") -> str:
        return custom_userdata


_FAMILIES = {f.name: f for f in (StandardFamily(), TomlFamily(), CustomFamily())}


def get_family(name: str) -> ImageFamily:
    """resolver.go:143-154 GetAMIFamily analog (defaults to standard)."""
    return _FAMILIES.get(name, _FAMILIES["standard"])


# ---------------------------------------------------------------------------
# node template
# ---------------------------------------------------------------------------


@dataclass
class BlockDevice:
    device_name: str = "/dev/xvda"
    size_gib: float = 20.0
    volume_type: str = "gp3"
    encrypted: bool = True


@dataclass
class NodeTemplate:
    """AWSNodeTemplate analog: how to build nodes for a provisioner."""

    name: str = "default"
    image_family: str = "standard"
    image_selector: Dict[str, str] = field(default_factory=dict)  # tag/id selectors
    subnet_selector: Dict[str, str] = field(default_factory=dict)
    security_group_selector: Dict[str, str] = field(default_factory=dict)
    user_data: str = ""
    instance_profile: str = ""
    block_devices: List[BlockDevice] = field(default_factory=list)
    # pre-built launch template override; excludes the fields it replaces
    # (provider_validation.go:64-84)
    launch_template_name: Optional[str] = None
    metadata_http_tokens: str = "required"
    metadata_http_endpoint: str = "enabled"
    metadata_hop_limit: int = 2
    tags: Dict[str, str] = field(default_factory=dict)
    detailed_monitoring: bool = False
    # status (filled by the nodetemplate controller)
    status_subnets: List[str] = field(default_factory=list)
    status_security_groups: List[str] = field(default_factory=list)
    status_images: List[Image] = field(default_factory=list)

    def validate(self) -> List[str]:
        """Full spec validation; single source of truth lives in
        webhooks.validate_node_template_spec."""
        from ..webhooks import validate_node_template_spec

        return validate_node_template_spec(self)


# ---------------------------------------------------------------------------
# image resolution
# ---------------------------------------------------------------------------


def resolve_images(
    template: NodeTemplate,
    available_images: Sequence[Image] = (),
) -> List[Image]:
    """Selector-based discovery (ami.go:158-230) or family-alias defaults
    (ami.go:135-149), newest-first (ami.go:232-241).

    The alias path has SSM semantics: it returns only the *current* image per
    (arch, accelerated) variant — when a newer image is published into the
    pool, older ones drop out of the resolved set, which is exactly what the
    drift check keys off (cloudprovider.go:258-287)."""
    family = get_family(template.image_family)
    if template.image_selector:
        ids = {
            one.strip()
            for k, v in template.image_selector.items()
            if k in ("id", "ids")
            for one in str(v).split(",")
        }
        pool = list(available_images) or family.default_images()
        picked = [i for i in pool if not ids or i.image_id in ids]
    else:
        pool = [i for i in available_images if i.family == family.name]
        if not pool:
            pool = family.default_images()
        newest: Dict[Tuple[str, bool], Image] = {}
        for img in pool:
            key = (img.arch, img.accelerated)
            cur = newest.get(key)
            if cur is None or img.created_at > cur.created_at:
                newest[key] = img
        picked = list(newest.values())
    return sorted(picked, key=lambda i: (-i.created_at, i.image_id))


def images_for_instance_type(images: Sequence[Image], it: InstanceType) -> List[Image]:
    """All resolved images mapping to this type's arch/accelerator variant
    (ami.go:99-133 MapInstanceTypes analog).  The drift check tests membership
    of the instance's image in this set (cloudprovider.go:258-287)."""
    arch = it.labels().get(L.ARCH, L.ARCH_AMD64)
    accelerated = L.RESOURCE_GPU in it.capacity
    exact = [i for i in images if i.arch == arch and i.accelerated == accelerated]
    if exact:
        return exact
    return [i for i in images if i.arch == arch]  # fall back on arch alone


def image_for_instance_type(images: Sequence[Image], it: InstanceType) -> Optional[Image]:
    """Pick the (newest) image matching the type's arch/accelerator."""
    mapped = images_for_instance_type(images, it)
    return mapped[0] if mapped else None


# ---------------------------------------------------------------------------
# launch templates
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LaunchTemplate:
    name: str
    image_id: str
    user_data_b64: str
    instance_profile: str
    security_groups: Tuple[str, ...]
    tags: Tuple[Tuple[str, str], ...]


class LaunchTemplateProvider:
    """Hash-keyed ensure-exists cache (launchtemplate.go:54-317)."""

    def __init__(
        self,
        cluster_name: str = "sim",
        max_templates: int = 256,
        cluster_endpoint: str = "",
        default_instance_profile: str = "",
    ) -> None:
        self.cluster_name = cluster_name
        self.cluster_endpoint = cluster_endpoint          # settings.go:44
        self.default_instance_profile = default_instance_profile  # settings.go:46
        self.max_templates = max_templates
        self._cache: Dict[str, LaunchTemplate] = {}
        self.created: List[str] = []
        self.deleted: List[str] = []

    @staticmethod
    def _hash(*parts: str) -> str:
        return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]

    def ensure(
        self,
        template: NodeTemplate,
        image: Image,
        labels: Dict[str, str],
        taints: Sequence[Taint],
        kubelet_flags: Optional[Dict[str, str]] = None,
    ) -> LaunchTemplate:
        family = get_family(template.image_family)
        userdata = family.bootstrap_script(
            self.cluster_name, labels, taints, kubelet_flags or {},
            template.user_data, cluster_endpoint=self.cluster_endpoint,
        )
        # the template's own profile wins; the settings-wide default fills
        # the gap (settings.go defaultInstanceProfile semantics)
        profile = template.instance_profile or self.default_instance_profile
        key = self._hash(
            image.image_id, userdata, profile,
            ",".join(sorted(template.status_security_groups)),
            str(sorted(template.tags.items())),
        )
        got = self._cache.get(key)
        if got is not None:
            return got
        lt = LaunchTemplate(
            name=f"karpenter.k8s.tpu/{key}",
            image_id=image.image_id,
            user_data_b64=base64.b64encode(userdata.encode()).decode(),
            instance_profile=profile,
            security_groups=tuple(sorted(template.status_security_groups)),
            tags=tuple(sorted(template.tags.items())),
        )
        if len(self._cache) >= self.max_templates:
            # evict-deletes (launchtemplate.go:291-305)
            evict_key = next(iter(self._cache))
            self.deleted.append(self._cache.pop(evict_key).name)
        self._cache[key] = lt
        self.created.append(lt.name)
        return lt

    def invalidate(self, name: str) -> None:
        """Drop a template reported not-found by the cloud
        (launchtemplate.go:120-128); next ensure() recreates it."""
        for key, lt in list(self._cache.items()):
            if lt.name == name:
                del self._cache[key]

    def hydrate(self, existing: Sequence[LaunchTemplate]) -> None:
        """Warm the cache from the cloud on leadership (launchtemplate.go:272-289)."""
        for lt in existing:
            key = lt.name.rsplit("/", 1)[-1]
            self._cache.setdefault(key, lt)

    def __len__(self) -> int:
        return len(self._cache)
