"""Fake cloud provider — the test double the whole tier-1 strategy rests on.

Ports the *semantics* of pkg/fake/ec2api.go (584 LoC of fakes; SURVEY.md §4):
in-memory instances, call capture, error/ICE injection per offering, eventual
consistency (instances invisible for the first N get/list calls, mirroring the
DescribeInstances retry loop at instance.go:99-107), and capacity tracking so
tests can assert exactly what got launched.
"""

from __future__ import annotations

import itertools
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..models import labels as L
from ..models.instancetype import InstanceType, specialize_for_kubelet
from ..models.machine import Machine
from ..models.provisioner import Provisioner
from ..utils.clock import Clock
from .base import (
    CloudProvider,
    InsufficientCapacityError,
    MachineNotFoundError,
)
from .launchpath import select_launch_types
from .templates import (
    Image,
    LaunchTemplateProvider,
    NodeTemplate,
    images_for_instance_type,
    resolve_images,
)

_instance_counter = itertools.count()


@dataclass
class FakeInstance:
    provider_id: str
    machine: Machine
    created_at: float
    visible_after_calls: int = 0  # eventual-consistency countdown
    terminated: bool = False
    drifted: bool = False
    tags: Dict[str, str] = field(default_factory=dict)


class FakeCloudProvider(CloudProvider):
    def __init__(
        self,
        instance_types: Sequence[InstanceType],
        clock: Optional[Clock] = None,
        eventual_consistency_calls: int = 0,
    ) -> None:
        self.instance_types = list(instance_types)
        self.clock = clock or Clock()
        self.eventual_consistency_calls = eventual_consistency_calls
        self.instances: Dict[str, FakeInstance] = {}
        # image catalog + node templates back the real drift check
        # (cloudprovider.go:258-287): creates stamp machine.image_id from the
        # template's currently-resolved images; publishing a newer image later
        # makes existing machines drift.
        self.templates: Dict[str, NodeTemplate] = {"default": NodeTemplate()}
        self.images: List[Image] = []
        # named pre-built launch templates (launch_template_name override):
        # LT name -> image id it launches with
        self.launch_templates: Dict[str, str] = {}
        self.fleet_calls = 0  # one per create_fleet round trip
        self.ice_offerings: Set[Tuple[str, str, str]] = set()  # (type, zone, ct)
        self.create_calls: List[Machine] = []
        self.delete_calls: List[str] = []
        self.launch_selections: List = []  # LaunchSelection per create (call capture)
        self.next_error: Optional[Exception] = None
        self.allow_creates = True
        # seconds until a launched node registers + passes readiness; >0
        # engages the deprovisioning wait-ready machine for replacements
        self.node_ready_delay: float = 0.0
        # global settings consumed at launch (configure_settings); the
        # launch-template flow (create -> ensure LT -> fleet) consumes
        # clusterEndpoint (bootstrap userdata) + defaultInstanceProfile,
        # and owns the single copy of cluster_name (see property below)
        self.launch_template_provider = LaunchTemplateProvider("sim")
        self.default_tags: Dict[str, str] = {}
        self.node_name_convention = "ip-name"

    @property
    def cluster_name(self) -> str:
        # single source of truth: instance tagging and bootstrap userdata
        # must never disagree on the cluster name
        return self.launch_template_provider.cluster_name

    @cluster_name.setter
    def cluster_name(self, value: str) -> None:
        self.launch_template_provider.cluster_name = value

    def configure_settings(self, settings) -> None:
        """settings.go:40-65 consumption: cluster name + default tags flow
        into instance tagging, nodeNameConvention into node naming, cluster
        endpoint + default instance profile into the launch templates."""
        self.default_tags = dict(settings.tags)
        self.node_name_convention = settings.node_name_convention
        ltp = self.launch_template_provider
        ltp.cluster_name = settings.cluster_name
        ltp.cluster_endpoint = settings.cluster_endpoint
        ltp.default_instance_profile = settings.default_instance_profile

    def _node_name(self, seq: int) -> str:
        """Node object name per nodeNameConvention (settings.go:52):
        'ip-name' mirrors EC2 private-DNS naming, 'resource-name' names the
        node after the instance id."""
        if self.node_name_convention == "resource-name":
            return f"i-{seq:017d}"
        # 24 bits of address space: node names key state dicts, so a long
        # simulation must not wrap into duplicate names
        return f"ip-10-{(seq >> 16) & 0xFF}-{(seq >> 8) & 0xFF}-{seq & 0xFF}"

    # ---- test injection ------------------------------------------------
    def inject_ice(self, instance_type: str, zone: str, capacity_type: str) -> None:
        self.ice_offerings.add((instance_type, zone, capacity_type))

    def clear_ice(self) -> None:
        self.ice_offerings.clear()

    def mark_drifted(self, provider_id: str) -> None:
        self.instances[provider_id].drifted = True

    def publish_image(self, image: Image) -> None:
        """Add an image to the catalog (the SSM-alias-update analog: a newer
        image per (family, arch, accel) supersedes the old in resolution)."""
        self.images.append(image)

    def register_launch_template(self, name: str, image_id: str) -> None:
        """Register a pre-built launch template for launch_template_name
        overrides (the user-managed LT the reference launches verbatim)."""
        self.launch_templates[name] = image_id

    # ---- CloudProvider -------------------------------------------------
    def create(self, machine: Machine) -> Machine:
        self.create_calls.append(machine)
        if self.next_error is not None:
            err, self.next_error = self.next_error, None
            raise err
        if not self.allow_creates:
            raise RuntimeError("creates disabled")

        # full reference launch pipeline (filter -> price-sort -> 60-cap ->
        # capacity-type choice), then fleet semantics: walk offerings of the
        # chosen capacity type cheapest-first, skipping ICE'd pools the way
        # CreateFleet's lowest-price strategy tries the next pool
        # (instance.go:83-87,201-259,405-529)
        sel = select_launch_types(machine, self.instance_types)
        machine.launch_warnings = list(sel.warnings)
        self.launch_selections.append(sel)
        choice, iced = self._resolve_fleet(machine, sel)
        if choice is None:
            if iced:
                # every matching pool is ICE'd: surface the cheapest one's
                # coordinates (what a CreateFleet ICE error carries)
                it0, o0 = iced[0]
                raise InsufficientCapacityError(it0.name, o0.zone, o0.capacity_type)
            wanted = sorted(machine.requirements.get(L.INSTANCE_TYPE).values)
            raise InsufficientCapacityError(wanted[0] if wanted else "<any>", "<any>", "<any>")
        it, offering = choice
        # ICE'd pools skipped on the way to success still get reported so the
        # controller can blacklist them (instance.go:395-401)
        machine.ice_errors = [(i.name, o.zone, o.capacity_type) for i, o in iced]

        seq = next(_instance_counter)
        pid = f"fake://{it.name}/{seq}"
        machine.provider_id = pid
        machine.node_name = self._node_name(seq)
        machine.image_id = self._image_for(machine.node_template, it)
        machine.instance_type = it.name
        machine.zone = offering.zone
        machine.capacity_type = offering.capacity_type
        machine.price = offering.price
        # the machine's kubeletConfiguration changes real node capacity
        # (instancetype.go:226-340): density + reservation overrides are
        # applied here exactly as the solver's candidate rows assumed
        it_eff = specialize_for_kubelet(it, machine.kubelet)
        machine.capacity = dict(it_eff.capacity)
        machine.allocatable = dict(it_eff.allocatable)
        machine.launched_at = self.clock.now()
        tmpl = self.templates.get(machine.node_template)
        if tmpl is not None and tmpl.launch_template_name is None and machine.image_id:
            # the reference ensures a launch template before CreateFleet
            # (launchtemplate.go EnsureAll): this is where clusterEndpoint
            # (bootstrap userdata) and defaultInstanceProfile are consumed.
            # Keyed on the PRE-resolution labels (the provisioner's static
            # set) — zone/type/capacity-type are fleet overrides, not
            # userdata, so LT cardinality stays per (template, image), not
            # per (catalog x zones x capacity-types)
            lt = self.launch_template_provider.ensure(
                tmpl,
                Image(machine.image_id, it.labels().get(L.ARCH, "")),
                labels=machine.labels, taints=machine.taints,
                kubelet_flags=(
                    machine.kubelet.bootstrap_flags() if machine.kubelet else None
                ),
            )
            machine.launch_template = lt.name
        machine.labels = {
            **machine.labels,
            **it.labels(),
            L.ZONE: offering.zone,
            L.CAPACITY_TYPE: offering.capacity_type,
            L.INSTANCE_TYPE: it.name,
            L.PROVISIONER_NAME: machine.provisioner,
        }
        self.instances[pid] = FakeInstance(
            provider_id=pid,
            machine=machine,
            created_at=self.clock.now(),
            visible_after_calls=self.eventual_consistency_calls,
            # tag layering: settings-wide defaults, then the template's own,
            # then the karpenter ownership/attribution tags LAST — user tags
            # must never override them (instance.go:216-218; settings tag
            # validation also rejects the reserved prefixes)
            tags={
                **self.default_tags,
                **(tmpl.tags if tmpl else {}),
                f"kubernetes.io/cluster/{self.cluster_name}": "owned",
                "karpenter.sh/provisioner-name": machine.provisioner,
            },
        )
        return machine

    def _resolve_fleet(self, machine: Machine, sel):
        """Fleet launch over the selected types: cheapest non-ICE'd pool of
        the chosen capacity type wins; ICE'd pools encountered cheaper than
        the winner are collected (price-ordered) for blacklist feedback."""
        reqs = machine.requirements
        zone_req = reqs.get(L.ZONE)
        pools = []
        for it in sel.instance_types:
            for o in it.offerings:
                if not o.available or o.capacity_type != sel.capacity_type:
                    continue
                if not zone_req.contains(o.zone):
                    continue
                pools.append((it, o))
        pools.sort(key=lambda p: (p[1].price, p[0].name, p[1].zone))
        iced = []
        for it, o in pools:
            if (it.name, o.zone, o.capacity_type) in self.ice_offerings:
                iced.append((it, o))
                continue
            return (it, o), iced
        return None, iced

    def delete(self, machine: Machine) -> None:
        self.delete_calls.append(machine.provider_id)
        inst = self.instances.get(machine.provider_id)
        if inst is None or inst.terminated:
            raise MachineNotFoundError(machine.provider_id)
        inst.terminated = True

    def get(self, provider_id: str) -> Machine:
        inst = self.instances.get(provider_id)
        if inst is None or inst.terminated:
            raise MachineNotFoundError(provider_id)
        if inst.visible_after_calls > 0:
            inst.visible_after_calls -= 1
            raise MachineNotFoundError(f"{provider_id} (eventual consistency)")
        return inst.machine

    def list(self) -> List[Machine]:
        out = []
        for inst in self.instances.values():
            if inst.terminated:
                continue
            if inst.visible_after_calls > 0:
                inst.visible_after_calls -= 1
                continue
            out.append(inst.machine)
        return out

    def get_instance_types(self, provisioner: Optional[Provisioner] = None) -> List[InstanceType]:
        return list(self.instance_types)

    def create_fleet(self, machines: Sequence[Machine]) -> List[object]:
        """Bulk create: ONE fleet round trip launches every machine
        (CreateFleet with summed capacity, createfleet.go fan-out).  Returns
        one slot per machine — the launched Machine, or the per-pool error —
        so callers see partial fulfilment exactly like a real fleet."""
        self.fleet_calls += 1
        out: List[object] = []
        for m in machines:
            try:
                out.append(self.create(m))
            # ktlint: allow[KT005] fleet partial-fulfilment contract: the
            # per-pool error IS the result slot (createfleet.go semantics)
            except Exception as err:
                out.append(err)
        return out

    def _image_for(self, template_name: str, it: InstanceType) -> str:
        tmpl = self.templates.get(template_name)
        if tmpl is None:
            return ""
        if tmpl.launch_template_name is not None:
            # user-managed LT launched verbatim: the image is whatever the
            # named template carries (instance.go launch-template override)
            return self.launch_templates.get(tmpl.launch_template_name, "")
        images = resolve_images(tmpl, self.images)
        mapped = images_for_instance_type(images, it)
        return mapped[0].image_id if mapped else ""

    def is_machine_drifted(self, machine: Machine) -> bool:
        """Real image drift (cloudprovider.go:233-251 + isAMIDrifted
        :258-287): the instance's image must be among the images the node
        template *currently* resolves for its instance type.  The injected
        `drifted` flag remains as a test escape hatch."""
        inst = self.instances.get(machine.provider_id)
        if inst is None:
            return False
        if inst.drifted:
            return True
        if not machine.image_id or not machine.instance_type:
            return False  # drift not detectable without a recorded image
        tmpl = self.templates.get(machine.node_template)
        if tmpl is None:
            return False
        if tmpl.launch_template_name is not None:
            # LT override: drift when the user repointed the named template
            # at a different image
            current = self.launch_templates.get(tmpl.launch_template_name, "")
            return bool(current) and machine.image_id != current
        it = next(
            (t for t in self.instance_types if t.name == machine.instance_type), None
        )
        if it is None:
            return False
        images = resolve_images(tmpl, self.images)
        mapped = {i.image_id for i in images_for_instance_type(images, it)}
        return machine.image_id not in mapped

    def name(self) -> str:
        return "fake"
