"""Batched cloud boundary — request coalescing at the provider edge.

Mirrors pkg/batcher (batcher.go:29-171 generic coalescer; createfleet.go,
describeinstances.go, terminateinstances.go executors): concurrent cloud
calls are hash-bucketed, the first caller in a bucket waits a short idle
window for peers to join, then ONE backend round trip serves the whole
bucket with per-caller results fanned back out.

- ``create``: bucketed by machine spec (provisioner, template, requirements)
  — the CreateFleet fan-out: identical specs share one fleet request and each
  requester receives its own instance (createfleet.go semantics).
- ``get``: all concurrent gets merge into one describe (describeinstances.go)
  resolved via a single ``inner.list()``; absent ids map back to per-caller
  ``MachineNotFoundError``.
- ``delete``: concurrent deletes merge into one terminate round trip
  (terminateinstances.go).

The decorator sits *below* the metrics decorator, like the reference's
batcher sits inside the AWS provider under core's metrics.Decorate.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..batcher import ThreadCoalescer
from ..models.instancetype import InstanceType
from ..models.machine import Machine
from ..models.provisioner import Provisioner
from .base import CloudProvider, MachineNotFoundError

#: outcome of one request inside a batch: ("ok", value) | ("err", exception)
_Outcome = Tuple[str, object]


class BatchedCloud(CloudProvider):
    """Coalescing decorator over any CloudProvider."""

    def __init__(self, inner: CloudProvider, idle_seconds: float = 0.002) -> None:
        self.inner = inner
        self.creates = ThreadCoalescer(self._do_creates, idle_seconds)
        self.describes = ThreadCoalescer(self._do_describes, idle_seconds)
        self.terminates = ThreadCoalescer(self._do_terminates, idle_seconds)

    # ---- batch executors: one backend round trip each -------------------
    def _do_creates(self, machines: List[Machine]) -> List[_Outcome]:
        bulk = getattr(self.inner, "create_fleet", None)
        if bulk is not None:
            # one fleet round trip; per-slot Machine or error fans out
            return [
                ("err", slot) if isinstance(slot, Exception) else ("ok", slot)
                for slot in bulk(machines)
            ]
        # provider without a bulk hook: coalescing only dedups the window,
        # each create is still its own round trip
        out: List[_Outcome] = []
        for m in machines:
            try:
                out.append(("ok", self.inner.create(m)))
            # ktlint: allow[KT005] per-machine fan-out contract: each slot
            # carries its own outcome and the caller re-raises its slot
            except Exception as err:
                out.append(("err", err))
        return out

    def _do_describes(self, pids: List[str]) -> List[_Outcome]:
        try:
            by_id = {m.provider_id: m for m in self.inner.list()}
        # ktlint: allow[KT005] a failed list fans the error to every
        # coalesced describe; each caller re-raises its slot
        except Exception as err:
            return [("err", err)] * len(pids)
        out: List[_Outcome] = []
        for pid in pids:
            m = by_id.get(pid)
            if m is None:
                out.append(("err", MachineNotFoundError(pid)))
            else:
                out.append(("ok", m))
        return out

    def _do_terminates(self, machines: List[Machine]) -> List[_Outcome]:
        out: List[_Outcome] = []
        for m in machines:
            try:
                self.inner.delete(m)
                out.append(("ok", None))
            # ktlint: allow[KT005] per-machine fan-out contract, as above
            except Exception as err:
                out.append(("err", err))
        return out

    # ---- CloudProvider ---------------------------------------------------
    def create(self, machine: Machine) -> Machine:
        key = (
            "create", machine.provisioner, machine.node_template,
            repr(machine.requirements),  # spec-hash bucket (createfleet.go)
        )
        return self.creates.call(key, machine)

    def get(self, provider_id: str) -> Machine:
        return self.describes.call("describe", provider_id)

    def delete(self, machine: Machine) -> None:
        return self.terminates.call("terminate", machine)

    def list(self) -> List[Machine]:
        return self.inner.list()

    def get_instance_types(self, provisioner: Optional[Provisioner] = None) -> List[InstanceType]:
        return self.inner.get_instance_types(provisioner)

    def is_machine_drifted(self, machine: Machine) -> bool:
        return self.inner.is_machine_drifted(machine)

    def link(self, machine: Machine) -> Machine:
        return self.inner.link(machine)

    def name(self) -> str:
        return self.inner.name()

    def liveness(self) -> bool:
        return self.inner.liveness()

    def configure_settings(self, settings) -> None:
        # explicit forward: the base class's no-op default would otherwise
        # shadow __getattr__ delegation and strand settings at this layer
        self.inner.configure_settings(settings)

    def __getattr__(self, name: str):
        # transparent for provider-specific surface (test injection hooks,
        # node_ready_delay, instance tables) — only missing attrs land here
        return getattr(self.inner, name)
