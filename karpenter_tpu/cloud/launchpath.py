"""Launch-path instance-type selection — the reference's Create pipeline.

Mirrors pkg/cloudprovider/instance.go's launch path semantics:

- exotic-type filtering (GPU/accelerator/metal types dropped when generic
  types suffice) — instance.go:505-529 filterExoticInstanceTypes
- unwanted-spot filtering on mixed-capacity launches (spot types whose
  cheapest offering beats no on-demand option) — instance.go:481-503
- price ordering by cheapest requirement-satisfying offering —
  instance.go:421-438 orderInstanceTypesByPrice
- truncation to MAX_INSTANCE_TYPES (60) — cloudprovider.go:64-67, applied
  instance.go:85-87
- capacity-type choice: spot iff a spot offering is reachable —
  instance.go:405-419 getCapacityType
- on-demand-fallback flexibility warning below 5 types —
  instance.go:52,261-281 checkODFallback

The TPU solver pins (type, zone, capacity-type) per machine, so controller
launches degenerate to a 1-type list and this pipeline is a no-op for them;
flexible machines (adoption, replacement launches, direct API users) get the
full fleet semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..models import labels as L
from ..models.instancetype import InstanceType, Offering
from ..models.machine import Machine
from ..models.requirements import Requirements

#: Max instance types handed to one fleet launch (cloudprovider.go:64-67).
MAX_INSTANCE_TYPES = 60

#: Below this many types, falling back to on-demand while flexible to spot
#: risks insufficient-capacity errors (instance.go:52).
FLEXIBILITY_THRESHOLD = 5

_EXOTIC_RESOURCES = (L.RESOURCE_GPU,)


@dataclass
class LaunchSelection:
    """Outcome of the selection pipeline, pre-launch."""

    instance_types: List[InstanceType]
    capacity_type: str
    warnings: List[str] = field(default_factory=list)


def _offerings_ok(it: InstanceType, reqs: Requirements) -> List[Offering]:
    """Available offerings of ``it`` satisfying the machine requirements."""
    zone_req = reqs.get(L.ZONE)
    ct_req = reqs.get(L.CAPACITY_TYPE)
    return [
        o for o in it.offerings
        if o.available and zone_req.contains(o.zone) and ct_req.contains(o.capacity_type)
    ]


def _cheapest(it: InstanceType, reqs: Requirements) -> float:
    offs = _offerings_ok(it, reqs)
    return min((o.price for o in offs), default=float("inf"))


def filter_exotic(instance_types: Sequence[InstanceType]) -> List[InstanceType]:
    """Drop GPU/accelerator/metal types when generic types remain
    (instance.go:505-529): a flexible request should not land on an
    expensive accelerator node just because one fits."""
    generic = []
    for it in instance_types:
        if "metal" in it.requirements.get(L.INSTANCE_SIZE).values:
            continue
        if any(it.capacity.get(r, 0.0) > 0 for r in _EXOTIC_RESOURCES):
            continue
        generic.append(it)
    return generic if generic else list(instance_types)


def is_mixed_capacity_launch(
    reqs: Requirements, instance_types: Sequence[InstanceType]
) -> bool:
    """Both spot and on-demand could launch (instance.go:455-479)."""
    ct_req = reqs.get(L.CAPACITY_TYPE)
    if not (ct_req.contains(L.CAPACITY_TYPE_SPOT) and ct_req.contains(L.CAPACITY_TYPE_ON_DEMAND)):
        return False
    has_spot = has_od = False
    for it in instance_types:
        for o in _offerings_ok(it, reqs):
            if o.capacity_type == L.CAPACITY_TYPE_SPOT:
                has_spot = True
            else:
                has_od = True
    return has_spot and has_od


def filter_unwanted_spot(
    instance_types: Sequence[InstanceType], reqs: Requirements
) -> List[InstanceType]:
    """Drop types whose cheapest offering is pricier than the cheapest
    on-demand type that would work (instance.go:481-503): prevents a large
    expensive spot instance beating a small sufficient on-demand one."""
    cheapest_od = float("inf")
    for it in instance_types:
        for o in _offerings_ok(it, reqs):
            if o.capacity_type == L.CAPACITY_TYPE_ON_DEMAND and o.price < cheapest_od:
                cheapest_od = o.price
    out = []
    for it in instance_types:
        price = _cheapest(it, reqs)
        if price != float("inf") and price <= cheapest_od:
            out.append(it)
    return out


def order_by_price(
    instance_types: Sequence[InstanceType], reqs: Requirements
) -> List[InstanceType]:
    """Cheapest requirement-satisfying offering first; name tiebreak
    (instance.go:421-438)."""
    return sorted(instance_types, key=lambda it: (_cheapest(it, reqs), it.name))


def choose_capacity_type(
    reqs: Requirements, instance_types: Sequence[InstanceType]
) -> str:
    """Spot iff the requirements admit spot and a spot offering is reachable;
    on-demand otherwise (instance.go:405-419)."""
    if reqs.get(L.CAPACITY_TYPE).contains(L.CAPACITY_TYPE_SPOT):
        for it in instance_types:
            if any(o.capacity_type == L.CAPACITY_TYPE_SPOT for o in _offerings_ok(it, reqs)):
                return L.CAPACITY_TYPE_SPOT
    return L.CAPACITY_TYPE_ON_DEMAND


def select_launch_types(
    machine: Machine,
    instance_types: Sequence[InstanceType],
    max_types: int = MAX_INSTANCE_TYPES,
) -> LaunchSelection:
    """The full Create-path pipeline: requirement prefilter -> exotic filter
    -> unwanted-spot filter -> price sort -> truncate -> capacity-type choice
    -> flexibility check (instance.go:83-87 + checkODFallback)."""
    from ..models.resources import fits

    reqs = machine.requirements
    type_req = reqs.get(L.INSTANCE_TYPE)
    types = [
        it for it in instance_types
        if type_req.contains(it.name) and _offerings_ok(it, reqs)
        and fits(machine.resource_requests, it.allocatable)
    ]
    types = filter_exotic(types)
    if is_mixed_capacity_launch(reqs, types):
        types = filter_unwanted_spot(types, reqs)
    types = order_by_price(types, reqs)
    if len(types) > max_types:
        types = types[:max_types]

    capacity_type = choose_capacity_type(reqs, types)
    warnings: List[str] = []
    if (
        capacity_type == L.CAPACITY_TYPE_ON_DEMAND
        and reqs.get(L.CAPACITY_TYPE).contains(L.CAPACITY_TYPE_SPOT)
        and len(types) < FLEXIBILITY_THRESHOLD
    ):
        warnings.append(
            f"at least {FLEXIBILITY_THRESHOLD} instance types are recommended when "
            f"flexible to spot but requesting on-demand; this request has {len(types)}"
        )
    return LaunchSelection(instance_types=types, capacity_type=capacity_type,
                           warnings=warnings)
