"""The provider-neutral CloudProvider boundary.

Mirrors core ``cloudprovider.CloudProvider`` exactly (asserted implemented at
/root/reference/pkg/cloudprovider/cloudprovider.go:74; methods Create :130,
Link :155, List :165, Get :181, GetInstanceTypes :206, Delete :223,
IsMachineDrifted :233, Name :254).  The solver sits behind this boundary the
same way EC2 does in the reference: controllers never touch provider
internals.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import List, Optional, Sequence

from ..models.instancetype import InstanceType
from ..models.machine import Machine
from ..models.provisioner import Provisioner


class CloudProviderError(Exception):
    pass


class InsufficientCapacityError(CloudProviderError):
    """ICE — maps to the unfulfillable-capacity error codes taxonomy
    (pkg/errors/errors.go:40-46); callers mark the offering unavailable."""

    def __init__(self, instance_type: str, zone: str, capacity_type: str) -> None:
        self.instance_type = instance_type
        self.zone = zone
        self.capacity_type = capacity_type
        super().__init__(f"insufficient capacity: {capacity_type}:{instance_type}:{zone}")


class MachineNotFoundError(CloudProviderError):
    pass


class CloudProvider(abc.ABC):
    def configure_settings(self, settings) -> None:
        """Push the hot-reloadable global settings into the provider
        (settings.go:40-65 are consumed by the AWS layer in the reference:
        cluster name/endpoint into bootstrap, default instance profile and
        tags into launches, node-name convention into node naming).
        Default: no-op for providers that don't consume them."""

    @abc.abstractmethod
    def create(self, machine: Machine) -> Machine:
        """Launch an instance satisfying the machine's requirements; returns
        the machine with status (provider_id, instance_type, zone, ...)."""

    @abc.abstractmethod
    def delete(self, machine: Machine) -> None:
        ...

    @abc.abstractmethod
    def get(self, provider_id: str) -> Machine:
        ...

    @abc.abstractmethod
    def list(self) -> List[Machine]:
        ...

    @abc.abstractmethod
    def get_instance_types(self, provisioner: Optional[Provisioner] = None) -> List[InstanceType]:
        ...

    @abc.abstractmethod
    def is_machine_drifted(self, machine: Machine) -> bool:
        ...

    def link(self, machine: Machine) -> Machine:
        """Adopt an orphaned instance (migration path, cloudprovider.go:155)."""
        return self.get(machine.provider_id)

    def name(self) -> str:
        return "tpu-sim"

    def liveness(self) -> bool:
        return True
