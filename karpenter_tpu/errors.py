"""Cloud error taxonomy (pkg/errors/errors.go:31-79 analog).

Classifies provider errors so controllers react correctly: not-found is
swallowed on delete paths, unfulfillable-capacity feeds the ICE cache, and
everything else propagates.
"""

from __future__ import annotations

from .cloud.base import (
    CloudProviderError,
    InsufficientCapacityError,
    MachineNotFoundError,
)

# unfulfillable-capacity classes beyond plain ICE (errors.go:40-46)
UNFULFILLABLE_REASONS = (
    "InsufficientInstanceCapacity",
    "MaxSpotInstanceCountExceeded",
    "VcpuLimitExceeded",
    "UnfulfillableCapacity",
    "Unsupported",
)


def is_not_found(err: Exception) -> bool:
    return isinstance(err, MachineNotFoundError)


def is_unfulfillable_capacity(err: Exception) -> bool:
    if isinstance(err, InsufficientCapacityError):
        return True
    return any(r in str(err) for r in UNFULFILLABLE_REASONS)


def ignore_not_found(err: Exception) -> None:
    """Re-raise unless it's a not-found (the lo.Must/IgnoreNotFound idiom)."""
    if not is_not_found(err):
        raise err
