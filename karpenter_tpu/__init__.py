"""karpenter_tpu — a TPU-native node-provisioning framework.

Re-implements the capabilities of Karpenter (reference at /root/reference,
see SURVEY.md) with the scheduling core — first-fit-decreasing bin-packing and
the consolidation repack search — expressed as vectorized constraint
satisfaction over a (pod-groups x node-candidates x topology-domains) tensor,
compiled by JAX/XLA for TPU.
"""

__version__ = "0.1.0"
