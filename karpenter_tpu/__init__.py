"""karpenter_tpu — a TPU-native node-provisioning framework.

Re-implements the capabilities of Karpenter (reference at /root/reference,
see SURVEY.md) with the scheduling core — first-fit-decreasing bin-packing and
the consolidation repack search — expressed as vectorized constraint
satisfaction over a (pod-groups x node-candidates x topology-domains) tensor,
compiled by JAX/XLA for TPU.
"""

__version__ = "0.1.0"

# Honor an explicit JAX_PLATFORMS env contract at the config layer.  The
# deployment image's sitecustomize force-registers the axon TPU plugin even
# when JAX_PLATFORMS=cpu is exported, so the env var alone doesn't stop
# jax.devices() from initializing (and possibly hanging on) the TPU tunnel;
# the config update does.  Only applied when the operator set the var.
import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception as _e:  # pin didn't apply: say so, loudly — a silent
        import warnings as _warnings  # drop re-exposes the TPU-tunnel hang

        _warnings.warn(
            f"karpenter_tpu: could not apply JAX_PLATFORMS="
            f"{_os.environ['JAX_PLATFORMS']} at the jax config layer ({_e!r}); "
            "accelerator plugins may still initialize",
            RuntimeWarning,
        )

# Lock-discipline sanitizer (docs/ANALYSIS.md): KT_SANITIZE=1 wraps the
# thread-sensitive solver-path classes in lock-assertion proxies that raise
# on cross-thread re-entrancy.  `make battletest` exports it; production
# leaves it off.
if _os.environ.get("KT_SANITIZE") == "1":
    from .analysis import sanitize as _sanitize

    _sanitize.install()
