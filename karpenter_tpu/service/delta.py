"""Delta serving — the session-stateful side of warm-start over the wire.

PR 6 made steady-state reconcile a sub-millisecond incremental update
(``solver/warmstart.delta_solve``), but every gRPC ``Solve`` still
re-shipped the full cluster and re-solved from scratch.  This module holds
the server-side session state that closes that gap (ISSUE 10; the serving
protocol itself lives in ``service/server.py`` ``SolvePipeline``
``_dispatch_delta`` and the client facade in ``service/client.py``
``DeltaSession``):

- :class:`DeltaSessionTable` — a bounded, TTL-evicted table of live
  warm-start chains, one per client session: each :class:`SessionEntry`
  carries the previous :class:`~karpenter_tpu.solver.types.SolveResult`
  (whose ``_warmstart_meta`` IS the incremental chain), the catalog the
  chain was packed against, and the epoch counter the wire protocol acks.
- :class:`DeltaReply` — the dispatcher-built, DETACHED response view: the
  session chain is mutated by the next delta the moment the dispatcher
  moves on, so everything the RPC thread encodes is snapshotted here
  first (O(delta) per incremental step; O(cluster) only on the rare
  establish/reseed/full-shaped replies).
- :class:`DeltaSessionUnknown` — the typed "no live chain for your
  (session, epoch)" outcome; the wire maps it to
  ``session_state="unknown"`` and the client re-establishes with ONE full
  solve (never a retry loop, never silent divergence).

Epoch contract: the server acks ``epoch`` after applying each step; a
client must send ``base_epoch`` equal to the last ack.  Any mismatch —
lost response, evicted session, server restart — is answered ``unknown``,
so an ambiguous outcome can only ever cost one re-establishing full
solve, never a diverged chain.

Knobs: ``KT_DELTA`` (default on; 0 disables the whole path and the wire
behaves byte-identically to pre-delta serving), ``KT_DELTA_SESSIONS``
(table capacity, default 64), ``KT_DELTA_TTL_S`` (idle TTL, default 900).
Durability (ISSUE 12, docs/RESILIENCE.md): ``KT_SESSION_DIR`` spools the
chains to disk on graceful shutdown and periodically at epoch boundaries
(``KT_SESSION_SNAPSHOT_S``), so a restarted replica serves the next delta
of every surviving session WARM instead of paying one re-establishing
full solve per client; ``KT_CATALOG_EPOCH`` (optional) refuses spools
from any OTHER catalog epoch (older or newer — rollbacks too).

Known limitation (documented, bounded): session ESTABLISHMENTS are full
solves served synchronously on the fast path (held batches are flushed
first, so other traffic proceeds between them), not coalesced into
megabatches — after a restart wipes the table, N re-establishing clients
serialize N full solves.  The cost is bounded by ``KT_DELTA_SESSIONS`` x
one full solve and paid once per restart; routing establishes through
the coalescer while seeding the table from finalization is the follow-on
if restart storms ever dominate (ROADMAP item 2's fleet story).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .. import faults as faults_mod
from ..metrics import (
    DELTA_EVICT_REASONS,
    DELTA_EVICTIONS,
    DELTA_RPC,
    DELTA_RPC_DURATION,
    DELTA_RPC_OUTCOMES,
    DELTA_SESSIONS,
    SNAPSHOT_DURATION,
    SNAPSHOT_RESTORE,
    SNAPSHOT_RESTORE_OUTCOMES,
    SNAPSHOT_SESSIONS,
    SNAPSHOT_SKIP_REASONS,
    SNAPSHOT_SKIPPED,
    SNAPSHOT_WRITE_OUTCOMES,
    SNAPSHOT_WRITES,
    Registry,
    registry as default_registry,
)
from ..solver.types import SimNode, SolveResult, advance_node_counter
from ..utils.clock import Clock
from . import snapshot as snap

logger = logging.getLogger(__name__)

#: default live-session capacity per pipeline (KT_DELTA_SESSIONS); LRU past
#: it — an evicted session costs its client one re-establishing full solve
DEFAULT_SESSIONS = 64
#: default idle TTL, seconds (KT_DELTA_TTL_S): a reconcile loop ticks every
#: few seconds, so 15 idle minutes means the client is gone
DEFAULT_TTL_S = 900.0


def delta_enabled() -> bool:
    """KT_DELTA=0 turns delta serving off entirely: session fields on the
    wire are ignored, every Solve takes the classic full path — byte-
    identical to pre-delta behavior."""
    return os.environ.get("KT_DELTA", "1") != "0"


class DeltaSessionUnknown(Exception):
    """The server holds no live chain for the client's (session, epoch) —
    evicted, never established, epoch mismatch after a lost response, or
    a catalog-epoch bump the request did not carry the new catalog for.
    The client's contract: re-establish with ONE full solve."""


@dataclass
class SessionEntry:
    """One live warm-start chain.  Dispatcher-owned after table lookup —
    only the pipeline's single dispatcher thread ever reads or mutates the
    chain state; the table lock below guards only the table itself."""

    session_id: str
    prev: SolveResult            # carries _warmstart_meta across the chain
    epoch: int                   # acked after every applied step
    catalog_epoch: int
    provisioners: Sequence
    instance_types: Sequence
    daemonsets: Sequence = ()
    #: every offering ever ICE'd onto this chain (establishment set + each
    #: step's wire set): re-passed on every step so a guard-trip full
    #: fallback — which drops the chain meta — cannot forget an ICE
    unavailable: set = field(default_factory=set)
    last_used: float = 0.0
    #: True while a delta step is mid-mutation on this chain.  Written by
    #: the dispatcher only; read by the snapshot writer so an epoch-atomic
    #: snapshot SKIPS a half-applied chain (a SIGTERM landing mid-step
    #: must never persist it — docs/RESILIENCE.md).  Transient: never
    #: serialized.
    in_step: bool = False


@dataclass
class DeltaReply:
    """Detached response view the dispatcher hands the RPC thread.

    ``full`` replies (establish / reseed / guard-trip fallback) carry the
    whole solution; incremental replies carry ONLY the step's changes —
    (re)placed watch pods in ``assignments``/``infeasible``, nodes the
    step created in ``nodes``, proposal nodes it pruned in
    ``removed_nodes`` — and the client merges them into its ledger.
    Every container here is a copy: the session chain mutates under the
    next delta while the RPC thread is still encoding this one."""

    state: str                    # "ok" | "unknown" | "" (delta off)
    epoch: int = 0
    mode: str = ""                # noop|host|scan|full|establish|reseed
    full: bool = True             # replace-wholesale vs merge
    assignments: Dict[str, str] = field(default_factory=dict)
    infeasible: Dict[str, str] = field(default_factory=dict)
    nodes: List[SimNode] = field(default_factory=list)
    removed_nodes: List[str] = field(default_factory=list)
    solve_ms: float = 0.0


class DeltaSessionTable:
    """Bounded, TTL-evicted map of live delta sessions (one per pipeline).

    Locking: the table dict is touched from the dispatcher (every
    session-routed RPC) and shutdown (``clear``), so every ``_sessions``
    access sits under ``_lock`` — ktlint KT015 pins this discipline and
    the KT_SANITIZE runtime watcher proxies the lock into the global
    order (analysis/sanitize.py LOCK_ORDER).  Entry CONTENTS are
    dispatcher-owned and never touched under the lock: holding it across
    a solve would serialize eviction behind device work."""

    def __init__(self, registry: Optional[Registry] = None,
                 clock: Optional[Clock] = None,
                 capacity: Optional[int] = None,
                 ttl_s: Optional[float] = None,
                 faults=None) -> None:
        self.registry = registry or default_registry
        self.clock = clock or Clock()
        if capacity is None:
            capacity = int(os.environ.get("KT_DELTA_SESSIONS",
                                          str(DEFAULT_SESSIONS)))
        if ttl_s is None:
            ttl_s = float(os.environ.get("KT_DELTA_TTL_S",
                                         str(DEFAULT_TTL_S)))
        self.capacity = max(1, capacity)
        self.ttl_s = max(0.0, ttl_s)
        # fault-injection plane (docs/RESILIENCE.md): the null no-op plane
        # unless KT_FAULTS configures a chaos schedule; the pipeline hands
        # its own plane down so one schedule covers table + delta path
        self._faults = (faults if faults is not None
                        else faults_mod.plane(self.registry))
        #: injected clock skew, seconds (fault kind ``clock_jump``):
        #: added to every TTL/LRU timestamp read, so a jump ages the whole
        #: table at once — the mass-TTL-eviction adversary
        self._skew = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()
        #: LRU order: oldest first  # guarded-by: _lock
        self._sessions: "OrderedDict[str, SessionEntry]" = OrderedDict()
        #: serializes spool WRITES (the background periodic writer vs the
        #: shutdown write): whoever starts last renames last, so a slow
        #: older capture can never replace a newer spool.  Never nested
        #: inside _lock (snapshot acquires it first, then _lock briefly
        #: for the capture).
        self._spool_lock = threading.Lock()
        #: strictly above every session epoch this table has ever issued,
        #: observed, restored, or evicted  # guarded-by: _lock
        self._epoch_floor = 1
        zero_init_metrics(self.registry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _gauge_locked(self) -> None:
        self.registry.gauge(DELTA_SESSIONS).set(len(self._sessions))

    def _note_epoch_locked(self, epoch: int) -> None:
        """Every epoch that leaves the table's sight (evicted, dropped,
        cleared) or enters it (put, restore) raises the establishment
        floor past it — see :meth:`next_epoch`."""
        if epoch + 1 > self._epoch_floor:
            self._epoch_floor = epoch + 1

    def next_epoch(self) -> int:
        """Establishment epoch: strictly above every epoch this table has
        ever issued, observed, restored, or evicted.  A re-established
        session can therefore NEVER advance back onto an epoch a stale
        incarnation reached — the epoch-collision path by which a stale
        spool (or a lost reply racing an eviction) could pass the exact-
        match check and silently diverge a chain is closed by
        construction."""
        with self._lock:
            for e in self._sessions.values():
                self._note_epoch_locked(e.epoch)
            return self._epoch_floor

    def _evict_expired_locked(self, now: float) -> None:
        if self.ttl_s <= 0:
            return
        dead = [sid for sid, e in self._sessions.items()
                if now - e.last_used > self.ttl_s]
        for sid in dead:
            self._note_epoch_locked(self._sessions[sid].epoch)
            del self._sessions[sid]
        if dead:
            self.registry.counter(DELTA_EVICTIONS).inc(
                {"reason": "ttl"}, value=float(len(dead)))

    def _table_fault(self) -> None:
        """Fire the session-table choke point (before taking the lock —
        the wipe effect re-enters via :meth:`clear`)."""
        effect = self._faults.fire("session_table")
        if effect is None:
            return
        if effect.kind == "session_wipe":
            self.clear("fault")
        elif effect.kind == "clock_jump":
            with self._lock:
                self._skew += effect.value

    def get(self, session_id: str) -> Optional[SessionEntry]:
        """Look up a live session (touches its TTL + LRU position); expired
        entries are evicted on the way."""
        if self._faults:
            self._table_fault()
        now = self.clock.now()
        with self._lock:
            now += self._skew
            self._evict_expired_locked(now)
            entry = self._sessions.get(session_id)
            if entry is not None:
                entry.last_used = now
                self._sessions.move_to_end(session_id)
            self._gauge_locked()
            return entry

    def put(self, entry: SessionEntry) -> None:
        """Insert or replace a session; LRU-evicts past capacity."""
        if self._faults:
            self._table_fault()
        now = self.clock.now()
        with self._lock:
            now += self._skew
            entry.last_used = now
            self._note_epoch_locked(entry.epoch)
            self._evict_expired_locked(now)
            self._sessions[entry.session_id] = entry
            self._sessions.move_to_end(entry.session_id)
            evicted = 0
            while len(self._sessions) > self.capacity:
                _sid, old = self._sessions.popitem(last=False)
                self._note_epoch_locked(old.epoch)
                evicted += 1
            if evicted:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": "capacity"}, value=float(evicted))
            self._gauge_locked()

    def drop(self, session_id: str, reason: str = "error") -> None:
        """Evict one session.  The error path: a delta step that raised
        mid-apply leaves the chain half-mutated at an UNCHANGED epoch —
        the client's cumulative retry would pass the epoch check and
        re-apply onto a corrupted base, so the only safe outcome is
        eviction (the client re-establishes with one full solve)."""
        with self._lock:
            gone = self._sessions.pop(session_id, None)
            if gone is not None:
                self._note_epoch_locked(gone.epoch)
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": reason})
            self._gauge_locked()

    def clear(self, reason: str = "stop") -> None:
        with self._lock:
            n = len(self._sessions)
            for e in self._sessions.values():
                self._note_epoch_locked(e.epoch)
            self._sessions.clear()
            if n:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": reason}, value=float(n))
            self._gauge_locked()

    # ---- durability (ISSUE 12: snapshot/restore, docs/RESILIENCE.md) ----
    def snapshot(self, dir_path: str) -> dict:
        """Write every quiescent session chain to the KT_SESSION_DIR
        spool (epoch-atomic: write-temp + fsync + rename).

        Needs NO scheduler lock, so the periodic write runs on a
        background thread and no serving path ever stalls behind pickle
        + fsync: each entry is pickled INDIVIDUALLY outside the table
        lock, and any chain a delta step touched during that window is
        discarded —

        - ``in_step`` at capture -> skipped (counted ``in_step``): the
          dispatcher sets the marker BEFORE its first mutation, so a
          chain mid-mutation is never even pickled;
        - pickle failure, or ``in_step``/``epoch`` moved by the time the
          entry's bytes are done -> discarded (counted ``torn``): a step
          that STARTED during pickling flips ``in_step`` first, and one
          that started AND committed moved the epoch — either way the
          possibly-inconsistent bytes are dropped.

        A skipped/torn session just costs its client one re-establish if
        the process dies before the next snapshot — the spool never
        carries a half-applied chain.  Returns ``{"written": n,
        "skipped": n}`` (skipped = in_step + torn).

        Concurrent writers (the background periodic thread vs the
        shutdown write) serialize on ``_spool_lock``: whoever starts
        last captures last AND renames last, so a slow older capture can
        never replace a newer spool."""
        with self._spool_lock:
            return self._snapshot_impl(dir_path)

    def _snapshot_impl(self, dir_path: str) -> dict:
        t0 = time.perf_counter()
        with self._lock:
            live = list(self._sessions.values())
        entries, skipped = [], 0
        max_epoch = 0
        for e in live:
            if e.in_step:
                skipped += 1
                self.registry.counter(SNAPSHOT_SKIPPED).inc(
                    {"reason": "in_step"})
                continue
            epoch0 = e.epoch
            try:
                blob = snap.pack_entry(dict(
                    session_id=e.session_id, prev=e.prev,
                    epoch=int(epoch0),
                    catalog_epoch=int(e.catalog_epoch),
                    provisioners=list(e.provisioners),
                    instance_types=list(e.instance_types),
                    daemonsets=list(e.daemonsets),
                    unavailable=set(e.unavailable)))
            # ktlint: allow[KT005] a chain mutating under the pickler can
            # raise anything; the entry is discarded as torn and counted
            except Exception:  # noqa: BLE001
                blob = None
            if blob is None or e.in_step or e.epoch != epoch0:
                skipped += 1
                self.registry.counter(SNAPSHOT_SKIPPED).inc(
                    {"reason": "torn"})
                continue
            max_epoch = max(max_epoch, int(e.catalog_epoch))
            entries.append(blob)
        writes = self.registry.counter(SNAPSHOT_WRITES)
        if not entries:
            if skipped == 0:
                # genuinely no sessions: an OLD spool left on disk would
                # resurrect long-evicted chains at the next restart —
                # "no sessions" must persist as "no spool" (with skipped
                # chains we keep the previous spool: those sessions are
                # live and a crash should still restore their last
                # committed epoch)
                try:
                    os.unlink(snap.spool_path(dir_path))
                except OSError:
                    pass
                self.registry.gauge(SNAPSHOT_SESSIONS).set(0.0)
            writes.inc({"outcome": "empty"})
            return {"written": 0, "skipped": skipped}
        try:
            blob = snap.pack(entries, catalog_epoch=max_epoch)
            # spool-byte adversary (snapshot_corrupt/_truncate): mangles
            # AFTER the checksum is computed, so a restore must detect it
            blob = self._faults.mangle("snapshot_write", blob)
            snap.write_atomic(dir_path, blob)
        # ktlint: allow[KT005] a failing snapshot must never take serving
        # down; the previous spool survives and the outcome is counted
        except Exception:  # noqa: BLE001
            logger.warning("session snapshot write to %s failed",
                           dir_path, exc_info=True)
            writes.inc({"outcome": "error"})
            faults_mod.count_recovery(self.registry, "snapshot_write",
                                      "failed")
            return {"written": 0, "skipped": skipped}
        writes.inc({"outcome": "written"})
        self.registry.gauge(SNAPSHOT_SESSIONS).set(float(len(entries)))
        self.registry.histogram(SNAPSHOT_DURATION).observe(
            time.perf_counter() - t0)
        return {"written": len(entries), "skipped": skipped}

    def restore(self, dir_path: str,
                expected_catalog_epoch: Optional[int] = None) -> int:
        """Rehydrate the table from the spool at startup.  Every refusal
        (corrupt / truncated / version skew / stale catalog epoch) is a
        counted COLD START — never a crash, never a diverged chain.
        Returns the number of sessions restored."""
        t0 = time.perf_counter()

        def _count(outcome: str) -> None:
            self.registry.counter(SNAPSHOT_RESTORE).inc(
                {"outcome": outcome})

        blob = snap.read(dir_path)
        if blob is None:
            _count("missing")
            return 0
        try:
            raw_entries, _epoch = snap.unpack(
                blob, expected_catalog_epoch=expected_catalog_epoch)
            entries = [snap.unpack_entry(b) for b in raw_entries]
            restored = 0
            now = self.clock.now()
            # a restarted process's auto-name counter starts at 0: advance
            # it past every restored node index so a fresh proposal can
            # never collide with (and silently cross-wire) a chain node
            max_idx = -1
            for d in entries:
                prev = d.get("prev")
                meta = getattr(prev, "_warmstart_meta", None)
                names = [n.name for n in
                         list(getattr(prev, "nodes", ()) or ())
                         + list(getattr(prev, "existing_nodes", ()) or ())]
                if meta is not None:
                    names += [n.name for n in meta.nodes]
                for nm in names:
                    if nm.startswith("node-"):
                        try:
                            max_idx = max(max_idx, int(nm[5:]))
                        except ValueError:
                            pass
            if max_idx >= 0:
                advance_node_counter(max_idx)
            with self._lock:
                now += self._skew
                for d in entries:
                    entry = SessionEntry(
                        session_id=d["session_id"], prev=d["prev"],
                        epoch=int(d["epoch"]),
                        catalog_epoch=int(d["catalog_epoch"]),
                        provisioners=d["provisioners"],
                        instance_types=d["instance_types"],
                        daemonsets=tuple(d.get("daemonsets") or ()),
                        unavailable=set(d.get("unavailable") or ()),
                        last_used=now,
                    )
                    # the establishment floor clears every restored epoch:
                    # a session re-established after a restore can never
                    # advance back onto an epoch its old incarnation
                    # reached (the epoch-collision divergence class)
                    self._note_epoch_locked(entry.epoch)
                    self._sessions[entry.session_id] = entry
                    self._sessions.move_to_end(entry.session_id)
                    restored += 1
                evicted = 0
                while len(self._sessions) > self.capacity:
                    self._sessions.popitem(last=False)
                    evicted += 1
                    restored -= 1
                if evicted:
                    self.registry.counter(DELTA_EVICTIONS).inc(
                        {"reason": "capacity"}, value=float(evicted))
                self._gauge_locked()
            # restore-once: the spool is CONSUMED — these chains mutate
            # from here on, and a later crash that never wrote a fresh
            # snapshot must cold-start rather than resurrect this now-
            # doubly-stale file (the stale-spool divergence class)
            try:
                os.unlink(snap.spool_path(dir_path))
            except OSError:
                pass
        except snap.SnapshotRefused as err:
            logger.warning("session snapshot refused; serving cold: %s",
                           err)
            _count(err.reason)
            faults_mod.count_recovery(self.registry, "snapshot_read",
                                      "cold")
            self.clear("stop")  # drop any partially-restored entries
            return 0
        # ktlint: allow[KT005] an unexpectedly-shaped spool is the same
        # outcome as a corrupt one: counted cold start, never a crash
        except Exception:  # noqa: BLE001
            logger.warning("session snapshot restore from %s failed; "
                           "serving cold", dir_path, exc_info=True)
            _count("error")
            faults_mod.count_recovery(self.registry, "snapshot_read",
                                      "cold")
            self.clear("stop")
            return 0
        _count("restored")
        self.registry.histogram(SNAPSHOT_DURATION).observe(
            time.perf_counter() - t0)
        logger.info("restored %d delta session(s) from %s", restored,
                    dir_path)
        return restored


def zero_init_metrics(registry: Registry) -> None:
    """Register every delta-serving series at 0 from construction (KT003:
    a counter born at its first increment loses that increment to
    rate()/increase())."""
    rpc = registry.counter(DELTA_RPC)
    for outcome in DELTA_RPC_OUTCOMES:
        if not rpc.has({"outcome": outcome}):
            rpc.inc({"outcome": outcome}, value=0.0)
    evict = registry.counter(DELTA_EVICTIONS)
    for reason in DELTA_EVICT_REASONS:
        if not evict.has({"reason": reason}):
            evict.inc({"reason": reason}, value=0.0)
    gauge = registry.gauge(DELTA_SESSIONS)
    if not gauge.has():
        gauge.set(0)
    registry.histogram(DELTA_RPC_DURATION)
    # session durability families (ISSUE 12): the first snapshot write /
    # restore refusal of a replica's life must survive rate()
    writes = registry.counter(SNAPSHOT_WRITES)
    for outcome in SNAPSHOT_WRITE_OUTCOMES:
        if not writes.has({"outcome": outcome}):
            writes.inc({"outcome": outcome}, value=0.0)
    skipped = registry.counter(SNAPSHOT_SKIPPED)
    for reason in SNAPSHOT_SKIP_REASONS:
        if not skipped.has({"reason": reason}):
            skipped.inc({"reason": reason}, value=0.0)
    restore = registry.counter(SNAPSHOT_RESTORE)
    for outcome in SNAPSHOT_RESTORE_OUTCOMES:
        if not restore.has({"outcome": outcome}):
            restore.inc({"outcome": outcome}, value=0.0)
    sg = registry.gauge(SNAPSHOT_SESSIONS)
    if not sg.has():
        sg.set(0)
    registry.histogram(SNAPSHOT_DURATION)
    # recovery-outcome population (KT016's funnel is live in production —
    # organic faults count too, so the series must exist from birth)
    faults_mod.zero_init_recovery(registry)
