"""Delta serving — the session-stateful side of warm-start over the wire.

PR 6 made steady-state reconcile a sub-millisecond incremental update
(``solver/warmstart.delta_solve``), but every gRPC ``Solve`` still
re-shipped the full cluster and re-solved from scratch.  This module holds
the server-side session state that closes that gap (ISSUE 10; the serving
protocol itself lives in ``service/server.py`` ``SolvePipeline``
``_dispatch_delta`` and the client facade in ``service/client.py``
``DeltaSession``):

- :class:`DeltaSessionTable` — a bounded, TTL-evicted table of live
  warm-start chains, one per client session: each :class:`SessionEntry`
  carries the previous :class:`~karpenter_tpu.solver.types.SolveResult`
  (whose ``_warmstart_meta`` IS the incremental chain), the catalog the
  chain was packed against, and the epoch counter the wire protocol acks.
- :class:`DeltaReply` — the dispatcher-built, DETACHED response view: the
  session chain is mutated by the next delta the moment the dispatcher
  moves on, so everything the RPC thread encodes is snapshotted here
  first (O(delta) per incremental step; O(cluster) only on the rare
  establish/reseed/full-shaped replies).
- :class:`DeltaSessionUnknown` — the typed "no live chain for your
  (session, epoch)" outcome; the wire maps it to
  ``session_state="unknown"`` and the client re-establishes with ONE full
  solve (never a retry loop, never silent divergence).

Epoch contract: the server acks ``epoch`` after applying each step; a
client must send ``base_epoch`` equal to the last ack.  Any mismatch —
lost response, evicted session, server restart — is answered ``unknown``,
so an ambiguous outcome can only ever cost one re-establishing full
solve, never a diverged chain.

Knobs: ``KT_DELTA`` (default on; 0 disables the whole path and the wire
behaves byte-identically to pre-delta serving), ``KT_DELTA_SESSIONS``
(table capacity, default 64), ``KT_DELTA_TTL_S`` (idle TTL, default 900).

Known limitation (documented, bounded): session ESTABLISHMENTS are full
solves served synchronously on the fast path (held batches are flushed
first, so other traffic proceeds between them), not coalesced into
megabatches — after a restart wipes the table, N re-establishing clients
serialize N full solves.  The cost is bounded by ``KT_DELTA_SESSIONS`` x
one full solve and paid once per restart; routing establishes through
the coalescer while seeding the table from finalization is the follow-on
if restart storms ever dominate (ROADMAP item 2's fleet story).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..metrics import (
    DELTA_EVICT_REASONS,
    DELTA_EVICTIONS,
    DELTA_RPC,
    DELTA_RPC_DURATION,
    DELTA_RPC_OUTCOMES,
    DELTA_SESSIONS,
    Registry,
    registry as default_registry,
)
from ..solver.types import SimNode, SolveResult
from ..utils.clock import Clock

#: default live-session capacity per pipeline (KT_DELTA_SESSIONS); LRU past
#: it — an evicted session costs its client one re-establishing full solve
DEFAULT_SESSIONS = 64
#: default idle TTL, seconds (KT_DELTA_TTL_S): a reconcile loop ticks every
#: few seconds, so 15 idle minutes means the client is gone
DEFAULT_TTL_S = 900.0


def delta_enabled() -> bool:
    """KT_DELTA=0 turns delta serving off entirely: session fields on the
    wire are ignored, every Solve takes the classic full path — byte-
    identical to pre-delta behavior."""
    return os.environ.get("KT_DELTA", "1") != "0"


class DeltaSessionUnknown(Exception):
    """The server holds no live chain for the client's (session, epoch) —
    evicted, never established, epoch mismatch after a lost response, or
    a catalog-epoch bump the request did not carry the new catalog for.
    The client's contract: re-establish with ONE full solve."""


@dataclass
class SessionEntry:
    """One live warm-start chain.  Dispatcher-owned after table lookup —
    only the pipeline's single dispatcher thread ever reads or mutates the
    chain state; the table lock below guards only the table itself."""

    session_id: str
    prev: SolveResult            # carries _warmstart_meta across the chain
    epoch: int                   # acked after every applied step
    catalog_epoch: int
    provisioners: Sequence
    instance_types: Sequence
    daemonsets: Sequence = ()
    #: every offering ever ICE'd onto this chain (establishment set + each
    #: step's wire set): re-passed on every step so a guard-trip full
    #: fallback — which drops the chain meta — cannot forget an ICE
    unavailable: set = field(default_factory=set)
    last_used: float = 0.0


@dataclass
class DeltaReply:
    """Detached response view the dispatcher hands the RPC thread.

    ``full`` replies (establish / reseed / guard-trip fallback) carry the
    whole solution; incremental replies carry ONLY the step's changes —
    (re)placed watch pods in ``assignments``/``infeasible``, nodes the
    step created in ``nodes``, proposal nodes it pruned in
    ``removed_nodes`` — and the client merges them into its ledger.
    Every container here is a copy: the session chain mutates under the
    next delta while the RPC thread is still encoding this one."""

    state: str                    # "ok" | "unknown" | "" (delta off)
    epoch: int = 0
    mode: str = ""                # noop|host|scan|full|establish|reseed
    full: bool = True             # replace-wholesale vs merge
    assignments: Dict[str, str] = field(default_factory=dict)
    infeasible: Dict[str, str] = field(default_factory=dict)
    nodes: List[SimNode] = field(default_factory=list)
    removed_nodes: List[str] = field(default_factory=list)
    solve_ms: float = 0.0


class DeltaSessionTable:
    """Bounded, TTL-evicted map of live delta sessions (one per pipeline).

    Locking: the table dict is touched from the dispatcher (every
    session-routed RPC) and shutdown (``clear``), so every ``_sessions``
    access sits under ``_lock`` — ktlint KT015 pins this discipline and
    the KT_SANITIZE runtime watcher proxies the lock into the global
    order (analysis/sanitize.py LOCK_ORDER).  Entry CONTENTS are
    dispatcher-owned and never touched under the lock: holding it across
    a solve would serialize eviction behind device work."""

    def __init__(self, registry: Optional[Registry] = None,
                 clock: Optional[Clock] = None,
                 capacity: Optional[int] = None,
                 ttl_s: Optional[float] = None) -> None:
        self.registry = registry or default_registry
        self.clock = clock or Clock()
        if capacity is None:
            capacity = int(os.environ.get("KT_DELTA_SESSIONS",
                                          str(DEFAULT_SESSIONS)))
        if ttl_s is None:
            ttl_s = float(os.environ.get("KT_DELTA_TTL_S",
                                         str(DEFAULT_TTL_S)))
        self.capacity = max(1, capacity)
        self.ttl_s = max(0.0, ttl_s)
        self._lock = threading.Lock()
        #: LRU order: oldest first  # guarded-by: _lock
        self._sessions: "OrderedDict[str, SessionEntry]" = OrderedDict()
        zero_init_metrics(self.registry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _gauge_locked(self) -> None:
        self.registry.gauge(DELTA_SESSIONS).set(len(self._sessions))

    def _evict_expired_locked(self, now: float) -> None:
        if self.ttl_s <= 0:
            return
        dead = [sid for sid, e in self._sessions.items()
                if now - e.last_used > self.ttl_s]
        for sid in dead:
            del self._sessions[sid]
        if dead:
            self.registry.counter(DELTA_EVICTIONS).inc(
                {"reason": "ttl"}, value=float(len(dead)))

    def get(self, session_id: str) -> Optional[SessionEntry]:
        """Look up a live session (touches its TTL + LRU position); expired
        entries are evicted on the way."""
        now = self.clock.now()
        with self._lock:
            self._evict_expired_locked(now)
            entry = self._sessions.get(session_id)
            if entry is not None:
                entry.last_used = now
                self._sessions.move_to_end(session_id)
            self._gauge_locked()
            return entry

    def put(self, entry: SessionEntry) -> None:
        """Insert or replace a session; LRU-evicts past capacity."""
        now = self.clock.now()
        entry.last_used = now
        with self._lock:
            self._evict_expired_locked(now)
            self._sessions[entry.session_id] = entry
            self._sessions.move_to_end(entry.session_id)
            evicted = 0
            while len(self._sessions) > self.capacity:
                self._sessions.popitem(last=False)
                evicted += 1
            if evicted:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": "capacity"}, value=float(evicted))
            self._gauge_locked()

    def drop(self, session_id: str, reason: str = "error") -> None:
        """Evict one session.  The error path: a delta step that raised
        mid-apply leaves the chain half-mutated at an UNCHANGED epoch —
        the client's cumulative retry would pass the epoch check and
        re-apply onto a corrupted base, so the only safe outcome is
        eviction (the client re-establishes with one full solve)."""
        with self._lock:
            if self._sessions.pop(session_id, None) is not None:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": reason})
            self._gauge_locked()

    def clear(self, reason: str = "stop") -> None:
        with self._lock:
            n = len(self._sessions)
            self._sessions.clear()
            if n:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": reason}, value=float(n))
            self._gauge_locked()


def zero_init_metrics(registry: Registry) -> None:
    """Register every delta-serving series at 0 from construction (KT003:
    a counter born at its first increment loses that increment to
    rate()/increase())."""
    rpc = registry.counter(DELTA_RPC)
    for outcome in DELTA_RPC_OUTCOMES:
        if not rpc.has({"outcome": outcome}):
            rpc.inc({"outcome": outcome}, value=0.0)
    evict = registry.counter(DELTA_EVICTIONS)
    for reason in DELTA_EVICT_REASONS:
        if not evict.has({"reason": reason}):
            evict.inc({"reason": reason}, value=0.0)
    gauge = registry.gauge(DELTA_SESSIONS)
    if not gauge.has():
        gauge.set(0)
    registry.histogram(DELTA_RPC_DURATION)
