"""Delta serving — the session-stateful side of warm-start over the wire.

PR 6 made steady-state reconcile a sub-millisecond incremental update
(``solver/warmstart.delta_solve``), but every gRPC ``Solve`` still
re-shipped the full cluster and re-solved from scratch.  This module holds
the server-side session state that closes that gap (ISSUE 10; the serving
protocol itself lives in ``service/server.py`` ``SolvePipeline``
``_dispatch_delta`` and the client facade in ``service/client.py``
``DeltaSession``):

- :class:`DeltaSessionTable` — a bounded, TTL-evicted table of live
  warm-start chains, one per client session: each :class:`SessionEntry`
  carries the previous :class:`~karpenter_tpu.solver.types.SolveResult`
  (whose ``_warmstart_meta`` IS the incremental chain), the catalog the
  chain was packed against, and the epoch counter the wire protocol acks.
- :class:`DeltaReply` — the dispatcher-built, DETACHED response view: the
  session chain is mutated by the next delta the moment the dispatcher
  moves on, so everything the RPC thread encodes is snapshotted here
  first (O(delta) per incremental step; O(cluster) only on the rare
  establish/reseed/full-shaped replies).
- :class:`DeltaSessionUnknown` — the typed "no live chain for your
  (session, epoch)" outcome; the wire maps it to
  ``session_state="unknown"`` and the client re-establishes with ONE full
  solve (never a retry loop, never silent divergence).

Epoch contract: the server acks ``epoch`` after applying each step; a
client must send ``base_epoch`` equal to the last ack.  Any mismatch —
lost response, evicted session, server restart — is answered ``unknown``,
so an ambiguous outcome can only ever cost one re-establishing full
solve, never a diverged chain.

Knobs: ``KT_DELTA`` (default on; 0 disables the whole path and the wire
behaves byte-identically to pre-delta serving), ``KT_DELTA_SESSIONS``
(table capacity, default 64), ``KT_DELTA_TTL_S`` (idle TTL, default 900).
Durability (ISSUE 12, docs/RESILIENCE.md): ``KT_SESSION_DIR`` spools the
chains to disk on graceful shutdown and periodically at epoch boundaries
(``KT_SESSION_SNAPSHOT_S``), so a restarted replica serves the next delta
of every surviving session WARM instead of paying one re-establishing
full solve per client; ``KT_CATALOG_EPOCH`` (optional) refuses spools
from any OTHER catalog epoch (older or newer — rollbacks too).

Meshed composition (ISSUE 14): on a mesh-configured scheduler the inline
delta shortcut survives because the displaced-subproblem solves route
through the HOST-LOCAL single-shard programs
(``BatchScheduler.solve_delta`` under ``_host_local``; ``KT_DELTA_LOCAL=0``
reverts) — a sub-ms step must not pay sharded dispatch plus a mesh-wide
fence; only the full-solve fallbacks (threshold/guard/reseed — whole-
cluster work) keep the sharded program.  Session state here is mesh-
agnostic: chains carry host objects, never device buffers.

Fleet handoff (ISSUE 13): the spool is SESSION-ADDRESSABLE — one record
file + one ownership lease per session (``service/snapshot.py``) — so on
a SHARED volume any replica can :meth:`DeltaSessionTable.adopt` a
specific session on demand: a replica death or graceful drain hands the
warm chain to whichever sibling the client re-homes to, and the lease
protocol (claim / typed refusal / steal-after-``KT_SESSION_LEASE_S``)
guarantees exactly one adopter.  ``KT_REPLICA_ID`` names this replica as
the lease owner (the deploy sets the pod name; defaults to a stable
per-process id so in-process restarts self-renew).

Known limitation (documented, bounded): session ESTABLISHMENTS are full
solves served synchronously on the fast path (held batches are flushed
first, so other traffic proceeds between them), not coalesced into
megabatches — after a restart wipes the table, N re-establishing clients
serialize N full solves.  The cost is bounded by ``KT_DELTA_SESSIONS`` x
one full solve and paid once per restart; routing establishes through
the coalescer while seeding the table from finalization is the follow-on
if restart storms ever dominate (ROADMAP item 2's fleet story).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .. import faults as faults_mod
from ..metrics import (
    DELTA_EVICT_REASONS,
    DELTA_EVICTIONS,
    DELTA_RPC,
    DELTA_RPC_DURATION,
    DELTA_RPC_OUTCOMES,
    DELTA_SESSIONS,
    SESSION_ADOPTION_OUTCOMES,
    SESSION_ADOPTIONS,
    SESSION_LEASES,
    SNAPSHOT_DURATION,
    SNAPSHOT_RESTORE,
    SNAPSHOT_RESTORE_OUTCOMES,
    SNAPSHOT_SESSIONS,
    SNAPSHOT_SKIP_REASONS,
    SNAPSHOT_SKIPPED,
    SNAPSHOT_WRITE_OUTCOMES,
    SNAPSHOT_WRITES,
    Registry,
    registry as default_registry,
)
from ..obs import protocol
from ..solver.types import SimNode, SolveResult, advance_node_counter
from ..utils.clock import Clock
from . import snapshot as snap

logger = logging.getLogger(__name__)

#: default live-session capacity per pipeline (KT_DELTA_SESSIONS); LRU past
#: it — an evicted session costs its client one re-establishing full solve
DEFAULT_SESSIONS = 64
#: default idle TTL, seconds (KT_DELTA_TTL_S): a reconcile loop ticks every
#: few seconds, so 15 idle minutes means the client is gone
DEFAULT_TTL_S = 900.0


def delta_enabled() -> bool:
    """KT_DELTA=0 turns delta serving off entirely: session fields on the
    wire are ignored, every Solve takes the classic full path — byte-
    identical to pre-delta behavior."""
    return os.environ.get("KT_DELTA", "1") != "0"


class DeltaSessionUnknown(Exception):
    """The server holds no live chain for the client's (session, epoch) —
    evicted, never established, epoch mismatch after a lost response, or
    a catalog-epoch bump the request did not carry the new catalog for.
    The client's contract: re-establish with ONE full solve."""


@dataclass
class SessionEntry:
    """One live warm-start chain.  Dispatcher-owned after table lookup —
    only the pipeline's single dispatcher thread ever reads or mutates the
    chain state; the table lock below guards only the table itself."""

    session_id: str
    prev: SolveResult            # carries _warmstart_meta across the chain
    epoch: int                   # acked after every applied step
    catalog_epoch: int
    provisioners: Sequence
    instance_types: Sequence
    daemonsets: Sequence = ()
    #: every offering ever ICE'd onto this chain (establishment set + each
    #: step's wire set): re-passed on every step so a guard-trip full
    #: fallback — which drops the chain meta — cannot forget an ICE
    unavailable: set = field(default_factory=set)
    last_used: float = 0.0
    #: True while a delta step is mid-mutation on this chain.  Written by
    #: the dispatcher only; read by the snapshot writer so an epoch-atomic
    #: snapshot SKIPS a half-applied chain (a SIGTERM landing mid-step
    #: must never persist it — docs/RESILIENCE.md).  Transient: never
    #: serialized.
    in_step: bool = False
    #: adoption provenance (ISSUE 15, transient like in_step): how this
    #: entry arrived ("" established here, "adopted" free-lease claim,
    #: "stolen" expired-lease steal) and WHOSE lease guarded the record —
    #: the /statusz session block and the session_adopt/session_steal
    #: lifecycle spans read these so a failed-over chain's journey is
    #: diagnosable without grepping the spool
    adopt_how: str = ""
    adopted_from: str = ""
    #: per-incarnation identity, minted at establishment and persisted
    #: with the record (ISSUE 17).  The epoch exact-match check alone
    #: cannot survive a cross-replica re-home: a fresh table's epoch
    #: floor never saw this session's history, so a rolled-back old-
    #: incarnation record can collide with the new chain's acked epoch
    #: and pass the check — the nonce pins WHICH incarnation an epoch
    #: belongs to.  Empty = legacy (pre-nonce client/record): wildcard,
    #: PR-10 semantics, so mixed-version fleets degrade instead of
    #: hard-failing.
    nonce: str = ""


@dataclass
class DeltaReply:
    """Detached response view the dispatcher hands the RPC thread.

    ``full`` replies (establish / reseed / guard-trip fallback) carry the
    whole solution; incremental replies carry ONLY the step's changes —
    (re)placed watch pods in ``assignments``/``infeasible``, nodes the
    step created in ``nodes``, proposal nodes it pruned in
    ``removed_nodes`` — and the client merges them into its ledger.
    Every container here is a copy: the session chain mutates under the
    next delta while the RPC thread is still encoding this one."""

    state: str                    # "ok" | "unknown" | "" (delta off)
    epoch: int = 0
    mode: str = ""                # noop|host|scan|full|establish|reseed
    full: bool = True             # replace-wholesale vs merge
    assignments: Dict[str, str] = field(default_factory=dict)
    infeasible: Dict[str, str] = field(default_factory=dict)
    nodes: List[SimNode] = field(default_factory=list)
    removed_nodes: List[str] = field(default_factory=list)
    solve_ms: float = 0.0
    #: the session incarnation's nonce, echoed to the client on every
    #: reply so it can present it with the next step (empty = legacy)
    nonce: str = ""


class DeltaSessionTable:
    """Bounded, TTL-evicted map of live delta sessions (one per pipeline).

    Locking: the table dict is touched from the dispatcher (every
    session-routed RPC) and shutdown (``clear``), so every ``_sessions``
    access sits under ``_lock`` — ktlint KT015 pins this discipline and
    the KT_SANITIZE runtime watcher proxies the lock into the global
    order (analysis/sanitize.py LOCK_ORDER).  Entry CONTENTS are
    dispatcher-owned and never touched under the lock: holding it across
    a solve would serialize eviction behind device work."""

    def __init__(self, registry: Optional[Registry] = None,
                 clock: Optional[Clock] = None,
                 capacity: Optional[int] = None,
                 ttl_s: Optional[float] = None,
                 faults=None,
                 spool_dir: Optional[str] = None,
                 lease_s: Optional[float] = None,
                 replica: Optional[str] = None) -> None:
        self.registry = registry or default_registry
        self.clock = clock or Clock()
        if capacity is None:
            capacity = int(os.environ.get("KT_DELTA_SESSIONS",
                                          str(DEFAULT_SESSIONS)))
        if ttl_s is None:
            ttl_s = float(os.environ.get("KT_DELTA_TTL_S",
                                         str(DEFAULT_TTL_S)))
        self.capacity = max(1, capacity)
        self.ttl_s = max(0.0, ttl_s)
        #: default spool directory for snapshot/restore/adopt (callers may
        #: still pass an explicit dir — tests do); set by the pipeline to
        #: its backend-namespaced KT_SESSION_DIR
        self.spool_dir = spool_dir or ""
        if lease_s is None:
            lease_s = float(os.environ.get(
                "KT_SESSION_LEASE_S", str(snap.DEFAULT_LEASE_S)))
        #: ownership-lease TTL (KT_SESSION_LEASE_S): a dead replica's
        #: sessions become stealable this long after its last record
        #: write — the fleet's failover-warmness window
        self.lease_s = max(0.0, lease_s)
        #: this replica's lease-owner identity (KT_REPLICA_ID or a stable
        #: per-process id — see snapshot.replica_id)
        self.replica = replica or os.environ.get(
            "KT_REPLICA_ID", "") or snap.replica_id()
        #: KT_CATALOG_EPOCH pin: when set, records from any OTHER catalog
        #: epoch are refused — by the boot restore AND by adopt-on-miss
        #: (a failed-over chain packed against stale prices must not
        #: serve warm any more than a restored one may)
        cat = os.environ.get("KT_CATALOG_EPOCH", "")
        self.expected_catalog_epoch: Optional[int] = (
            int(cat) if cat else None)
        #: sids whose spool leases this table holds  # guarded-by: _lock
        self._owned: set = set()
        # fault-injection plane (docs/RESILIENCE.md): the null no-op plane
        # unless KT_FAULTS configures a chaos schedule; the pipeline hands
        # its own plane down so one schedule covers table + delta path
        self._faults = (faults if faults is not None
                        else faults_mod.plane(self.registry))
        #: injected clock skew, seconds (fault kind ``clock_jump``):
        #: added to every TTL/LRU timestamp read, so a jump ages the whole
        #: table at once — the mass-TTL-eviction adversary
        self._skew = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()
        #: LRU order: oldest first  # guarded-by: _lock
        self._sessions: "OrderedDict[str, SessionEntry]" = OrderedDict()
        #: serializes spool WRITES (the background periodic writer vs the
        #: shutdown write): whoever starts last renames last, so a slow
        #: older capture can never replace a newer spool.  Never nested
        #: inside _lock (snapshot acquires it first, then _lock briefly
        #: for the capture).
        self._spool_lock = threading.Lock()
        #: strictly above every session epoch this table has ever issued,
        #: observed, restored, or evicted  # guarded-by: _lock
        self._epoch_floor = 1
        zero_init_metrics(self.registry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _gauge_locked(self) -> None:
        self.registry.gauge(DELTA_SESSIONS).set(len(self._sessions))

    def _leases_gauge_locked(self) -> None:
        self.registry.gauge(SESSION_LEASES).set(float(len(self._owned)))

    def _note_epoch_locked(self, epoch: int) -> None:
        """Every epoch that leaves the table's sight (evicted, dropped,
        cleared) or enters it (put, restore) raises the establishment
        floor past it — see :meth:`next_epoch`."""
        if epoch + 1 > self._epoch_floor:
            self._epoch_floor = epoch + 1

    def next_epoch(self) -> int:
        """Establishment epoch: strictly above every epoch this table has
        ever issued, observed, restored, or evicted.  A re-established
        session can therefore NEVER advance back onto an epoch a stale
        incarnation reached — the epoch-collision path by which a stale
        spool (or a lost reply racing an eviction) could pass the exact-
        match check and silently diverge a chain is closed by
        construction."""
        with self._lock:
            for e in self._sessions.values():
                self._note_epoch_locked(e.epoch)
            return self._epoch_floor

    def _evict_expired_locked(self, now: float) -> None:
        if self.ttl_s <= 0:
            return
        dead = [sid for sid, e in self._sessions.items()
                if now - e.last_used > self.ttl_s]
        for sid in dead:
            self._note_epoch_locked(self._sessions[sid].epoch)
            if protocol._SINK is not None:
                protocol.emit(sid, "evict:ttl", replica=self.replica)
            del self._sessions[sid]
        if dead:
            self.registry.counter(DELTA_EVICTIONS).inc(
                {"reason": "ttl"}, value=float(len(dead)))

    def _table_fault(self) -> None:
        """Fire the session-table choke point (before taking the lock —
        the wipe effect re-enters via :meth:`clear`)."""
        effect = self._faults.fire("session_table")
        if effect is None:
            return
        if effect.kind == "session_wipe":
            self.clear("fault")
        elif effect.kind == "clock_jump":
            with self._lock:
                self._skew += effect.value

    def get(self, session_id: str) -> Optional[SessionEntry]:
        """Look up a live session (touches its TTL + LRU position); expired
        entries are evicted on the way."""
        if self._faults:
            self._table_fault()
        now = self.clock.now()
        with self._lock:
            now += self._skew
            self._evict_expired_locked(now)
            entry = self._sessions.get(session_id)
            if entry is not None:
                entry.last_used = now
                self._sessions.move_to_end(session_id)
            self._gauge_locked()
            return entry

    def put(self, entry: SessionEntry) -> None:
        """Insert or replace a session; LRU-evicts past capacity."""
        if self._faults:
            self._table_fault()
        now = self.clock.now()
        with self._lock:
            now += self._skew
            entry.last_used = now
            self._note_epoch_locked(entry.epoch)
            self._evict_expired_locked(now)
            self._sessions[entry.session_id] = entry
            self._sessions.move_to_end(entry.session_id)
            evicted = 0
            while len(self._sessions) > self.capacity:
                sid, old = self._sessions.popitem(last=False)
                self._note_epoch_locked(old.epoch)
                if protocol._SINK is not None:
                    protocol.emit(sid, "evict:capacity",
                                  replica=self.replica)
                evicted += 1
            if evicted:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": "capacity"}, value=float(evicted))
            self._gauge_locked()
        if protocol._SINK is not None:
            protocol.emit(entry.session_id, "establish",
                          replica=self.replica, epoch=entry.epoch)

    def drop(self, session_id: str, reason: str = "error") -> None:
        """Evict one session.  The error path: a delta step that raised
        mid-apply leaves the chain half-mutated at an UNCHANGED epoch —
        the client's cumulative retry would pass the epoch check and
        re-apply onto a corrupted base, so the only safe outcome is
        eviction (the client re-establishes with one full solve).  An
        error-evicted session's spool RECORD dies with it: the last
        committed epoch on disk is clean, but a poisoned chain's client
        must re-establish from ground truth, not re-adopt and re-apply
        onto state the server already failed to advance once — but ONLY
        when the record is actually OURS.  The lease is re-read under
        the spool lock first (ISSUE 17, pinned by the lease model's
        ``record-owner-safety`` invariant): a zombie whose lease was
        stolen while it was wedged may still be mid-step when the step
        fails, and unconditionally removing the record here would
        destroy the ADOPTER's durability — the one file that makes the
        real owner's chain survive ITS next crash.  A ``lease_lost``
        drop touches NO spool state — the record and lease belong to
        the new owner now."""
        with self._lock:
            gone = self._sessions.pop(session_id, None)
            if gone is not None:
                self._note_epoch_locked(gone.epoch)
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": reason})
            self._owned.discard(session_id)
            self._leases_gauge_locked()
            self._gauge_locked()
        if gone is not None and reason == "error" and self.spool_dir:
            # ownership re-check + removal are one _spool_lock section so
            # they cannot interleave with a concurrent adoption
            with self._spool_lock:
                try:
                    lease = snap.lease_state(self.spool_dir, session_id)
                # ktlint: allow[KT005] an unreadable lease file defaults
                # to NOT ours — keeping a stale record costs one refused
                # adoption; removing an adopter's record loses a chain
                except Exception:  # noqa: BLE001
                    lease = {"owner": ""}
                owner = str((lease or {}).get("owner", "") or "")
                if owner == self.replica:
                    snap.remove_record(self.spool_dir, session_id)
                    snap.release_lease(self.spool_dir, session_id,
                                       self.replica)
        if gone is not None and protocol._SINK is not None:
            protocol.emit(session_id, "drop:" + reason,
                          replica=self.replica, epoch=gone.epoch)

    def clear(self, reason: str = "stop") -> None:
        """Evict everything.  The graceful-shutdown path (``stop``) also
        RELEASES every owned lease — records stay on disk, so a sibling
        (or the replacement replica) adopts each surviving session
        instantly instead of waiting out the lease TTL.  The injected
        ``fault`` wipe releases nothing: a real in-memory loss would
        not."""
        with self._lock:
            n = len(self._sessions)
            cleared = list(self._sessions)
            for e in self._sessions.values():
                self._note_epoch_locked(e.epoch)
            self._sessions.clear()
            if n:
                self.registry.counter(DELTA_EVICTIONS).inc(
                    {"reason": reason}, value=float(n))
            owned = list(self._owned)
            if reason == "stop":
                self._owned.clear()
            self._leases_gauge_locked()
            self._gauge_locked()
        if reason == "stop" and self.spool_dir:
            for sid in owned:
                snap.release_lease(self.spool_dir, sid, self.replica)
        if protocol._SINK is not None:
            for sid in cleared:
                protocol.emit(sid, "clear:" + reason,
                              replica=self.replica)

    # ---- durability + fleet handoff (ISSUE 12/13, docs/RESILIENCE.md) ----
    def snapshot(self, dir_path: Optional[str] = None) -> dict:
        """Write every quiescent session chain to its own record file
        under the KT_SESSION_DIR spool (epoch-atomic: write-temp + fsync
        + rename per record), claiming/renewing this replica's ownership
        lease on each.

        Needs NO scheduler lock, so the periodic write runs on a
        background thread and no serving path ever stalls behind pickle
        + fsync: each entry is pickled INDIVIDUALLY outside the table
        lock, and any chain a delta step touched during that window is
        discarded —

        - ``in_step`` at capture -> skipped (counted ``in_step``): the
          dispatcher sets the marker BEFORE its first mutation, so a
          chain mid-mutation is never even pickled;
        - pickle failure, or ``in_step``/``epoch`` moved by the time the
          entry's bytes are done -> discarded (counted ``torn``): a step
          that STARTED during pickling flips ``in_step`` first, and one
          that started AND committed moved the epoch — either way the
          possibly-inconsistent bytes are dropped;
        - lease renewal refused (counted ``lease_lost``): a sibling stole
          this session after our lease expired — the zombie-writer guard:
          the chain is DROPPED, never served again here and never spooled
          over the new owner's record.

        A skipped/torn session just costs its client one re-establish if
        the process dies before the next snapshot — the spool never
        carries a half-applied chain.  Records owned by this replica
        whose sessions have since been evicted are swept (record removed,
        lease released).  Returns ``{"written": n, "skipped": n}``.

        Concurrent writers (the background periodic thread, the shutdown
        write, adopt/own/handoff on the serving threads) serialize on
        ``_spool_lock`` PER RECORD — each claim+write is one locked
        section with a liveness + epoch re-check, so a slow older
        capture can never replace a newer record while a serving-thread
        adoption stalls behind at most one record's write, never a whole
        table pass."""
        dir_path = dir_path or self.spool_dir
        if not dir_path:
            return {}
        # a table spools to ONE directory for its lifetime; learning it
        # from the first explicit call keeps eviction/clear lease cleanup
        # working for callers that pass the dir per call (tests, scripts)
        self.spool_dir = self.spool_dir or dir_path
        # _spool_lock is taken PER ENTRY inside (around each claim +
        # write), never across the whole pass: adopt-on-miss and
        # establishment ownership run on the SERVING threads, and a pass
        # pickling KT_DELTA_SESSIONS chains must stall them by at most
        # one record's claim+write, not the whole table's
        return self._snapshot_impl(dir_path)

    def _snapshot_impl(self, dir_path: str) -> dict:
        t0 = time.perf_counter()
        with self._lock:
            live = list(self._sessions.values())
        skipped = self.registry.counter(SNAPSHOT_SKIPPED)
        writes = self.registry.counter(SNAPSHOT_WRITES)
        written, n_skipped, errored = 0, 0, False
        lease_lost: list = []
        for e in live:
            if e.in_step:
                n_skipped += 1
                skipped.inc({"reason": "in_step"})
                continue
            epoch0 = e.epoch
            try:
                blob = snap.pack_entry(dict(
                    session_id=e.session_id, prev=e.prev,
                    epoch=int(epoch0),
                    catalog_epoch=int(e.catalog_epoch),
                    provisioners=list(e.provisioners),
                    instance_types=list(e.instance_types),
                    daemonsets=list(e.daemonsets),
                    unavailable=set(e.unavailable),
                    nonce=str(e.nonce)))
            # ktlint: allow[KT005] a chain mutating under the pickler can
            # raise anything; the entry is discarded as torn and counted
            except Exception:  # noqa: BLE001
                blob = None
            if blob is None or e.in_step or e.epoch != epoch0:
                n_skipped += 1
                skipped.inc({"reason": "torn"})
                continue
            # the slow pickle above ran lock-free; the claim + write are
            # one _spool_lock section so they can never interleave with
            # a concurrent adopt/own/handoff of the SAME session — and a
            # session that left the table while we pickled (drain
            # handoff, eviction) is not re-spooled from its stale bytes
            with self._spool_lock:
                with self._lock:
                    gone = e.session_id not in self._sessions
                if gone:
                    continue
                if e.in_step or e.epoch != epoch0:
                    # the chain moved while we pickled OR while we waited
                    # for the spool lock (a concurrent pass/handoff may
                    # have written a NEWER record) — these bytes must not
                    # land
                    n_skipped += 1
                    skipped.inc({"reason": "torn"})
                    continue
                try:
                    snap.claim_lease(dir_path, e.session_id, self.replica,
                                     self.clock.now(), self.lease_s)
                except snap.LeaseHeld:
                    # stolen after our lease expired (a wedged interval,
                    # a paused container): the session belongs to its
                    # adopter now — write NOTHING over their record.
                    # The drop itself is deferred to after the locked
                    # section: drop("lease_lost") touches no spool state,
                    # and _spool_lock must stay single-acquisition
                    # (KT012) — drop("error") re-acquires it
                    n_skipped += 1
                    skipped.inc({"reason": "lease_lost"})
                    lease_lost.append(e.session_id)
                    continue
                except OSError:
                    # a wedged lease MUTEX (a claimant died inside the
                    # critical section; self-heals after the staleness
                    # breaker) is an infrastructure failure, NOT a lost
                    # lease — the session is KEPT and this pass simply
                    # could not refresh its record
                    logger.warning("lease mutex wedged for %s; record "
                                   "not refreshed this pass",
                                   e.session_id, exc_info=True)
                    errored = True
                    faults_mod.count_recovery(self.registry,
                                              "snapshot_write", "failed")
                    continue
                try:
                    rec = snap.pack([blob],
                                    catalog_epoch=int(e.catalog_epoch))
                    # spool-byte adversary (snapshot_corrupt/_truncate):
                    # mangles AFTER the checksum — restore must detect it
                    rec = self._faults.mangle("snapshot_write", rec)
                    snap.write_record(dir_path, e.session_id, rec)
                # ktlint: allow[KT005] a failing record write must never
                # take serving down; the previous record survives,
                # outcome counted
                except Exception:  # noqa: BLE001
                    logger.warning("session record write (%s) to %s "
                                   "failed", e.session_id, dir_path,
                                   exc_info=True)
                    errored = True
                    faults_mod.count_recovery(self.registry,
                                              "snapshot_write", "failed")
                    continue
                written += 1
                with self._lock:
                    self._owned.add(e.session_id)
                    self._leases_gauge_locked()
                if protocol._SINK is not None:
                    protocol.emit(e.session_id, "spool",
                                  replica=self.replica, epoch=epoch0)
        for sid in lease_lost:
            self.drop(sid, "lease_lost")
        # sweep: owned records whose sessions are GONE (ttl/capacity/
        # wipe-evicted between passes) must not outlive them — a stale
        # record resurrected later is the divergence class restore-once
        # exists to close.  Judged against the LIVE table under _lock,
        # never the pass-start capture: a session established or adopted
        # WHILE this pass pickled is live, and releasing its fresh lease
        # would hand it back to whatever zombie incarnation own() just
        # superseded.  Drain handoffs left _owned already, so a sibling's
        # adopted record is never swept.
        with self._lock:
            stale = [sid for sid in self._owned
                     if sid not in self._sessions]
        for sid in stale:
            snap.remove_record(dir_path, sid)
            snap.release_lease(dir_path, sid, self.replica)
            with self._lock:
                self._owned.discard(sid)
                self._leases_gauge_locked()
        self._gc_orphans(dir_path)
        if errored:
            writes.inc({"outcome": "error"})
        elif not written:
            writes.inc({"outcome": "empty"})
            if not n_skipped:
                self.registry.gauge(SNAPSHOT_SESSIONS).set(0.0)
        else:
            writes.inc({"outcome": "written"})
        if written:
            self.registry.gauge(SNAPSHOT_SESSIONS).set(float(written))
            self.registry.histogram(SNAPSHOT_DURATION).observe(
                time.perf_counter() - t0)
        return {"written": written, "skipped": n_skipped}

    def _gc_orphans(self, dir_path: str) -> None:
        """Expire ORPHANED records: a replica that died uncleanly and
        whose clients never came back leaves records nobody will ever
        adopt (boot restores stop at capacity, adoption is client-driven),
        and a shared PVC must not grow without bound.  A record is
        reaped when it is not ours, its bytes have not been refreshed
        for the session idle TTL (a live sibling rewrites records every
        snapshot pass, so a stale mtime means the writer is gone), AND
        its lease is free or expired.  The session's client — if it ever
        returns — pays the PR-10 one re-establish, exactly what TTL
        eviction has always cost.  Disabled with the TTL (ttl_s=0)."""
        if self.ttl_s <= 0:
            return
        now = self.clock.now()
        for sid in snap.list_sessions(dir_path):
            with self._lock:
                if sid in self._owned or sid in self._sessions:
                    continue
            # the reap decision + removal are one _spool_lock section, so
            # it fully serializes against an in-flight adoption of the
            # same record (adopt holds the lock end to end); the checks
            # re-run inside
            with self._spool_lock:
                age = snap.record_age_s(dir_path, sid)
                if age is None or age <= max(self.ttl_s, self.lease_s):
                    continue
                lease = snap.lease_state(dir_path, sid)
                if lease is not None \
                        and float(lease.get("expires_at", 0.0)) > now:
                    # ANY unexpired lease — a live sibling's, or our own
                    # serving thread's in-flight adoption — is hands-off
                    continue
                snap.remove_record(dir_path, sid)
                snap.release_lease(dir_path, sid,
                                   str((lease or {}).get("owner", "")))
            if protocol._SINK is not None:
                protocol.emit(sid, "reap", replica=self.replica)
            self.registry.counter(DELTA_EVICTIONS).inc({"reason": "ttl"})
            logger.info("reaped orphaned session record %s (idle %.0fs)",
                        sid, age)

    def restore(self, dir_path: Optional[str] = None,
                expected_catalog_epoch: Optional[int] = None) -> int:
        """Rehydrate the table from the spool at startup: scan the
        session records and ADOPT each one whose lease this replica can
        claim.  Sibling-owned sessions (unexpired foreign lease) are left
        untouched — on a shared volume a boot-time restore must never
        poach a live replica's chains.  Records past this table's
        capacity are also left ON DISK with their leases unclaimed, so a
        sibling can adopt what we cannot hold (the PR-12 whole-file spool
        deleted capacity-evicted entries; on a shared spool that would
        destroy a sibling's sessions).  Every envelope refusal (corrupt /
        truncated / version skew / stale catalog epoch) is a counted COLD
        START for that record only — never a crash, never a diverged
        chain.  Returns the number of sessions restored."""
        dir_path = dir_path or self.spool_dir
        if dir_path:
            self.spool_dir = self.spool_dir or dir_path
        if expected_catalog_epoch is None:
            expected_catalog_epoch = self.expected_catalog_epoch
        t0 = time.perf_counter()
        sids = snap.list_sessions(dir_path) if dir_path else []
        if not sids:
            self.registry.counter(SNAPSHOT_RESTORE).inc(
                {"outcome": "missing"})
            return 0
        restored = 0
        for sid in sids:
            if len(self) >= self.capacity:
                # full: leave the remaining records (and their leases)
                # for siblings — adoption respects capacity, it never
                # adopt-then-evicts someone else's chain off the disk
                break
            if self._adopt_impl(dir_path, sid,
                                expected_catalog_epoch) is not None:
                restored += 1
        if restored:
            self.registry.counter(SNAPSHOT_RESTORE).inc(
                {"outcome": "restored"})
            self.registry.histogram(SNAPSHOT_DURATION).observe(
                time.perf_counter() - t0)
            logger.info("restored %d delta session(s) from %s", restored,
                        dir_path)
        return restored

    def adopt(self, dir_path: Optional[str] = None,
              session_id: str = "") -> Optional[SessionEntry]:
        """On-demand single-session adoption — the fleet-failover path:
        a session-routed RPC missing the table tries the shared spool
        before answering ``session_unknown``, so the replica a client
        re-homed to (replica death, graceful drain) serves the next delta
        WARM.  Exactly-one-owner is the lease protocol's job: a free
        lease is claimed, an expired one stolen (counted — the dead-
        replica path), an unexpired foreign one refuses typed (counted
        ``lease_held``; the caller answers unknown and the client pays
        the PR-10 exactly-one re-establish).  Returns the live entry or
        None."""
        dir_path = dir_path or self.spool_dir
        if not dir_path or not session_id:
            return None
        self.spool_dir = self.spool_dir or dir_path
        with self._spool_lock:
            return self._adopt_impl(dir_path, session_id,
                                    self.expected_catalog_epoch)

    def _adopt_impl(self, dir_path: str, session_id: str,
                    expected_catalog_epoch: Optional[int] = None,
                    ) -> Optional[SessionEntry]:
        adoptions = self.registry.counter(SESSION_ADOPTIONS)

        def _count(outcome: str) -> None:
            adoptions.inc({"outcome": outcome})

        if not snap.record_exists(dir_path, session_id):
            # the COMMON miss (a genuinely unknown session — every
            # session_unknown RPC retries this path) short-circuits to
            # one stat: the lease claim's ~6 shared-volume file ops are
            # only paid when there is actually a record to adopt.  The
            # post-claim read below still guards the consumed-between
            # race.
            _count("missing")
            return None
        # provenance BEFORE the claim rewrites it: whose lease guarded the
        # record is the "adopted_from" the lifecycle span + /statusz show
        try:
            prior = snap.lease_state(dir_path, session_id)
        # ktlint: allow[KT005] provenance is observability, not protocol —
        # an unreadable lease file must not fail the adoption
        except Exception:  # noqa: BLE001
            prior = None
        prior_owner = str((prior or {}).get("owner", "") or "")
        if self._faults:
            effect = self._faults.fire("adopt")
            if effect is not None and effect.kind == "lease_steal":
                # the contention adversary: a sibling claims the lease an
                # instant before we do — our claim below must refuse
                try:
                    snap.claim_lease(dir_path, session_id,
                                     "injected-contender",
                                     self.clock.now(), effect.value)
                except snap.LeaseHeld:
                    pass  # someone (maybe us) already holds it — fine
        try:
            how = snap.claim_lease(dir_path, session_id, self.replica,
                                   self.clock.now(), self.lease_s)
        except snap.LeaseHeld as held:
            logger.info("session %s not adopted: lease held by %s",
                        session_id, held.owner)
            _count("lease_held")
            if protocol._SINK is not None:
                protocol.emit(session_id, "adopt_refused",
                              replica=self.replica, owner=held.owner)
            return None
        except OSError:
            # wedged lease mutex: typed cold outcome (the client pays
            # the one re-establish), never an untyped dispatcher error
            logger.warning("lease mutex wedged adopting %s; serving "
                           "cold", session_id, exc_info=True)
            _count("error")
            faults_mod.count_recovery(self.registry, "snapshot_read",
                                      "cold")
            return None
        try:
            blob = snap.read_record(dir_path, session_id)
            if blob is None:
                _count("missing")
                if how != "renewed":
                    snap.release_lease(dir_path, session_id, self.replica)
                return None
            raw_entries, _epoch = snap.unpack(
                blob, expected_catalog_epoch=expected_catalog_epoch)
            d = snap.unpack_entry(raw_entries[0])
            # a restarted process's auto-name counter starts at 0: advance
            # it past every adopted node index so a fresh proposal can
            # never collide with (and silently cross-wire) a chain node
            prev = d.get("prev")
            meta = getattr(prev, "_warmstart_meta", None)
            names = [n.name for n in
                     list(getattr(prev, "nodes", ()) or ())
                     + list(getattr(prev, "existing_nodes", ()) or ())]
            if meta is not None:
                names += [n.name for n in meta.nodes]
            max_idx = -1
            for nm in names:
                if nm.startswith("node-"):
                    try:
                        max_idx = max(max_idx, int(nm[5:]))
                    except ValueError:
                        pass
            if max_idx >= 0:
                advance_node_counter(max_idx)
            now = self.clock.now()
            entry = SessionEntry(
                session_id=d["session_id"], prev=d["prev"],
                epoch=int(d["epoch"]),
                catalog_epoch=int(d["catalog_epoch"]),
                provisioners=d["provisioners"],
                instance_types=d["instance_types"],
                daemonsets=tuple(d.get("daemonsets") or ()),
                unavailable=set(d.get("unavailable") or ()),
                adopt_how="stolen" if how == "stolen" else "adopted",
                adopted_from=(prior_owner
                              if prior_owner != self.replica else ""),
                # legacy (pre-nonce) records adopt with the wildcard
                nonce=str(d.get("nonce", "") or ""),
            )
            with self._lock:
                entry.last_used = now + self._skew
                # the establishment floor clears every adopted epoch: a
                # session re-established after adoption can never advance
                # back onto an epoch its old incarnation reached (the
                # epoch-collision divergence class)
                self._note_epoch_locked(entry.epoch)
                self._sessions[entry.session_id] = entry
                self._sessions.move_to_end(entry.session_id)
                self._owned.add(entry.session_id)
                evicted = 0
                while len(self._sessions) > self.capacity:
                    _sid, old = self._sessions.popitem(last=False)
                    self._note_epoch_locked(old.epoch)
                    evicted += 1
                if evicted:
                    self.registry.counter(DELTA_EVICTIONS).inc(
                        {"reason": "capacity"}, value=float(evicted))
                self._leases_gauge_locked()
                self._gauge_locked()
            # adopt-once: the record is CONSUMED — the chain mutates from
            # here on, and a later crash that never wrote a fresh record
            # must cold-start rather than resurrect this now-stale file
            # (the stale-spool divergence class); our periodic snapshot
            # re-creates it at the next committed epoch
            snap.remove_record(dir_path, session_id)
            _count("stolen" if how == "stolen" else "adopted")
            if protocol._SINK is not None:
                protocol.emit(
                    session_id,
                    "steal" if how == "stolen" else "adopt",
                    replica=self.replica, epoch=entry.epoch,
                    adopted_from=entry.adopted_from)
            return entry
        except snap.SnapshotRefused as err:
            logger.warning("session record %s refused; serving cold: %s",
                           session_id, err)
            self.registry.counter(SNAPSHOT_RESTORE).inc(
                {"outcome": err.reason})
            _count("refused")
            faults_mod.count_recovery(self.registry, "snapshot_read",
                                      "cold")
            if how != "renewed":
                snap.release_lease(dir_path, session_id, self.replica)
            return None
        # ktlint: allow[KT005] an unexpectedly-shaped record is the same
        # outcome as a corrupt one: counted cold start, never a crash
        except Exception:  # noqa: BLE001
            logger.warning("session record %s adoption failed; serving "
                           "cold", session_id, exc_info=True)
            self.registry.counter(SNAPSHOT_RESTORE).inc(
                {"outcome": "error"})
            _count("error")
            faults_mod.count_recovery(self.registry, "snapshot_read",
                                      "cold")
            if how != "renewed":
                snap.release_lease(dir_path, session_id, self.replica)
            return None

    def handoff(self, session_id: str,
                dir_path: Optional[str] = None) -> bool:
        """Graceful-drain handoff of ONE session: spool its record at the
        current (committed) epoch, RELEASE the lease so any sibling
        adopts instantly, and drop the entry (evicted ``drain``) so this
        replica can never serve another epoch of a chain it just gave
        away.  The client saw ``session_state="draining"`` on the same
        reply and re-homes; the adopting replica restores the record and
        serves its next delta WARM.  Returns True when the chain was
        handed off."""
        dir_path = dir_path or self.spool_dir
        if not dir_path:
            return False
        lost = False
        with self._spool_lock:
            with self._lock:
                e = self._sessions.get(session_id)
                if e is None or e.in_step:
                    return False
                blob_src = dict(
                    session_id=e.session_id, prev=e.prev,
                    epoch=int(e.epoch),
                    catalog_epoch=int(e.catalog_epoch),
                    provisioners=list(e.provisioners),
                    instance_types=list(e.instance_types),
                    daemonsets=list(e.daemonsets),
                    unavailable=set(e.unavailable),
                    nonce=str(e.nonce))
                catalog_epoch = int(e.catalog_epoch)
                epoch0 = int(e.epoch)
            try:
                snap.claim_lease(dir_path, session_id, self.replica,
                                 self.clock.now(), self.lease_s)
                rec = snap.pack([snap.pack_entry(blob_src)],
                                catalog_epoch=catalog_epoch)
                rec = self._faults.mangle("snapshot_write", rec)
                snap.write_record(dir_path, session_id, rec)
            except snap.LeaseHeld:
                # a sibling already owns it (stolen while we were
                # wedged): drop without touching their spool state.  The
                # drop runs AFTER the locked section (below): _spool_lock
                # must stay single-acquisition (KT012) and drop("error")
                # re-takes it
                lost = True
            # ktlint: allow[KT005] a failing handoff write degrades to the
            # stop()-path snapshot (the session stays until shutdown);
            # counted so a drain that cannot spool is visible
            except Exception:  # noqa: BLE001
                logger.warning("drain handoff of %s failed; session kept "
                               "for the shutdown snapshot", session_id,
                               exc_info=True)
                faults_mod.count_recovery(self.registry, "snapshot_write",
                                          "failed")
                return False
            if not lost:
                snap.release_lease(dir_path, session_id, self.replica)
                with self._lock:
                    gone = self._sessions.pop(session_id, None)
                    if gone is not None:
                        self._note_epoch_locked(gone.epoch)
                        self.registry.counter(DELTA_EVICTIONS).inc(
                            {"reason": "drain"})
                    self._owned.discard(session_id)
                    self._leases_gauge_locked()
                    self._gauge_locked()
                if protocol._SINK is not None:
                    protocol.emit(session_id, "handoff",
                                  replica=self.replica, epoch=epoch0)
                return True
        self.drop(session_id, "lease_lost")
        faults_mod.count_recovery(self.registry, "snapshot_write",
                                  "skipped")
        return False

    def own(self, session_id: str,
            dir_path: Optional[str] = None) -> None:
        """Take spool ownership of a just-ESTABLISHED session: force-claim
        the lease (the client re-established HERE, so any incarnation a
        sibling's lease guarded is obsolete by the client's own
        authority) and discard the obsolete record.  Without this, a
        session re-established away from its lease holder livelocks:
        the holder renews forever over a zombie entry while the serving
        replica's every snapshot drops the live chain as lease-lost."""
        dir_path = dir_path or self.spool_dir
        if not dir_path:
            return
        with self._spool_lock:
            try:
                snap.claim_lease(dir_path, session_id, self.replica,
                                 self.clock.now(), self.lease_s,
                                 force=True)
            # ktlint: allow[KT005] a lost claim race or I/O failure just
            # defers ownership to the next snapshot pass; serving goes on
            except Exception:  # noqa: BLE001
                logger.warning("could not take spool ownership of %s",
                               session_id, exc_info=True)
                return
            snap.remove_record(dir_path, session_id)
            with self._lock:
                self._owned.add(session_id)
                self._leases_gauge_locked()
        if protocol._SINK is not None:
            protocol.emit(session_id, "claim", replica=self.replica)

    def leases_owned(self) -> int:
        with self._lock:
            return len(self._owned)

    def sessions_status(self) -> Dict[str, dict]:
        """Per-session diagnostic view for the /statusz session block
        (ISSUE 15): chain epoch, seconds since the last served delta,
        the current lease owner (this replica when we hold the spool
        lease), and — for failed-over chains — which replica it was
        adopted/stolen from, so a stuck chain is diagnosable from one
        HTTP GET instead of grepping the spool.  Reads table state only
        (no disk); entry CONTENTS are limited to scalars the dispatcher
        writes atomically, so the snapshot under ``_lock`` is safe."""
        now = self.clock.now()
        with self._lock:
            now += self._skew
            return {
                sid: {
                    "epoch": int(e.epoch),
                    "last_delta_age_s": round(max(0.0, now - e.last_used),
                                              3),
                    "lease_owner": (self.replica if sid in self._owned
                                    else ""),
                    "adopted_from": e.adopted_from,
                    "adopt_how": e.adopt_how,
                    "in_step": bool(e.in_step),
                }
                for sid, e in self._sessions.items()
            }


def zero_init_metrics(registry: Registry) -> None:
    """Register every delta-serving series at 0 from construction (KT003:
    a counter born at its first increment loses that increment to
    rate()/increase())."""
    rpc = registry.counter(DELTA_RPC)
    for outcome in DELTA_RPC_OUTCOMES:
        if not rpc.has({"outcome": outcome}):
            rpc.inc({"outcome": outcome}, value=0.0)
    evict = registry.counter(DELTA_EVICTIONS)
    for reason in DELTA_EVICT_REASONS:
        if not evict.has({"reason": reason}):
            evict.inc({"reason": reason}, value=0.0)
    gauge = registry.gauge(DELTA_SESSIONS)
    if not gauge.has():
        gauge.set(0)
    registry.histogram(DELTA_RPC_DURATION)
    # session durability families (ISSUE 12): the first snapshot write /
    # restore refusal of a replica's life must survive rate()
    writes = registry.counter(SNAPSHOT_WRITES)
    for outcome in SNAPSHOT_WRITE_OUTCOMES:
        if not writes.has({"outcome": outcome}):
            writes.inc({"outcome": outcome}, value=0.0)
    skipped = registry.counter(SNAPSHOT_SKIPPED)
    for reason in SNAPSHOT_SKIP_REASONS:
        if not skipped.has({"reason": reason}):
            skipped.inc({"reason": reason}, value=0.0)
    restore = registry.counter(SNAPSHOT_RESTORE)
    for outcome in SNAPSHOT_RESTORE_OUTCOMES:
        if not restore.has({"outcome": outcome}):
            restore.inc({"outcome": outcome}, value=0.0)
    sg = registry.gauge(SNAPSHOT_SESSIONS)
    if not sg.has():
        sg.set(0)
    registry.histogram(SNAPSHOT_DURATION)
    # fleet-handoff families (ISSUE 13): the first adoption/steal of a
    # replica's life must survive rate()
    adoptions = registry.counter(SESSION_ADOPTIONS)
    for outcome in SESSION_ADOPTION_OUTCOMES:
        if not adoptions.has({"outcome": outcome}):
            adoptions.inc({"outcome": outcome}, value=0.0)
    lg = registry.gauge(SESSION_LEASES)
    if not lg.has():
        lg.set(0)
    # recovery-outcome population (KT016's funnel is live in production —
    # organic faults count too, so the series must exist from birth)
    faults_mod.zero_init_recovery(registry)
