"""Solver sidecar — gRPC server wrapping the batch scheduler.

The reconciler-facing service boundary (SURVEY.md §2.3 component (1)).
Stubs are registered manually via a generic handler since grpc_tools isn't in
the image; the method table matches the comment block in solver.proto.

Run standalone:  python -m karpenter_tpu.service.server --port 50151
"""

from __future__ import annotations

import argparse
import time
from concurrent import futures
from typing import Optional

import grpc

from ..metrics import Registry, registry as default_registry
from ..solver.scheduler import BatchScheduler
from . import codec
from . import solver_pb2 as pb

SERVICE = "karpenter.tpu.Solver"


class SolverService:
    def __init__(self, scheduler: Optional[BatchScheduler] = None,
                 registry: Optional[Registry] = None) -> None:
        self.registry = registry or default_registry
        self.scheduler = scheduler or BatchScheduler(registry=self.registry)
        self._schedulers = {"": self.scheduler}

    def _scheduler_for(self, backend: str) -> BatchScheduler:
        if backend and backend != self.scheduler.backend:
            if backend not in self._schedulers:
                self._schedulers[backend] = BatchScheduler(
                    backend=backend, registry=self.registry
                )
            return self._schedulers[backend]
        return self.scheduler

    # ---- RPC methods -----------------------------------------------------
    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        kwargs = codec.decode_request(request)
        sched = self._scheduler_for(request.backend)
        result = sched.solve(
            kwargs.pop("pods"), kwargs.pop("provisioners"), kwargs.pop("instance_types"),
            **kwargs,
        )
        return codec.encode_response(result)

    def Warm(self, request: pb.WarmRequest, context) -> pb.WarmResponse:
        """Forwarded warm_startup: the operator ships its live provisioners,
        catalog, and cluster snapshots; compiles run behind on the sidecar's
        chips (BatchScheduler.warm_startup semantics, including signature
        dedupe, so repeated Warm calls are cheap)."""
        kwargs = codec.decode_warm_request(request)
        sched = self._scheduler_for(request.backend)
        started = sched.warm_startup(
            kwargs.pop("provisioners"), kwargs.pop("instance_types"), **kwargs
        )
        return pb.WarmResponse(started=started)

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            ok=True, backend=jax.default_backend(), devices=len(jax.devices())
        )


def make_server(
    service: Optional[SolverService] = None,
    port: int = 0,
    max_workers: int = 4,
    host: str = "127.0.0.1",
) -> "tuple[grpc.Server, int]":
    service = service or SolverService()
    handlers = {
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.Solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Warm": grpc.unary_unary_rpc_method_handler(
            service.Warm,
            request_deserializer=pb.WarmRequest.FromString,
            response_serializer=pb.WarmResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.Health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                 ("grpc.max_send_message_length", 256 * 1024 * 1024)],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument("--port", type=int, default=50151)
    # 0.0.0.0: the deployed topology dials this across pods
    # (deploy/operator.yaml -> Service karpenter-tpu-solver); loopback would
    # strand the operator on its local fallback forever
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--backend", default="auto", choices=["auto", "tpu", "oracle"])
    args = parser.parse_args(argv)
    service = SolverService(BatchScheduler(backend=args.backend))
    server, port = make_server(service, port=args.port, host=args.host)
    print(f"solver sidecar listening on {args.host}:{port} (backend={args.backend})")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(grace=2.0)
        for sched in service._schedulers.values():
            sched.stop_warms()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
