"""Solver sidecar — gRPC server wrapping the batch scheduler.

The reconciler-facing service boundary (SURVEY.md §2.3 component (1)).
Stubs are registered manually via a generic handler since grpc_tools isn't in
the image; the method table matches the comment block in solver.proto.

Run standalone:  python -m karpenter_tpu.service.server --port 50151
"""

from __future__ import annotations

import argparse
import os
import queue
import threading
import time
from concurrent import futures
from concurrent.futures import Future
from typing import Optional

import grpc

from ..batcher import InflightQueue, SlotCoalescer
from ..metrics import (
    INFLIGHT_DEPTH,
    MEGABATCH_FLUSH,
    MEGABATCH_SLOTS,
    Registry,
    registry as default_registry,
)
from ..obs import tracer_for
from ..obs.trace import NULL_TRACE, Tracer
from ..solver.scheduler import BatchScheduler
from ..solver.tpu import MEGA_MAX_SLOTS
from ..utils.clock import Clock
from . import codec
from . import solver_pb2 as pb

SERVICE = "karpenter.tpu.Solver"

#: default megabatch request-slot cap per coalescer flush (KT_MAX_SLOTS /
#: --max-slots override; 1 disables cross-request batching)
DEFAULT_MAX_SLOTS = 8
#: default max-wait before a partially-filled batch flushes, milliseconds
#: (KT_MAX_WAIT_MS / --max-wait-ms).  0 = flush the moment the inbound
#: queue goes idle — single-request latency then matches the unbatched
#: path; coalescing engages exactly when requests actually queue up.
DEFAULT_MAX_WAIT_MS = 0.0


def _resolve(fut: Future, result=None, exc: Optional[BaseException] = None) -> None:
    """Resolve a future exactly once, tolerating the racer.  stop() and the
    dispatcher's _finalize can reach the same future concurrently (a fence
    completing at the instant the 5s join gives up); done()-check-then-set
    is not atomic, so the loser's set raises InvalidStateError — swallow it:
    either resolution unblocks the RPC thread, which is all that matters."""
    try:
        if not fut.done():
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
    except futures.InvalidStateError:
        pass  # the other side resolved it first


class SolvePipeline:
    """Double-buffered, cross-request-batching solve dispatch for one
    scheduler.

    All scheduler access funnels through ONE dispatcher thread (the
    scheduler is not re-entrant — concurrent RPC handlers previously raced
    on it).  Two throughput mechanisms compose behind it:

    - **Pipelining** (PR 1): ``scheduler.submit`` returns after the async
      device dispatch; the dispatcher tensorizes batch N+1 while batch N
      executes, fencing via the in-flight queue.  Serves the low-concurrency
      regime.
    - **Cross-request megabatching** (this round): a deadline-aware
      :class:`~karpenter_tpu.batcher.SlotCoalescer` drains concurrent RPCs
      into request slots (flush on max-slots, max-wait, or shape-bucket
      change) and ``scheduler.submit_many`` solves the whole flush in ONE
      vmapped device dispatch — service throughput stops being capped at
      one solve per device round trip.  Engages exactly when requests
      queue; a lone request flushes immediately (``max_wait=0`` default),
      so single-request latency matches the unbatched path.

    Responses keep arrival order (singles and megabatches share ONE
    FIFO in-flight queue), and every megabatched response carries the
    honest per-request ``solve_ms``: enqueue→respond wall time, NOT the
    megabatch-amortized device time.
    """

    def __init__(self, scheduler: BatchScheduler,
                 registry: Optional[Registry] = None, depth: int = 2,
                 max_slots: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 clock: Optional[Clock] = None) -> None:
        self.scheduler = scheduler
        self.registry = registry or default_registry
        if max_slots is None:
            max_slots = int(os.environ.get("KT_MAX_SLOTS",
                                           str(DEFAULT_MAX_SLOTS)))
        if max_wait_ms is None:
            max_wait_ms = float(os.environ.get("KT_MAX_WAIT_MS",
                                               str(DEFAULT_MAX_WAIT_MS)))
        self.max_slots = max(1, min(MEGA_MAX_SLOTS, max_slots))
        self.max_wait = max(0.0, max_wait_ms) / 1000.0
        self._clock = clock or Clock()
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._submit_lock = threading.Lock()  # makes stop-check + put atomic
        #: futures the dispatcher has popped (from _q or _inflight) but not
        #: yet resolved — the dispatcher's hand.  Written by the dispatcher
        #: only; stop() snapshots it after the join times out so a wedge at
        #: ANY point between pop and resolution (inside submit's device
        #: dispatch, inside a fence, between an _inflight drain and its
        #: finalize) can't strand an RPC thread.  _resolve tolerates the
        #: benign race with a merely-slow dispatcher.  Coalesced-but-not-
        #: yet-flushed requests are in it too — a stop() mid-hold fails
        #: them instead of stranding them in the coalescer.
        self._in_hand: "list[Future]" = []
        gauge = self.registry.gauge(INFLIGHT_DEPTH)
        labels = {"backend": scheduler.backend}  # one series per backend
        if not gauge.has(labels):
            # only when absent: a second pipeline on a shared registry must
            # not zero a live series (same guard as BatchScheduler.__init__)
            gauge.set(0, labels)
        self._inflight: InflightQueue = InflightQueue(
            depth=depth, on_depth=lambda d: gauge.set(d, labels))
        #: dispatcher-owned: batch boundaries for the megabatch path
        self._coal: SlotCoalescer = SlotCoalescer(
            max_slots=self.max_slots, max_wait=self.max_wait,
            clock=self._clock)
        # zero-init every flush-reason series (KT003: a counter born at its
        # first increment loses that increment to rate()/increase())
        flush = self.registry.counter(MEGABATCH_FLUSH)
        for reason in ("full", "deadline", "bucket"):
            flush.inc({"reason": reason}, value=0.0)
        self.registry.histogram(MEGABATCH_SLOTS)
        self._thread = threading.Thread(
            target=self._loop, name="solve-pipeline", daemon=True)
        self._thread.start()

    def solve(self, kwargs: dict):
        """RPC-thread entry: enqueue and block for this request's result."""
        fut: Future = Future()
        # queue-wait attribution: stamp the enqueue on the request's trace
        # clock here (RPC thread); the dispatcher closes the "window" span
        # when it picks the request up — the cross-thread phase is recorded
        # as an already-closed span, so nothing can leak.  The perf_counter
        # stamp feeds the megabatch path's honest enqueue→respond solve_ms.
        trace = kwargs.get("trace") or NULL_TRACE
        t_enq = trace.now()
        t_wall = time.perf_counter()
        # the stop-check and the put are one atomic step: a put that wins
        # the lock before stop()'s drain is guaranteed to be seen by the
        # drain; a put that loses sees _stop and refuses — either way no
        # future is ever left unresolved (an RPC thread blocked forever on
        # fut.result() would pin process exit)
        with self._submit_lock:
            if self._stop.is_set():
                raise RuntimeError("solve pipeline stopped")
            self._q.put((kwargs, fut, t_enq, t_wall))
        return fut.result()

    def stop(self) -> None:
        """Stop the dispatcher.  Requests still queued OR in flight are
        FAILED, not abandoned — a blocked RPC thread waiting on an
        unresolved future would pin process exit forever."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # dispatcher wedged (e.g. a device fence behind a dead tunnel,
            # forced backend so no guard, or an H2D dispatch inside
            # scheduler.submit): fail everything still in flight so the RPC
            # threads unblock; the daemon dispatcher thread itself cannot
            # pin exit.  deque ops are thread-safe, and every entry the
            # wedged thread already popped is still in its _in_hand ledger
            # (coalescer-held requests included).
            for head, rest in self._inflight.pop_to(0):
                if head == "mega":
                    for (_kw, fut, _t, _w), _pending in rest:
                        _resolve(fut,
                                 exc=RuntimeError("solve pipeline stopped"))
                else:
                    _resolve(rest, exc=RuntimeError("solve pipeline stopped"))
            for fut in list(self._in_hand):
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))
        with self._submit_lock:
            while True:
                try:
                    _kwargs, fut, _t_enq, _t_wall = self._q.get_nowait()
                except queue.Empty:
                    break
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))

    def _finalize(self, pending, fut: Future) -> None:
        try:
            try:
                result = pending.result()
            # ktlint: allow[KT005] the dispatcher must survive any fence
            # outcome; the exception is handed to the blocked RPC thread via
            # its future and re-raised there
            except BaseException as err:  # noqa: BLE001 — fan to the RPC
                _resolve(fut, exc=err)
                return
            _resolve(fut, result=result)
        finally:
            # resolved either way: out of the dispatcher's hand
            try:
                self._in_hand.remove(fut)
            except ValueError:
                pass  # already failed by a concurrent stop()

    def _bucket_of(self, kwargs: dict):
        """Megabatch bucket probe — None routes the request down the classic
        single path (also when the scheduler has no bucketing: RemoteScheduler
        facades, test doubles)."""
        if self.max_slots <= 1:
            return None
        bucket = getattr(self.scheduler, "bucket_key", None)
        if bucket is None:
            return None
        # the probe itself never fails a request (bucket_key boxes its own
        # errors and returns None), but a facade without that contract must
        # not take the dispatcher down either
        try:
            return bucket(kwargs)
        # ktlint: allow[KT005] probe failure = unbatchable, logged at the
        # scheduler layer; the request solves on the single path
        except Exception:
            return None

    def _flush(self, batch, reason: str) -> None:
        """Dispatch one coalescer flush: a single request keeps the classic
        pipelined submit; 2+ requests ride one scheduler.submit_many
        megabatch dispatch.  NEITHER fences here — both park in the
        in-flight queue so the dispatcher coalesces/tensorizes the next
        batch while this one executes; megabatched responses get honest
        enqueue→respond solve_ms at finalization."""
        if not batch:
            return
        self.registry.counter(MEGABATCH_FLUSH).inc({"reason": reason})
        if len(batch) == 1:
            self._dispatch_single(*batch[0])
            return
        try:
            pendings = self.scheduler.submit_many(
                [kw for kw, _f, _t, _w in batch])
        # ktlint: allow[KT005] submit failures fan to every waiting RPC
        # thread through their futures; the dispatcher itself must live on
        except BaseException as err:  # noqa: BLE001
            for _kw, fut, _t, _w in batch:
                _resolve(fut, exc=err)
                self._unhand(fut)
            return
        # one in-flight entry for the WHOLE megabatch (depth counts device
        # dispatches, and the megabatch is one); finalization order stays
        # FIFO because singles and megabatches share the one queue
        self._drain(self._inflight.push(("mega", list(zip(batch, pendings)))))
        if self._q.empty() and not len(self._coal):
            self._drain(self._inflight.pop_to(0))

    def _unhand(self, fut: Future) -> None:
        try:
            self._in_hand.remove(fut)
        except ValueError:
            pass  # already failed by a concurrent stop()

    def _drain(self, entries) -> None:
        for entry in entries:
            head, rest = entry
            if head == "mega":
                self._finalize_mega(rest)
            else:
                self._finalize(head, rest)

    def _finalize_mega(self, pairs) -> None:
        for (kwargs, fut, _t_enq, t_wall), pending in pairs:
            try:
                result = pending.result()
                # honest per-request latency: this RPC's enqueue → respond
                # wall time, not the megabatch-amortized device time
                result.solve_ms = (time.perf_counter() - t_wall) * 1000.0
            # ktlint: allow[KT005] per-request failure fans to ITS RPC
            # thread only; batchmates still resolve
            except BaseException as err:  # noqa: BLE001
                _resolve(fut, exc=err)
            else:
                _resolve(fut, result=result)
            self._unhand(fut)

    def _dispatch_single(self, kwargs: dict, fut: Future, t_enq, t_wall) -> None:
        try:
            pending = self.scheduler.submit(
                kwargs.pop("pods"), kwargs.pop("provisioners"),
                kwargs.pop("instance_types"), **kwargs,
            )
        # ktlint: allow[KT005] submit failures fan to the waiting RPC
        # thread through its future; the dispatcher itself must live on
        except BaseException as err:  # noqa: BLE001
            _resolve(fut, exc=err)
            self._unhand(fut)
            return
        self._drain(self._inflight.push((pending, fut)))
        if self._q.empty() and not len(self._coal):
            # no overlap work available: drain so this caller's latency
            # is one dispatch + one fence, exactly the unpipelined path
            self._drain(self._inflight.pop_to(0))

    def _loop(self) -> None:
        while not self._stop.is_set():
            deadline = self._coal.deadline()
            if deadline is not None:
                timeout = min(0.1, max(0.0, deadline - self._clock.now()))
            else:
                timeout = 0.1
            try:
                kwargs, fut, t_enq, t_wall = self._q.get(timeout=timeout)
            except queue.Empty:
                for reason, _key, batch in self._coal.poll():
                    self._flush(batch, reason)
                if not len(self._coal):
                    self._drain(self._inflight.pop_to(0))
                continue
            # close the queue-wait phase on the request's trace: enqueue
            # (RPC thread) -> pickup (this dispatcher)
            trace = kwargs.get("trace") or NULL_TRACE
            trace.record("window", t_enq, trace.now(),
                         inflight=len(self._inflight),
                         coalesced=len(self._coal))
            # in hand from pop to resolution (_flush/_finalize remove it);
            # coalescer-held requests stay in the ledger so a stop() mid-
            # hold fails them instead of stranding their RPC threads.  A
            # fut parked in _inflight is in the ledger too — stop() may
            # fail it twice (once per structure), which _resolve absorbs.
            self._in_hand.append(fut)
            key = self._bucket_of(kwargs)
            for reason, _key, batch in self._coal.add(
                    key, (kwargs, fut, t_enq, t_wall)):
                self._flush(batch, reason)
            if len(self._coal) and self._q.empty() and self.max_wait <= 0.0:
                # queue went idle with no wait configured: flush NOW so a
                # lone request's latency matches the unbatched path; under
                # real concurrency the queue is non-empty here and slots
                # keep filling
                for reason, _key, batch in self._coal.flush("deadline"):
                    self._flush(batch, reason)
        for reason, _key, batch in self._coal.flush("deadline"):
            self._flush(batch, reason)
        self._drain(self._inflight.pop_to(0))


class SolverService:
    def __init__(self, scheduler: Optional[BatchScheduler] = None,
                 registry: Optional[Registry] = None,
                 tracer: Optional[Tracer] = None,
                 max_slots: Optional[int] = None,
                 max_wait_ms: Optional[float] = None) -> None:
        self.registry = registry or default_registry
        self.scheduler = scheduler or BatchScheduler(registry=self.registry)
        # serving knobs for every pipeline this service constructs (None:
        # KT_MAX_SLOTS / KT_MAX_WAIT_MS env, then the module defaults)
        self.max_slots = max_slots
        self.max_wait_ms = max_wait_ms
        # per-RPC traces; default to the scheduler's tracer so the sidecar's
        # /tracez sees exactly what its scheduler recorded
        self.tracer = tracer or getattr(
            self.scheduler, "tracer", None) or tracer_for(self.registry)
        self._schedulers = {"": self.scheduler}  # guarded-by: _direct_lock
        # KT_SOLVE_PIPELINE=0 falls back to direct, lock-serialized solves
        self._pipelined = os.environ.get("KT_SOLVE_PIPELINE", "1") != "0"
        self._pipelines: dict = {}               # guarded-by: _direct_lock
        self._closed = False                     # guarded-by: _direct_lock
        self._direct_lock = threading.Lock()

    def _scheduler_for(self, backend: str) -> BatchScheduler:
        if backend and backend != self.scheduler.backend:
            # locked check-then-create: two concurrent first RPCs for the
            # same backend must share ONE scheduler (and therefore one
            # pipeline — _pipeline_for keys on the scheduler instance; a
            # lost race here would leak a live dispatcher thread forever)
            with self._direct_lock:
                if backend not in self._schedulers:
                    self._schedulers[backend] = BatchScheduler(
                        backend=backend, registry=self.registry
                    )
                return self._schedulers[backend]
        return self.scheduler

    def _pipeline_for(self, sched: BatchScheduler) -> SolvePipeline:
        with self._direct_lock:  # concurrent first RPCs must share one pipe
            if self._closed:
                # a Solve racing close() must not construct a fresh pipeline
                # AFTER close()'s snapshot — its dispatcher thread would
                # outlive the service with nothing left to stop it
                raise RuntimeError("solver service closed")
            pipe = self._pipelines.get(id(sched))
            if pipe is None:
                pipe = SolvePipeline(sched, registry=self.registry,
                                     max_slots=self.max_slots,
                                     max_wait_ms=self.max_wait_ms)
                self._pipelines[id(sched)] = pipe
            return pipe

    def close(self) -> None:
        # latch closed + snapshot under the lock (a late first RPC racing
        # shutdown must neither resize the dict mid-iteration nor construct
        # a never-stopped pipeline after the snapshot), stop outside it —
        # stop() joins the dispatcher, and a join under _direct_lock would
        # deadlock against a dispatcher-path call that takes the lock
        with self._direct_lock:
            self._closed = True
            pipes = list(self._pipelines.values())
        for pipe in pipes:
            pipe.stop()

    # ---- RPC methods -----------------------------------------------------
    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        kwargs = codec.decode_request(request)
        sched = self._scheduler_for(request.backend)
        # one trace per RPC, threaded through the pipeline's dispatch/
        # finalize boundary via the kwargs dict (the dispatcher records the
        # queue-wait "window" span on it; the scheduler opens tensorize/
        # dispatch/fence/reseat under it); "respond" covers the encode back
        # onto the wire
        with self.tracer.start(
            "solve", rpc="Solve", backend=sched.backend,
            n_pods=len(kwargs.get("pods", ())),
        ) as trace:
            kwargs["trace"] = trace
            if self._pipelined:
                result = self._pipeline_for(sched).solve(kwargs)
            else:
                with self._direct_lock:
                    result = sched.solve(
                        kwargs.pop("pods"), kwargs.pop("provisioners"),
                        kwargs.pop("instance_types"), **kwargs,
                    )
            with trace.span("respond"):
                resp = codec.encode_response(result)
        return resp

    def Warm(self, request: pb.WarmRequest, context) -> pb.WarmResponse:
        """Forwarded warm_startup: the operator ships its live provisioners,
        catalog, and cluster snapshots; compiles run behind on the sidecar's
        chips (BatchScheduler.warm_startup semantics, including signature
        dedupe, so repeated Warm calls are cheap)."""
        kwargs = codec.decode_warm_request(request)
        sched = self._scheduler_for(request.backend)
        started = sched.warm_startup(
            kwargs.pop("provisioners"), kwargs.pop("instance_types"), **kwargs
        )
        return pb.WarmResponse(started=started)

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            ok=True, backend=jax.default_backend(), devices=len(jax.devices())
        )


def make_server(
    service: Optional[SolverService] = None,
    port: int = 0,
    # enough RPC threads to fill a full megabatch: handlers just block on
    # the pipeline's futures (the dispatcher does the work), so idle-parked
    # threads are cheap — but 4 workers would cap the coalescer's reachable
    # occupancy at 4 no matter how many clients queue
    max_workers: int = MEGA_MAX_SLOTS + 4,
    host: str = "127.0.0.1",
) -> "tuple[grpc.Server, int]":
    service = service or SolverService()
    handlers = {
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.Solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Warm": grpc.unary_unary_rpc_method_handler(
            service.Warm,
            request_deserializer=pb.WarmRequest.FromString,
            response_serializer=pb.WarmResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.Health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                 ("grpc.max_send_message_length", 256 * 1024 * 1024)],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument("--port", type=int, default=50151)
    # 0.0.0.0: the deployed topology dials this across pods
    # (deploy/operator.yaml -> Service karpenter-tpu-solver); loopback would
    # strand the operator on its local fallback forever
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--backend", default="auto", choices=["auto", "tpu", "oracle"])
    parser.add_argument("--obs-port", type=int, default=0,
                        help="observability HTTP port (/tracez, /statusz, "
                             "/metrics); 0 disables")
    parser.add_argument("--max-slots", type=int, default=None,
                        help="megabatch request slots per coalescer flush "
                             f"(default KT_MAX_SLOTS or {DEFAULT_MAX_SLOTS}; "
                             "1 disables cross-request batching)")
    parser.add_argument("--max-wait-ms", type=float, default=None,
                        help="max hold before a partial batch flushes "
                             f"(default KT_MAX_WAIT_MS or "
                             f"{DEFAULT_MAX_WAIT_MS:g}; 0 flushes the "
                             "moment the inbound queue idles)")
    parser.add_argument("--warmup", action="store_true",
                        help="block until the AOT bucket-grid precompile "
                             "lands (single-solve ladder + megabatch slot "
                             "rungs against the generated catalog) before "
                             "accepting traffic; pair with --jit-cache-dir "
                             "to skip even this across restarts")
    parser.add_argument("--small", action="store_true",
                        help="--warmup against the 20-type catalog")
    args = parser.parse_args(argv)
    service = SolverService(BatchScheduler(backend=args.backend),
                            max_slots=args.max_slots,
                            max_wait_ms=args.max_wait_ms)
    if args.warmup:
        from ..models.catalog import generate_catalog
        from ..models.provisioner import Provisioner

        print("warmup: AOT bucket-grid precompile running "
              "(single ladder + megabatch rungs)...", flush=True)
        n = service.scheduler.precompile_buckets(
            [Provisioner(name="default").with_defaults()],
            generate_catalog(full=not args.small),
            wait=True,
        )
        print(f"warmup: {n} bucket programs compiled; serving", flush=True)
    server, port = make_server(service, port=args.port, host=args.host)
    print(f"solver sidecar listening on {args.host}:{port} (backend={args.backend})")
    if args.obs_port:
        from ..obs import default_flight
        from ..obs.export import serve as obs_serve

        flight = service.tracer.flight or default_flight()
        _obs_server, obs_port = obs_serve(
            service.registry, flight, port=args.obs_port, host=args.host)
        print(f"observability on http://{args.host}:{obs_port}/tracez")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(grace=2.0)
        service.close()
        for sched in service._schedulers.values():
            sched.stop_warms()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
