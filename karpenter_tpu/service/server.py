"""Solver sidecar — gRPC server wrapping the batch scheduler.

The reconciler-facing service boundary (SURVEY.md §2.3 component (1)).
Stubs are registered manually via a generic handler since grpc_tools isn't in
the image; the method table matches the comment block in solver.proto.

Run standalone:  python -m karpenter_tpu.service.server --port 50151
"""

from __future__ import annotations

import argparse
import os
import queue
import threading
import time
from concurrent import futures
from concurrent.futures import Future
from typing import Optional

import grpc

from ..batcher import InflightQueue
from ..metrics import INFLIGHT_DEPTH, Registry, registry as default_registry
from ..obs import tracer_for
from ..obs.trace import NULL_TRACE, Tracer
from ..solver.scheduler import BatchScheduler
from . import codec
from . import solver_pb2 as pb

SERVICE = "karpenter.tpu.Solver"


def _resolve(fut: Future, result=None, exc: Optional[BaseException] = None) -> None:
    """Resolve a future exactly once, tolerating the racer.  stop() and the
    dispatcher's _finalize can reach the same future concurrently (a fence
    completing at the instant the 5s join gives up); done()-check-then-set
    is not atomic, so the loser's set raises InvalidStateError — swallow it:
    either resolution unblocks the RPC thread, which is all that matters."""
    try:
        if not fut.done():
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
    except futures.InvalidStateError:
        pass  # the other side resolved it first


class SolvePipeline:
    """Double-buffered solve dispatch for one scheduler.

    All scheduler access funnels through ONE dispatcher thread (the
    scheduler is not re-entrant — concurrent RPC handlers previously raced
    on it), and device dispatch is pipelined: the dispatcher calls
    ``scheduler.submit`` (host tensorize + async device dispatch, returns
    before the fence), immediately picks up the NEXT queued request, and
    only fences batch N when the in-flight queue is past ``depth`` or the
    inbound queue drains.  Host tensorize of batch N+1 therefore overlaps
    device execution of batch N; each response still carries its own honest
    one-RTT-fenced ``solve_ms`` (PendingTpuSolve.result semantics).
    Finalization is FIFO, so responses keep arrival order.
    """

    def __init__(self, scheduler: BatchScheduler,
                 registry: Optional[Registry] = None, depth: int = 2) -> None:
        self.scheduler = scheduler
        self.registry = registry or default_registry
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._submit_lock = threading.Lock()  # makes stop-check + put atomic
        #: futures the dispatcher has popped (from _q or _inflight) but not
        #: yet resolved — the dispatcher's hand.  Written by the dispatcher
        #: only; stop() snapshots it after the join times out so a wedge at
        #: ANY point between pop and resolution (inside submit's device
        #: dispatch, inside a fence, between an _inflight drain and its
        #: finalize) can't strand an RPC thread.  _resolve tolerates the
        #: benign race with a merely-slow dispatcher.
        self._in_hand: "list[Future]" = []
        gauge = self.registry.gauge(INFLIGHT_DEPTH)
        labels = {"backend": scheduler.backend}  # one series per backend
        if not gauge.has(labels):
            # only when absent: a second pipeline on a shared registry must
            # not zero a live series (same guard as BatchScheduler.__init__)
            gauge.set(0, labels)
        self._inflight: InflightQueue = InflightQueue(
            depth=depth, on_depth=lambda d: gauge.set(d, labels))
        self._thread = threading.Thread(
            target=self._loop, name="solve-pipeline", daemon=True)
        self._thread.start()

    def solve(self, kwargs: dict):
        """RPC-thread entry: enqueue and block for this request's result."""
        fut: Future = Future()
        # queue-wait attribution: stamp the enqueue on the request's trace
        # clock here (RPC thread); the dispatcher closes the "window" span
        # when it picks the request up — the cross-thread phase is recorded
        # as an already-closed span, so nothing can leak
        trace = kwargs.get("trace") or NULL_TRACE
        t_enq = trace.now()
        # the stop-check and the put are one atomic step: a put that wins
        # the lock before stop()'s drain is guaranteed to be seen by the
        # drain; a put that loses sees _stop and refuses — either way no
        # future is ever left unresolved (an RPC thread blocked forever on
        # fut.result() would pin process exit)
        with self._submit_lock:
            if self._stop.is_set():
                raise RuntimeError("solve pipeline stopped")
            self._q.put((kwargs, fut, t_enq))
        return fut.result()

    def stop(self) -> None:
        """Stop the dispatcher.  Requests still queued OR in flight are
        FAILED, not abandoned — a blocked RPC thread waiting on an
        unresolved future would pin process exit forever."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # dispatcher wedged (e.g. a device fence behind a dead tunnel,
            # forced backend so no guard, or an H2D dispatch inside
            # scheduler.submit): fail everything still in flight so the RPC
            # threads unblock; the daemon dispatcher thread itself cannot
            # pin exit.  deque ops are thread-safe, and every entry the
            # wedged thread already popped is still in its _in_hand ledger.
            for _pending, fut in self._inflight.pop_to(0):
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))
            for fut in list(self._in_hand):
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))
        with self._submit_lock:
            while True:
                try:
                    _kwargs, fut, _t_enq = self._q.get_nowait()
                except queue.Empty:
                    break
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))

    def _finalize(self, pending, fut: Future) -> None:
        try:
            try:
                result = pending.result()
            # ktlint: allow[KT005] the dispatcher must survive any fence
            # outcome; the exception is handed to the blocked RPC thread via
            # its future and re-raised there
            except BaseException as err:  # noqa: BLE001 — fan to the RPC
                _resolve(fut, exc=err)
                return
            _resolve(fut, result=result)
        finally:
            # resolved either way: out of the dispatcher's hand
            try:
                self._in_hand.remove(fut)
            except ValueError:
                pass  # already failed by a concurrent stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                kwargs, fut, t_enq = self._q.get(timeout=0.1)
            except queue.Empty:
                for pending, f in self._inflight.pop_to(0):
                    self._finalize(pending, f)
                continue
            # close the queue-wait phase on the request's trace: enqueue
            # (RPC thread) -> pickup (this dispatcher)
            trace = kwargs.get("trace") or NULL_TRACE
            trace.record("window", t_enq, trace.now(),
                         inflight=len(self._inflight))
            # in hand from pop to resolution; _finalize removes it.  A fut
            # parked in _inflight stays in the ledger too — stop() may then
            # fail it twice (once per structure), which _resolve absorbs.
            self._in_hand.append(fut)
            try:
                pending = self.scheduler.submit(
                    kwargs.pop("pods"), kwargs.pop("provisioners"),
                    kwargs.pop("instance_types"), **kwargs,
                )
            # ktlint: allow[KT005] submit failures fan to the waiting RPC
            # thread through its future; the dispatcher itself must live on
            except BaseException as err:  # noqa: BLE001
                _resolve(fut, exc=err)
                try:
                    self._in_hand.remove(fut)
                except ValueError:
                    pass
                continue
            for done_pending, done_fut in self._inflight.push((pending, fut)):
                self._finalize(done_pending, done_fut)
            if self._q.empty():
                # no overlap work available: drain so this caller's latency
                # is one dispatch + one fence, exactly the unpipelined path
                for done_pending, done_fut in self._inflight.pop_to(0):
                    self._finalize(done_pending, done_fut)
        for done_pending, done_fut in self._inflight.pop_to(0):
            self._finalize(done_pending, done_fut)


class SolverService:
    def __init__(self, scheduler: Optional[BatchScheduler] = None,
                 registry: Optional[Registry] = None,
                 tracer: Optional[Tracer] = None) -> None:
        self.registry = registry or default_registry
        self.scheduler = scheduler or BatchScheduler(registry=self.registry)
        # per-RPC traces; default to the scheduler's tracer so the sidecar's
        # /tracez sees exactly what its scheduler recorded
        self.tracer = tracer or getattr(
            self.scheduler, "tracer", None) or tracer_for(self.registry)
        self._schedulers = {"": self.scheduler}  # guarded-by: _direct_lock
        # KT_SOLVE_PIPELINE=0 falls back to direct, lock-serialized solves
        self._pipelined = os.environ.get("KT_SOLVE_PIPELINE", "1") != "0"
        self._pipelines: dict = {}               # guarded-by: _direct_lock
        self._closed = False                     # guarded-by: _direct_lock
        self._direct_lock = threading.Lock()

    def _scheduler_for(self, backend: str) -> BatchScheduler:
        if backend and backend != self.scheduler.backend:
            # locked check-then-create: two concurrent first RPCs for the
            # same backend must share ONE scheduler (and therefore one
            # pipeline — _pipeline_for keys on the scheduler instance; a
            # lost race here would leak a live dispatcher thread forever)
            with self._direct_lock:
                if backend not in self._schedulers:
                    self._schedulers[backend] = BatchScheduler(
                        backend=backend, registry=self.registry
                    )
                return self._schedulers[backend]
        return self.scheduler

    def _pipeline_for(self, sched: BatchScheduler) -> SolvePipeline:
        with self._direct_lock:  # concurrent first RPCs must share one pipe
            if self._closed:
                # a Solve racing close() must not construct a fresh pipeline
                # AFTER close()'s snapshot — its dispatcher thread would
                # outlive the service with nothing left to stop it
                raise RuntimeError("solver service closed")
            pipe = self._pipelines.get(id(sched))
            if pipe is None:
                pipe = SolvePipeline(sched, registry=self.registry)
                self._pipelines[id(sched)] = pipe
            return pipe

    def close(self) -> None:
        # latch closed + snapshot under the lock (a late first RPC racing
        # shutdown must neither resize the dict mid-iteration nor construct
        # a never-stopped pipeline after the snapshot), stop outside it —
        # stop() joins the dispatcher, and a join under _direct_lock would
        # deadlock against a dispatcher-path call that takes the lock
        with self._direct_lock:
            self._closed = True
            pipes = list(self._pipelines.values())
        for pipe in pipes:
            pipe.stop()

    # ---- RPC methods -----------------------------------------------------
    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        kwargs = codec.decode_request(request)
        sched = self._scheduler_for(request.backend)
        # one trace per RPC, threaded through the pipeline's dispatch/
        # finalize boundary via the kwargs dict (the dispatcher records the
        # queue-wait "window" span on it; the scheduler opens tensorize/
        # dispatch/fence/reseat under it); "respond" covers the encode back
        # onto the wire
        with self.tracer.start(
            "solve", rpc="Solve", backend=sched.backend,
            n_pods=len(kwargs.get("pods", ())),
        ) as trace:
            kwargs["trace"] = trace
            if self._pipelined:
                result = self._pipeline_for(sched).solve(kwargs)
            else:
                with self._direct_lock:
                    result = sched.solve(
                        kwargs.pop("pods"), kwargs.pop("provisioners"),
                        kwargs.pop("instance_types"), **kwargs,
                    )
            with trace.span("respond"):
                resp = codec.encode_response(result)
        return resp

    def Warm(self, request: pb.WarmRequest, context) -> pb.WarmResponse:
        """Forwarded warm_startup: the operator ships its live provisioners,
        catalog, and cluster snapshots; compiles run behind on the sidecar's
        chips (BatchScheduler.warm_startup semantics, including signature
        dedupe, so repeated Warm calls are cheap)."""
        kwargs = codec.decode_warm_request(request)
        sched = self._scheduler_for(request.backend)
        started = sched.warm_startup(
            kwargs.pop("provisioners"), kwargs.pop("instance_types"), **kwargs
        )
        return pb.WarmResponse(started=started)

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            ok=True, backend=jax.default_backend(), devices=len(jax.devices())
        )


def make_server(
    service: Optional[SolverService] = None,
    port: int = 0,
    max_workers: int = 4,
    host: str = "127.0.0.1",
) -> "tuple[grpc.Server, int]":
    service = service or SolverService()
    handlers = {
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.Solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Warm": grpc.unary_unary_rpc_method_handler(
            service.Warm,
            request_deserializer=pb.WarmRequest.FromString,
            response_serializer=pb.WarmResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.Health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                 ("grpc.max_send_message_length", 256 * 1024 * 1024)],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument("--port", type=int, default=50151)
    # 0.0.0.0: the deployed topology dials this across pods
    # (deploy/operator.yaml -> Service karpenter-tpu-solver); loopback would
    # strand the operator on its local fallback forever
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--backend", default="auto", choices=["auto", "tpu", "oracle"])
    parser.add_argument("--obs-port", type=int, default=0,
                        help="observability HTTP port (/tracez, /statusz, "
                             "/metrics); 0 disables")
    args = parser.parse_args(argv)
    service = SolverService(BatchScheduler(backend=args.backend))
    server, port = make_server(service, port=args.port, host=args.host)
    print(f"solver sidecar listening on {args.host}:{port} (backend={args.backend})")
    if args.obs_port:
        from ..obs import default_flight
        from ..obs.export import serve as obs_serve

        flight = service.tracer.flight or default_flight()
        _obs_server, obs_port = obs_serve(
            service.registry, flight, port=args.obs_port, host=args.host)
        print(f"observability on http://{args.host}:{obs_port}/tracez")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop(grace=2.0)
        service.close()
        for sched in service._schedulers.values():
            sched.stop_warms()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
