"""Solver sidecar — gRPC server wrapping the batch scheduler.

The reconciler-facing service boundary (SURVEY.md §2.3 component (1)).
Stubs are registered manually via a generic handler since grpc_tools isn't in
the image; the method table matches the comment block in solver.proto.

Run standalone:  python -m karpenter_tpu.service.server --port 50151
"""

from __future__ import annotations

import argparse
import logging
import os
import queue
import signal
import threading
import time
import uuid
from concurrent import futures
from concurrent.futures import Future
from typing import Optional

import grpc

from .. import faults as faults_mod
from .. import gang as gangmod
from ..admission import (
    AdmissionControl,
    SolveDeadlineError,
    SolveShedError,
    admission_enabled,
    parse_class,
)
from ..batcher import InflightQueue, SlotCoalescer
from ..metrics import (
    DELTA_RPC,
    DELTA_RPC_DURATION,
    INFLIGHT_DEPTH,
    MEGABATCH_FLUSH,
    MEGABATCH_FLUSH_REASONS,
    MEGABATCH_SLOTS,
    MULTIHOST_FENCE_BYTES,
    MULTIHOST_FENCE_SCOPES,
    MULTIHOST_SLOT_OWNERSHIP,
    MULTIHOST_SLOTS,
    MULTIHOST_UNIFIED,
    OCCUPANCY_DELTA_INLINE,
    OCCUPANCY_DEVICE_BUSY,
    OCCUPANCY_SLOT_FILL,
    Registry,
    registry as default_registry,
)
from ..obs import protocol, tracer_for
from ..obs.occupancy import OccupancyAccountant
from ..obs.slo import WINDOWS as SLO_WINDOWS, SloEngine
from ..obs.timeseries import sampler_for
from ..obs.trace import NULL_TRACE, Tracer
from ..parallel.forward import ResultForwarder, SlotNotOwned
from ..solver.guard import DeviceHang
from ..solver.scheduler import BatchScheduler
from ..solver.tpu import MEGA_MAX_SLOTS, max_mega_slots, mesh_shardable
from ..tuning import TuningController, global_knobs, tune_enabled
from ..tuning.controller import zero_init as tuning_zero_init
from ..tuning.knobs import Knobs
from ..utils.clock import Clock
from . import codec
from . import solver_pb2 as pb
from .delta import (
    DeltaReply,
    DeltaSessionTable,
    SessionEntry,
    delta_enabled,
)

SERVICE = "karpenter.tpu.Solver"

#: default megabatch request-slot cap per coalescer flush (KT_MAX_SLOTS /
#: --max-slots override; 1 disables cross-request batching)
DEFAULT_MAX_SLOTS = 8
#: default max-wait before a partially-filled batch flushes, milliseconds
#: (KT_MAX_WAIT_MS / --max-wait-ms).  0 = flush the moment the inbound
#: queue goes idle — single-request latency then matches the unbatched
#: path; coalescing engages exactly when requests actually queue up.
DEFAULT_MAX_WAIT_MS = 0.0


def _resolve(fut: Future, result=None, exc: Optional[BaseException] = None) -> None:
    """Resolve a future exactly once, tolerating the racer.  stop() and the
    dispatcher's _finalize can reach the same future concurrently (a fence
    completing at the instant the 5s join gives up); done()-check-then-set
    is not atomic, so the loser's set raises InvalidStateError — swallow it:
    either resolution unblocks the RPC thread, which is all that matters."""
    try:
        if not fut.done():
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
    except futures.InvalidStateError:
        pass  # the other side resolved it first


def _full_reply(result, epoch: int, mode: str, state: str = "ok") -> DeltaReply:
    """Full-shaped, DETACHED DeltaReply (establish / reseed / guard-trip
    fallback): the client replaces its ledger wholesale.  Copies are taken
    HERE, on the dispatcher, because the session chain these containers
    belong to mutates under the very next delta while the RPC thread is
    still encoding."""
    return DeltaReply(
        state=state, epoch=epoch, mode=mode, full=True,
        assignments=dict(result.assignments),
        infeasible=dict(result.infeasible),
        nodes=[n.snapshot() for n in result.nodes],
        solve_ms=result.solve_ms,
    )


class SolvePipeline:
    """Double-buffered, cross-request-batching solve dispatch for one
    scheduler.

    All scheduler access funnels through ONE dispatcher thread (the
    scheduler is not re-entrant — concurrent RPC handlers previously raced
    on it).  Two throughput mechanisms compose behind it:

    - **Pipelining** (PR 1): ``scheduler.submit`` returns after the async
      device dispatch; the dispatcher tensorizes batch N+1 while batch N
      executes, fencing via the in-flight queue.  Serves the low-concurrency
      regime.
    - **Cross-request megabatching** (PR 4): a deadline-aware
      :class:`~karpenter_tpu.batcher.SlotCoalescer` drains concurrent RPCs
      into request slots (flush on max-slots, max-wait, or shape-bucket
      change) and ``scheduler.submit_many`` solves the whole flush in ONE
      vmapped device dispatch — service throughput stops being capped at
      one solve per device round trip.  Engages exactly when requests
      queue; a lone request flushes immediately (``max_wait=0`` default),
      so single-request latency matches the unbatched path.

    Mesh-configured schedulers ride the same path SHARDED: the flush's
    slot axis spreads one-slot-per-chip over the scheduler's (pods, types)
    mesh (solver/tpu.py ``solve_many_async(mesh=...)``), so a multi-chip
    host serves coalesced flushes at full device count — the pipeline
    floors ``max_slots`` at the mesh's device count so sharded flushes
    fill every chip.  Bucket keys carry the mesh signature, so requests
    against different meshes never coalesce.

    Responses keep arrival order (singles and megabatches share ONE
    FIFO in-flight queue), and every megabatched response carries the
    honest per-request ``solve_ms``: enqueue→respond wall time, NOT the
    megabatch-amortized device time.
    """

    def __init__(self, scheduler: BatchScheduler,
                 registry: Optional[Registry] = None, depth: int = 2,
                 max_slots: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 clock: Optional[Clock] = None,
                 admission: Optional[AdmissionControl] = None,
                 knobs: Optional[Knobs] = None) -> None:
        self.scheduler = scheduler
        self.registry = registry or default_registry
        # the live knob registry (ISSUE 19, docs/TUNING.md): construction
        # defaults read THROUGH it — an unset knob falls back to the env
        # (KT_MAX_SLOTS / KT_MAX_WAIT_MS) exactly as before, a tuned
        # override lands at the next _apply_knobs snapshot
        self.knobs = knobs if knobs is not None else global_knobs()
        if max_slots is None:
            max_slots = int(self.knobs.get("max_slots"))
        if max_wait_ms is None:
            max_wait_ms = float(self.knobs.get("max_wait_ms"))
        # meshed scheduler: the sharded megabatch pads its slot axis to the
        # mesh's device count (one slot per chip), so floor the flush size
        # there — a smaller cap would flush half-empty shards and serve the
        # mesh below its chip count — and CAP it at the mesh's largest
        # in-ladder rung (awkward device counts: 20 chips top out at a
        # 20-slot rung, so a 32-entry flush would overflow the sharded
        # program and degrade to serial on every full flush).
        # max_slots=1 (batching disabled) is honored; an unshardable mesh
        # (device count past the slot-rung ladder) keeps the configured
        # cap and rides the serial path.
        self.max_slots = self._clamp_slots(max_slots)
        #: an unshardable mesh on a megabatching backend serves every
        #: request as its own single-request serial flush: count those
        #: flushes under mesh_serial, not 'bucket', so degradation stays
        #: visible in flush units.  The verdict is the SCHEDULER's
        #: construction-time ``mega_unshardable`` (ISSUE 14 satellite:
        #: hoisted so the per-request bucket probe disappears —
        #: _bucket_of short-circuits on this flag without calling
        #: bucket_key at all); facades without the attribute fall back to
        #: the pipeline-side computation.
        mesh = getattr(scheduler, "mesh", None)
        sched_verdict = getattr(scheduler, "mega_unshardable", None)
        if sched_verdict is None:
            sched_verdict = mesh is not None and not mesh_shardable(mesh)
        self._mesh_unshardable = (
            bool(sched_verdict)
            and getattr(scheduler, "backend", None) in ("auto", "tpu"))
        self.max_wait = max(0.0, max_wait_ms) / 1000.0
        #: the per-iteration atomic knob snapshot (_apply_knobs, under
        #: _sched_lock); _inline_ok and _effective_max_wait read the
        #: IMMUTABLE object, so a mid-flight tuner update can never tear
        #: a flush or a brownout evaluation (ISSUE 19)
        self._knob_snap = self.knobs.snapshot()
        self._clock = clock or Clock()
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._submit_lock = threading.Lock()  # makes stop-check + put atomic
        # scheduler-OWNERSHIP lock: every section that touches the (non-
        # re-entrant) scheduler or fences in-flight device work holds it —
        # the dispatcher's dispatch/finalize sections, and the delta fast
        # path's INLINE shortcut (_solve_inline: an idle pipeline serves a
        # sub-ms delta RPC directly on its RPC thread, skipping both
        # queue-handoff context switches).  Uncontended acquisition costs
        # the dispatcher ~1us per dispatch; re-entrant because _flush
        # nests _dispatch_single/_finalize under one flush.
        self._sched_lock = threading.RLock()
        #: futures the dispatcher has popped (from _q or _inflight) but not
        #: yet resolved — the dispatcher's hand.  Written by the dispatcher
        #: only; stop() snapshots it after the join times out so a wedge at
        #: ANY point between pop and resolution (inside submit's device
        #: dispatch, inside a fence, between an _inflight drain and its
        #: finalize) can't strand an RPC thread.  _resolve tolerates the
        #: benign race with a merely-slow dispatcher.  Coalesced-but-not-
        #: yet-flushed requests are in it too — a stop() mid-hold fails
        #: them instead of stranding them in the coalescer.
        self._in_hand: "list[Future]" = []
        gauge = self.registry.gauge(INFLIGHT_DEPTH)
        labels = {"backend": scheduler.backend}  # one series per backend
        if not gauge.has(labels):
            # only when absent: a second pipeline on a shared registry must
            # not zero a live series (same guard as BatchScheduler.__init__)
            gauge.set(0, labels)
        self._inflight: InflightQueue = InflightQueue(
            depth=depth, on_depth=lambda d: gauge.set(d, labels))
        #: dispatcher-owned: batch boundaries for the megabatch path.
        #: The scheduler's ``unify_buckets`` (when it has one) lets a held
        #: flush admit a dominated mixed-bucket request so both shapes
        #: share one mesh dispatch (ISSUE 14 host-aware coalescing)
        self._coal: SlotCoalescer = SlotCoalescer(
            max_slots=self.max_slots, max_wait=self.max_wait,
            clock=self._clock,
            # no on_unify counting here: the COLLECTOR counts unified
            # dispatches (submit_many's group merge re-derives the same
            # unification) — counting the coalescer join too would tally
            # one logical unification twice
            unify=getattr(scheduler, "unify_buckets", None))
        # zero-init every flush-reason series (KT003: a counter born at its
        # first increment loses that increment to rate()/increase())
        flush = self.registry.counter(MEGABATCH_FLUSH)
        for reason in MEGABATCH_FLUSH_REASONS:
            flush.inc({"reason": reason}, value=0.0)
        self.registry.histogram(MEGABATCH_SLOTS)
        # multi-host serving families at 0 from construction (KT003) —
        # the pipeline re-zero-inits like the flush reasons above, for
        # facade schedulers without the BatchScheduler init
        fence_c = self.registry.counter(MULTIHOST_FENCE_BYTES)
        for scope in MULTIHOST_FENCE_SCOPES:
            fence_c.inc({"scope": scope}, value=0.0)
        slots_c = self.registry.counter(MULTIHOST_SLOTS)
        for ownership in MULTIHOST_SLOT_OWNERSHIP:
            slots_c.inc({"ownership": ownership}, value=0.0)
        self.registry.counter(MULTIHOST_UNIFIED).inc(value=0.0)
        #: cross-host result-forwarding shim (ISSUE 14): a megabatch slot
        #: whose RPC arrived here but whose shards another host owns
        #: resolves SlotNotOwned; the shim re-routes it to the owning
        #: host's endpoint (KT_MULTIHOST_PEERS) over the fleet transport.
        #: Null-enabled by default — single-process serving never
        #: produces foreign slots.
        self._forwarder = ResultForwarder(registry=self.registry)
        self._forwarder.zero_init()
        #: lazily-built bounded pool for forwarding RPCs (foreign slots
        #: arrive per flush on a multi-host mesh — per-request thread
        #: spawn would churn unboundedly under burst); None until the
        #: first foreign slot, shut down in stop()
        self._fwd_pool = None
        #: dispatcher-owned: the admitted priority class per in-hand
        #: future, so a forwarded foreign slot re-dispatches in ITS class
        #: on the owning host (cleared by _unhand with the _in_hand entry)
        self._fwd_pclass: dict = {}
        # admission control (docs/ADMISSION.md): the bounded priority queue
        # + breaker + brownout front door.  None = construct from env
        # (KT_ADMISSION=0 disables); False = force off (bench A/B runs).
        # Disabled keeps the raw FIFO above verbatim — byte-identical to
        # the pre-admission path.
        if admission is None and admission_enabled():
            admission = AdmissionControl(
                registry=self.registry, clock=self._clock,
                flight=getattr(getattr(scheduler, "tracer", None),
                               "flight", None),
            )
        self._adm: Optional[AdmissionControl] = admission or None
        if self._adm is not None:
            # a preemption happens on the PREEMPTING request's RPC thread;
            # the victim's blocked RPC thread is unblocked right there
            self._adm.on_shed = lambda t, exc: _resolve(t.item[1], exc=exc)
        # delta serving (docs/ARCHITECTURE.md round 14): the bounded,
        # TTL-evicted table of live warm-start chains behind the session-
        # stateful SolveDelta protocol.  KT_DELTA=0 leaves it None and
        # every session-carrying request degrades to the classic full
        # path — byte-identical to pre-delta serving.  Table entries are
        # dispatcher-owned; the table's own lock only guards the dict.
        # fault-injection plane (ISSUE 12, docs/RESILIENCE.md): the
        # zero-cost null plane unless KT_FAULTS configures a chaos
        # schedule; shared with the session table so ONE seeded schedule
        # covers the delta path and the table/spool choke points
        self._faults = faults_mod.plane(
            self.registry,
            flight=getattr(getattr(scheduler, "tracer", None),
                           "flight", None))
        # session durability (ISSUE 12) + fleet handoff (ISSUE 13): with
        # KT_SESSION_DIR set, every session spools to its own record file
        # (graceful shutdown, drain handoff, and periodically at epoch
        # boundaries — KT_SESSION_SNAPSHOT_S), and any replica sharing the
        # volume rehydrates a session on demand (boot restore + adopt-on-
        # miss) under the exactly-one-owner lease protocol — a failed-over
        # session's next delta is served WARM by whichever replica the
        # client re-homes to.  A refused record (corrupt/version/catalog
        # skew) is a counted cold start.
        self._spool_dir = os.environ.get("KT_SESSION_DIR", "")
        if self._spool_dir:
            # records are namespaced PER BACKEND under the shared dir: the
            # service lazily builds a pipeline per requested backend, and
            # an auto-backend replica must never adopt (or clobber) an
            # oracle-backend chain — same-backend SIBLING replicas share
            # the namespace deliberately; the lease protocol arbitrates.
            self._spool_dir = os.path.join(
                self._spool_dir, getattr(scheduler, "backend", "") or "auto")
        self._delta_tab: Optional[DeltaSessionTable] = (
            DeltaSessionTable(registry=self.registry, clock=self._clock,
                              faults=self._faults,
                              spool_dir=self._spool_dir)
            if delta_enabled() else None)
        #: graceful-drain latch (SIGTERM / SolverService.drain): new
        #: session establishments are refused with a DRAINING hint, and
        #: every served delta hands its chain off to the shared spool so
        #: the client's next RPC lands warm on a sibling
        self._draining = False
        self._snap_interval = float(
            os.environ.get("KT_SESSION_SNAPSHOT_S", "30"))
        self._last_snap = self._clock.now()   # guarded-by: _sched_lock
        #: in-flight background spool write (the periodic snapshot runs
        #: OFF the serving paths — the table's torn-entry guard makes a
        #: lock-free write safe).  Written under _sched_lock
        #: (_maybe_snapshot); snapshot_sessions' shutdown read is
        #: deliberately lock-free — a dispatcher wedged inside the lock
        #: must not deadlock shutdown, and the unique write_atomic temp
        #: names make even a racing writer rename-safe.
        self._snap_worker: Optional[threading.Thread] = None
        if self._spool_dir and self._delta_tab is not None:
            cat = os.environ.get("KT_CATALOG_EPOCH", "")
            tracer = getattr(scheduler, "tracer", None)
            if tracer is not None:
                with tracer.start("restore", spool=self._spool_dir) as tr:
                    n = self._delta_tab.restore(
                        self._spool_dir,
                        expected_catalog_epoch=int(cat) if cat else None)
                    tr.annotate(sessions=n)
            else:
                self._delta_tab.restore(
                    self._spool_dir,
                    expected_catalog_epoch=int(cat) if cat else None)
        #: lazily-built host FFD scheduler for breaker-open / brownout
        #: routed solves (device capacity stays reserved for the classes
        #: that keep the device path)
        self._host_sched: Optional[BatchScheduler] = None
        #: dispatcher-owned: futures whose dispatch was host-routed — their
        #: outcomes must NOT feed the breaker's device-path probe accounting
        self._host_futs: set = set()
        self._thread = threading.Thread(
            target=self._loop, name="solve-pipeline", daemon=True)
        self._thread.start()

    def solve(self, kwargs: dict, pclass: Optional[str] = None,
              deadline_s: Optional[float] = None):
        """RPC-thread entry: enqueue and block for this request's result.

        With admission enabled, ``pclass``/``deadline_s`` route the request
        through the bounded priority queue — :class:`SolveShedError` /
        :class:`SolveDeadlineError` surface HERE (before any tensorize or
        device work happened for the request); disabled, both are ignored
        and the raw FIFO path is byte-identical to pre-admission."""
        # queue-wait attribution: stamp the enqueue on the request's trace
        # clock here (RPC thread); the dispatcher closes the "window" span
        # when it picks the request up — the cross-thread phase is recorded
        # as an already-closed span, so nothing can leak.  The perf_counter
        # stamp feeds the megabatch path's honest enqueue→respond solve_ms.
        trace = kwargs.get("trace") or NULL_TRACE
        t_enq = trace.now()
        t_wall = time.perf_counter()
        if "_delta" in kwargs and self._inline_ok():
            # delta fast path, idle-pipeline shortcut: serve the sub-ms
            # incremental step ON THIS RPC THREAD under the scheduler-
            # ownership lock — no queue handoff, no dispatcher wakeup, no
            # future wake: two context switches gone from the steady-state
            # path.  Non-blocking acquire: a busy dispatcher (or another
            # inline solve) sends the request down the normal queue path,
            # so class ordering under load is untouched.
            if self._sched_lock.acquire(blocking=False):
                try:
                    return self._solve_inline(kwargs, pclass, deadline_s,
                                              trace, t_enq, t_wall)
                finally:
                    self._sched_lock.release()
        fut: Future = Future()
        item = (kwargs, fut, t_enq, t_wall)
        # the stop-check and the put are one atomic step: a put that wins
        # the lock before stop()'s drain is guaranteed to be seen by the
        # drain; a put that loses sees _stop and refuses — either way no
        # future is ever left unresolved (an RPC thread blocked forever on
        # fut.result() would pin process exit)
        with self._submit_lock:
            if self._stop.is_set():
                raise RuntimeError("solve pipeline stopped")
            if self._adm is not None:
                pclass = parse_class(pclass or "")
                # the dispatcher pops this back out before the scheduler
                # sees kwargs (routing + slot-fill ordering read it)
                kwargs["_pclass"] = pclass
                t0 = trace.now()
                # raises the typed shed/deadline error straight to the RPC
                # thread — nothing was enqueued, nothing to clean up
                ticket = self._adm.admit(item, pclass,
                                         deadline_s=deadline_s)
                trace.record(
                    "admission", t0, trace.now(), priority_class=pclass,
                    queued=len(self._adm.queue),
                    brownout=self._adm.brownout.level,
                    breaker=self._adm.breaker.state)
                # every resolution path (finalize, shed, stop) returns the
                # class's concurrency-quota slot exactly once
                fut.add_done_callback(
                    lambda _f, t=ticket: self._adm.release(t))
            else:
                self._q.put(item)
        return fut.result()

    def stop(self) -> None:
        """Stop the dispatcher.  Requests still queued OR in flight are
        FAILED, not abandoned — a blocked RPC thread waiting on an
        unresolved future would pin process exit forever."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # dispatcher wedged (e.g. a device fence behind a dead tunnel,
            # forced backend so no guard, or an H2D dispatch inside
            # scheduler.submit): fail everything still in flight so the RPC
            # threads unblock; the daemon dispatcher thread itself cannot
            # pin exit.  deque ops are thread-safe, and every entry the
            # wedged thread already popped is still in its _in_hand ledger
            # (coalescer-held requests included).
            for head, rest in self._inflight.pop_to(0):
                if head == "mega":
                    for (_kw, fut, _t, _w), _pending in rest:
                        _resolve(fut,
                                 exc=RuntimeError("solve pipeline stopped"))
                else:
                    _resolve(rest, exc=RuntimeError("solve pipeline stopped"))
            for fut in list(self._in_hand):
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))
        with self._submit_lock:
            while True:
                try:
                    _kwargs, fut, _t_enq, _t_wall = self._q.get_nowait()
                except queue.Empty:
                    break
                _resolve(fut, exc=RuntimeError("solve pipeline stopped"))
            if self._adm is not None:
                # tickets still queued in the admission queue: FAIL them
                # (same contract as the raw FIFO above — a blocked RPC
                # thread waiting on an unresolved future pins process exit)
                for ticket in self._adm.drain():
                    _kwargs, fut, _t_enq, _t_wall = ticket.item
                    _resolve(fut, exc=RuntimeError("solve pipeline stopped"))
        if self._delta_tab is not None:
            # graceful shutdown: spool the chains FIRST (KT_SESSION_DIR
            # set), so the replacement replica serves every surviving
            # session warm...
            if self._spool_dir:
                self.snapshot_sessions()
            # ...then the in-memory chains die with the pipeline; clients
            # whose sessions were not spooled re-establish against the
            # replacement (counted so a restart storm is visible as
            # eviction reason "stop", not mystery unknowns)
            self._delta_tab.clear("stop")
        if self._fwd_pool is not None:
            # queued forwards resolve their futures from pool threads;
            # wait=False — stop() must not block on a peer RPC, and
            # _resolve tolerates the stopped-pipeline double-fail
            self._fwd_pool.shutdown(wait=False)
        self._forwarder.close()

    def drain(self) -> None:
        """Enter graceful-drain mode (the fleet handshake, docs/
        RESILIENCE.md): from here on NEW session establishments are
        refused with a ``session_state="draining"`` hint, every served
        delta hands its chain off to the shared spool (record + released
        lease + dropped entry) on the same reply, and an immediate
        snapshot pass spools every quiescent chain so sessions that never
        send another delta before the pod dies are already adoptable.
        Serving continues — classic full solves and in-flight session
        chains are unaffected until their handoff."""
        self._draining = True
        if self._delta_tab is not None and self._spool_dir:
            self._delta_tab.snapshot(self._spool_dir)

    def draining(self) -> bool:
        return self._draining

    def snapshot_sessions(self) -> dict:
        """Spool every quiescent session chain (graceful-shutdown path:
        the serve SIGTERM handler and deploy preStop land here via
        ``stop()``; chaos/regression tests call it directly).  Safe
        against a dispatcher wedged MID-STEP — the wedged chain carries
        ``in_step``/moves its epoch and the table skips/discards it
        (epoch-atomicity over completeness: that one client
        re-establishes, nobody replays half a mutation).  Ordering vs an
        in-flight background periodic write is the table's ``_spool_lock``:
        this call captures AND renames after that writer finishes, so an
        older capture can never replace this newer spool."""
        if not self._spool_dir or self._delta_tab is None:
            return {}
        return self._delta_tab.snapshot(self._spool_dir)

    def _maybe_snapshot(self) -> None:
        """Periodic epoch-boundary spool write, handed to a background
        thread: pickling up to KT_DELTA_SESSIONS chains + fsync must
        never sit on a sub-ms serving path or hold the scheduler lock
        (the table's per-entry torn-entry guard makes the lock-free
        write safe).  Interval state is _sched_lock-serialized (every
        call site holds it); at most one write is in flight — a boundary
        arriving while one runs is skipped, the next one catches up."""
        if (not self._spool_dir or self._delta_tab is None
                or self._snap_interval <= 0 or self._stop.is_set()):
            # the _stop check matters: stop() writes the shutdown spool
            # then clears the table, and a straggling tick afterwards
            # would snapshot the now-EMPTY table — whose empty-write
            # path removes the spool the shutdown just wrote
            return
        # callers already hold the (re-entrant) ownership lock; taking it
        # here keeps the interval/worker state lexically guarded
        with self._sched_lock:
            now = self._clock.now()
            if now - self._last_snap < self._snap_interval:
                return
            if (self._snap_worker is not None
                    and self._snap_worker.is_alive()):
                return
            self._last_snap = now
            self._snap_worker = threading.Thread(
                target=self._delta_tab.snapshot, args=(self._spool_dir,),
                name="session-snapshot", daemon=True)
            self._snap_worker.start()

    def _finalize(self, pending, fut: Future) -> None:
        try:
            try:
                result = pending.result()
            # ktlint: allow[KT005] the dispatcher must survive any fence
            # outcome; the exception is handed to the blocked RPC thread via
            # its future and re-raised there
            except BaseException as err:  # noqa: BLE001 — fan to the RPC
                self._feed_breaker(fut, err)
                _resolve(fut, exc=err)
                return
            self._feed_breaker(fut, None)
            _resolve(fut, result=result)
        finally:
            # resolved either way: out of the dispatcher's hand
            try:
                self._in_hand.remove(fut)
            except ValueError:
                pass  # already failed by a concurrent stop()

    def _feed_breaker(self, fut: Future, err: Optional[BaseException]) -> None:
        """Per-request outcome -> circuit-breaker probe accounting.  Host-
        routed solves never touch the device, so their outcomes must not
        close (or trip) the device-path breaker."""
        if self._adm is None:
            return
        if fut in self._host_futs:
            self._host_futs.discard(fut)
            return
        if self._faults:
            effect = self._faults.fire("breaker")
            if effect is not None and effect.kind == "breaker_trip":
                # synthetic failure into the breaker's device-path feed:
                # composes breaker-open host routing with whatever else
                # the schedule is doing.  RETURN: the request whose
                # completion carried the injected trip must not also
                # record its organic outcome — record_success would
                # reset the closed-state failure count to 0 and N
                # consecutive injected trips could never reach the
                # open threshold
                self._adm.breaker.record_failure("injected")
                return
        if err is None:
            self._adm.breaker.record_success()
        elif isinstance(err, DeviceHang):
            self._adm.breaker.record_failure("device_hang")

    def _bucket_of(self, kwargs: dict):
        """Megabatch bucket probe — None routes the request down the classic
        single path (also when the scheduler has no bucketing: RemoteScheduler
        facades, test doubles)."""
        if self.max_slots <= 1:
            return None
        if self._mesh_unshardable:
            # construction-time verdict (scheduler.mega_unshardable): no
            # sharded megabatch program exists for this mesh, so the
            # per-request probe — and its tensorize — is skipped entirely;
            # _flush labels the resulting single-request flushes
            # mesh_serial
            return None
        bucket = getattr(self.scheduler, "bucket_key", None)
        if bucket is None:
            return None
        # the probe itself never fails a request (bucket_key boxes its own
        # errors and returns None), but a facade without that contract must
        # not take the dispatcher down either
        try:
            return bucket(kwargs)
        # ktlint: allow[KT005] probe failure = unbatchable, logged at the
        # scheduler layer; the request solves on the single path
        except Exception:
            return None

    def _flush(self, batch, reason: str) -> None:
        """Dispatch one coalescer flush: a single request keeps the classic
        pipelined submit; 2+ requests ride one scheduler.submit_many
        megabatch dispatch.  NEITHER fences here — both park in the
        in-flight queue so the dispatcher coalesces/tensorizes the next
        batch while this one executes; megabatched responses get honest
        enqueue→respond solve_ms at finalization."""
        if not batch:
            return
        if reason == "bucket" and len(batch) == 1 and self._mesh_unshardable:
            # the coalescer resolved an unshardable-mesh rejection (None
            # bucket key) into this single-request serial flush — the
            # mesh is WHY it rides alone, so label it honestly
            reason = "mesh_serial"
        if len(batch) == 1:
            self.registry.counter(MEGABATCH_FLUSH).inc({"reason": reason})
            self._dispatch_single(*batch[0])
            return
        # a scheduler that can degrade a meshed flush to serial owns the
        # flush count (it incs mesh_serial OR our reason at dispatch, so
        # the labels partition flushes); facades/doubles without the
        # capability keep the upfront count here
        delegated = getattr(self.scheduler, "counts_flush_reason", False)
        if not delegated:
            self.registry.counter(MEGABATCH_FLUSH).inc({"reason": reason})
        try:
            pendings = self.scheduler.submit_many(
                [kw for kw, _f, _t, _w in batch],
                **({"flush_reason": reason} if delegated else {}))
        # ktlint: allow[KT005] submit failures fan to every waiting RPC
        # thread through their futures; the dispatcher itself must live on
        except BaseException as err:  # noqa: BLE001
            if delegated:
                # a registration-phase raise never reached the collector's
                # end-of-dispatch count — the flush still happened, and an
                # uncounted failing flush is the one an operator most
                # wants visible in the partition
                self.registry.counter(MEGABATCH_FLUSH).inc(
                    {"reason": reason})
            for _kw, fut, _t, _w in batch:
                _resolve(fut, exc=err)
                self._unhand(fut)
            return
        # one in-flight entry for the WHOLE megabatch (depth counts device
        # dispatches, and the megabatch is one); finalization order stays
        # FIFO because singles and megabatches share the one queue
        self._drain(self._inflight.push(("mega", list(zip(batch, pendings)))))
        if self._inbound_idle() and not len(self._coal):
            self._drain(self._inflight.pop_to(0))

    def _inbound_idle(self) -> bool:
        """No request waiting to be picked up (whichever front door is
        active: the admission queue or the raw FIFO)."""
        if self._adm is not None:
            return len(self._adm.queue) == 0
        return self._q.empty()

    def _host_scheduler(self) -> BatchScheduler:
        """Lazily-built oracle (host FFD) scheduler for breaker-open /
        brownout-routed solves.  Shares the pipeline's registry and the
        main scheduler's tracer so routed solves stay observable."""
        if self._host_sched is None:
            self._host_sched = BatchScheduler(
                backend="oracle", registry=self.registry,
                tracer=getattr(self.scheduler, "tracer", None),
            )
        return self._host_sched

    def _unhand(self, fut: Future) -> None:
        self._fwd_pclass.pop(fut, None)
        try:
            self._in_hand.remove(fut)
        except ValueError:
            pass  # already failed by a concurrent stop()

    def _drain(self, entries) -> None:
        for entry in entries:
            head, rest = entry
            if head == "mega":
                self._finalize_mega(rest)
            else:
                self._finalize(head, rest)

    def _finalize_mega(self, pairs) -> None:
        for (kwargs, fut, _t_enq, t_wall), pending in pairs:
            try:
                result = pending.result()
                # honest per-request latency: this RPC's enqueue → respond
                # wall time, not the megabatch-amortized device time
                result.solve_ms = (time.perf_counter() - t_wall) * 1000.0
            except SlotNotOwned as err:
                # the per-host fence demuxed this slot to another host
                # (multi-process mesh): route it through the forwarding
                # shim — NOT a device failure, so the breaker never sees
                # it, and the owner-host RPC runs off-thread so
                # batchmates' finalization is never stalled behind it
                self._forward_foreign(kwargs, fut, err, t_wall)
            # ktlint: allow[KT005] per-request failure fans to ITS RPC
            # thread only; batchmates still resolve
            except BaseException as err:  # noqa: BLE001
                self._feed_breaker(fut, err)
                _resolve(fut, exc=err)
            else:
                self._feed_breaker(fut, None)
                _resolve(fut, result=result)
            self._unhand(fut)

    def _forward_foreign(self, kwargs: dict, fut: Future,
                         err: SlotNotOwned, t_wall) -> None:
        """Resolve a foreign-slot future via the cross-host forwarding
        shim on its own thread (the RPC to the owning host must not stall
        the dispatcher); a disabled shim resolves the typed SlotNotOwned
        inline (counted 'unrouted')."""
        fwd = self._forwarder
        # read the admitted class NOW (dispatcher thread) — _unhand
        # clears the ledger entry right after this returns
        pclass = self._fwd_pclass.get(fut, "")
        if not fwd.enabled():
            try:
                fwd.forward(kwargs, err, priority=pclass)
            # ktlint: allow[KT005] the typed SlotNotOwned (or the shim's
            # wrapped transport error) fans to the waiting RPC thread
            except BaseException as exc:  # noqa: BLE001
                _resolve(fut, exc=exc)
            return
        kwargs = dict(kwargs)

        def run():
            try:
                result = fwd.forward(kwargs, err, priority=pclass)
                result.solve_ms = (time.perf_counter() - t_wall) * 1000.0
            # ktlint: allow[KT005] forwarding failure fans to ITS RPC
            # thread only, typed by the shim
            except BaseException as exc:  # noqa: BLE001
                _resolve(fut, exc=exc)
            else:
                _resolve(fut, result=result)

        if self._fwd_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._fwd_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="slot-forward")
        self._fwd_pool.submit(run)

    def _dispatch_single(self, kwargs: dict, fut: Future, t_enq, t_wall,
                         scheduler: Optional[BatchScheduler] = None) -> None:
        try:
            pending = (scheduler or self.scheduler).submit(
                kwargs.pop("pods"), kwargs.pop("provisioners"),
                kwargs.pop("instance_types"), **kwargs,
            )
        # ktlint: allow[KT005] submit failures fan to the waiting RPC
        # thread through its future; the dispatcher itself must live on
        except BaseException as err:  # noqa: BLE001
            self._host_futs.discard(fut)
            _resolve(fut, exc=err)
            self._unhand(fut)
            return
        self._drain(self._inflight.push((pending, fut)))
        if self._inbound_idle() and not len(self._coal):
            # no overlap work available: drain so this caller's latency
            # is one dispatch + one fence, exactly the unpipelined path
            self._drain(self._inflight.pop_to(0))

    def delta_live(self) -> bool:
        """Whether session-routed requests have somewhere to land (KT_DELTA
        on).  Service-side routing probes this before tagging kwargs."""
        return self._delta_tab is not None

    def _inline_ok(self) -> bool:
        """Inline-shortcut eligibility: the pipeline is COMPLETELY idle —
        nothing queued, coalesced, in flight, or in the dispatcher's hand.
        Best-effort reads from the RPC thread (dispatcher-owned state);
        CORRECTNESS never rests on them — only _sched_lock serializes
        scheduler access — the check protects class ORDERING: an inline
        delta must not overtake work already queued ahead of it."""
        return (not self._stop.is_set()
                # live inline-routing knob: reads the last applied
                # IMMUTABLE snapshot (best-effort like the rest of this
                # probe; the registry knob lands via _apply_knobs)
                and bool(self._knob_snap.inline_delta)
                and not self._in_hand
                and not len(self._inflight)
                and not len(self._coal)
                and self._inbound_idle())

    def _solve_inline(self, kwargs: dict, pclass, deadline_s,
                      trace, t_enq, t_wall):
        """Serve one session-routed request on its own RPC thread (caller
        holds _sched_lock).  Admission posture applies in full via
        admit_inline — brownout-rung sheds, concurrency quota, rate limit
        all raise the same typed errors the queue path maps to the wire;
        only queue residency (depth quotas, preemption, deadline expiry
        while queued) is moot because dispatch is immediate."""
        ticket = None
        if self._adm is not None:
            pclass = parse_class(pclass or "")
            t0a = trace.now()
            ticket = self._adm.admit_inline(pclass, deadline_s=deadline_s)
            trace.record(
                "admission", t0a, trace.now(), priority_class=pclass,
                queued=0, inline=True,
                brownout=self._adm.brownout.level,
                breaker=self._adm.breaker.state)
        try:
            trace.record("window", t_enq, trace.now(), inflight=0,
                         coalesced=0, inline=True)
            info = kwargs.pop("_delta")
            kwargs.pop("_pclass", None)
            t0 = trace.now()
            wall0 = time.perf_counter()
            reply, outcome = self._serve_delta(kwargs, info, trace)
            self.registry.histogram(DELTA_RPC_DURATION).observe(
                time.perf_counter() - wall0)
            trace.record("delta", t0, trace.now(),
                         session=info["session_id"], outcome=outcome,
                         mode=reply.mode, epoch=reply.epoch, inline=True)
            # no observe_idle here: the dispatcher's own idle ticks (every
            # 100ms regardless of inline traffic) keep the brownout EWMA
            # decaying and the breaker feeds polled — paying a breaker
            # counter sweep per sub-ms RPC would tax exactly the path this
            # shortcut exists to strip
            reply.solve_ms = (time.perf_counter() - t_wall) * 1000.0
            self._maybe_snapshot()  # epoch boundary (caller holds the
            return reply            # scheduler-ownership lock)
        finally:
            if ticket is not None:
                self._adm.release(ticket)

    def _dispatch_delta(self, kwargs: dict, fut: Future, t_enq, t_wall) -> None:
        """Session-routed dispatch — the delta fast path.

        Bypasses the megabatch coalescer entirely: a sub-millisecond
        incremental step must not wait out ``KT_MAX_WAIT_MS`` in a slot
        queue, and it could never share a compiled bucket with full solves
        anyway.  Anything already held is flushed FIRST, so coalesced
        batchmates are never delayed behind session traffic.  Host routing
        (breaker open / brownout rung 3) is deliberately skipped: the
        incremental tiers never dispatch to the device, and the scan/full
        subsolves run through ``scheduler.solve``, which owns the device-
        health fallback ladder — guards err toward latency, never
        correctness (the PR-6 contract).  Admission is NOT skipped: the
        request was admitted as a normal ticket in its class before it
        got here (brownout L4 sheds best_effort deltas like any other)."""
        for reason, _key, batch in self._coal.flush("bucket"):
            self._flush(batch, reason)
        info = kwargs.pop("_delta")
        trace = kwargs.get("trace") or NULL_TRACE
        t0 = trace.now()
        wall0 = time.perf_counter()
        try:
            reply, outcome = self._serve_delta(kwargs, info, trace)
        # ktlint: allow[KT005] a failing step fans to its RPC thread via
        # the future; the dispatcher itself must live on
        except BaseException as err:  # noqa: BLE001
            _resolve(fut, exc=err)
            self._unhand(fut)
            return
        self.registry.histogram(DELTA_RPC_DURATION).observe(
            time.perf_counter() - wall0)
        trace.record("delta", t0, trace.now(),
                     session=info["session_id"], outcome=outcome,
                     mode=reply.mode, epoch=reply.epoch)
        # honest per-request latency: enqueue -> respond wall time
        reply.solve_ms = (time.perf_counter() - t_wall) * 1000.0
        _resolve(fut, result=reply)
        self._unhand(fut)
        # epoch boundary: the chain just committed, nothing is mid-step —
        # the natural moment for the periodic durability write
        self._maybe_snapshot()

    def _serve_delta(self, kwargs: dict, info: dict, trace):
        """One session-routed request -> (DeltaReply, outcome label).

        Runs on the dispatcher thread; the chain entry is dispatcher-owned
        end to end, so everything handed back for encoding is DETACHED
        (DeltaReply snapshots) — the next delta may mutate the chain while
        the RPC thread is still serializing this reply."""
        tab = self._delta_tab
        sid = info["session_id"]
        pods = kwargs.pop("pods")
        provisioners = kwargs.pop("provisioners")
        instance_types = kwargs.pop("instance_types")

        def _counted(reply: DeltaReply, outcome: str):
            # every outcome — incremental, fallback, establish, unknown —
            # is counted HERE, in the function that runs the solves:
            # ktlint KT015 pins that no delta-path full solve can ship
            # without its outcome landing in karpenter_solver_delta_rpc_total
            self.registry.counter(DELTA_RPC).inc({"outcome": outcome})
            return reply, outcome

        if not info["delta"]:
            if self._draining and tab is not None:
                # graceful drain: this replica admits NO new (or re-
                # establishing) sessions — the DRAINING hint sends the
                # client to a sibling, which establishes there instead of
                # binding a chain to a pod about to die
                if protocol._SINK is not None:
                    protocol.emit(sid, "drain_refused",
                                  replica=tab.replica)
                return _counted(DeltaReply(state="draining", full=False),
                                "drain_refused")
            # establish (or re-establish): ONE classic full solve, and the
            # result becomes the session's chain base
            result = self.scheduler.solve(
                pods, provisioners, instance_types,
                existing_nodes=kwargs.get("existing_nodes", ()),
                daemonsets=kwargs.get("daemonsets", ()),
                unavailable=kwargs.get("unavailable") or None,
                allow_new_nodes=kwargs.get("allow_new_nodes", True),
                max_new_nodes=kwargs.get("max_new_nodes"),
                trace=trace,
            )
            if tab is None:
                # delta serving off: answer like a plain solve ("" state
                # tells the client no session was retained)
                return _counted(_full_reply(result, 0, "", state=""), "establish")
            # establishment epochs come from the table's monotone floor,
            # NOT a constant 1: a re-established session must never be
            # able to advance back onto an epoch a stale incarnation
            # (spooled, or lost to an eviction race) already reached —
            # an exact-match epoch check against stale state is the one
            # silent-divergence path the protocol must close
            epoch0 = tab.next_epoch()
            # chain-identity nonce (model-checker divergence fix, ISSUE
            # 17): the epoch floor alone cannot protect against a spool
            # ROLLBACK restoring an old incarnation's record — its epoch
            # can collide with the new chain's acked epoch and the exact-
            # match check would silently apply a delta across lineages.
            # A per-establishment nonce makes chain identity explicit;
            # "" (old clients, legacy spool records) stays a wildcard.
            nonce0 = uuid.uuid4().hex[:16]
            tab.put(SessionEntry(
                session_id=sid, prev=result, epoch=epoch0,
                catalog_epoch=info["catalog_epoch"],
                provisioners=provisioners, instance_types=instance_types,
                daemonsets=kwargs.get("daemonsets") or (),
                unavailable=set(kwargs.get("unavailable") or ()),
                nonce=nonce0,
            ))
            if self._spool_dir:
                # take spool ownership NOW (force-claim): the client's
                # establishment supersedes any incarnation a sibling's
                # lease still guards — without this a session re-homed by
                # a routing flap livelocks between the stale lease holder
                # and the replica actually serving it.  Lifecycle span:
                # the session's lease CLAIM, the first event of its
                # journey timeline (docs/OBSERVABILITY.md span taxonomy).
                t0c = trace.now()
                tab.own(sid, self._spool_dir)
                trace.record("session_claim", t0c, trace.now(),
                             session_id=sid, replica_id=tab.replica,
                             epoch=epoch0)
            reply = _full_reply(result, epoch0, "establish")
            reply.nonce = nonce0
            return _counted(reply, "establish")
        # ---- incremental step -------------------------------------------
        entry = tab.get(sid) if tab is not None else None
        if entry is None and tab is not None and self._spool_dir:
            # fleet failover (docs/RESILIENCE.md): the chain may be
            # waiting in the shared spool — a dead or drained sibling
            # spooled it, the client re-homed here, and adoption (lease
            # claim + record consume) serves this very delta WARM.  Every
            # adoption outcome is counted; an unexpired sibling lease
            # refuses typed and the client pays the PR-10 exactly-one
            # re-establish instead.  Lifecycle span: "session_steal" when
            # the previous owner's lease had expired (the dead-replica
            # path), "session_adopt" otherwise — with the adopted-from
            # replica, so the journey timeline shows WHERE the chain came
            # from (docs/OBSERVABILITY.md span taxonomy).
            t0a = trace.now()
            entry = tab.adopt(self._spool_dir, sid)
            if entry is not None:
                trace.record(
                    "session_steal" if entry.adopt_how == "stolen"
                    else "session_adopt",
                    t0a, trace.now(), session_id=sid,
                    replica_id=tab.replica, epoch=entry.epoch,
                    adopted_from=entry.adopted_from)
        nonce_mismatch = (entry is not None and entry.nonce
                          and info.get("nonce")
                          and entry.nonce != info["nonce"])
        if entry is None or entry.epoch != info["base_epoch"] \
                or nonce_mismatch:
            # evicted / never established / epoch mismatch after a lost
            # response: the only safe answer is "re-establish" — applying
            # a delta onto the wrong base would silently diverge.  The
            # nonce arm closes the cross-lineage collision the model
            # checker found: a rolled-back old-incarnation record can
            # re-reach the very epoch this client acked, and the epoch
            # check alone would pass; matching chain IDENTITY (not just
            # position) makes the collision typed instead of silent.
            if protocol._SINK is not None:
                protocol.emit(sid, "serve_unknown", replica=tab.replica,
                              why=("nonce" if nonce_mismatch else
                                   "epoch" if entry is not None
                                   else "missing"))
            return _counted(DeltaReply(state="unknown", full=False),
                            "session_unknown")
        reseed = info["catalog_epoch"] != entry.catalog_epoch
        if reseed and not instance_types:
            # the catalog/price epoch moved and the new catalog is not
            # on the wire: every price the chain packed against is
            # stale, and there is nothing to re-pack with
            if protocol._SINK is not None:
                protocol.emit(sid, "serve_unknown", replica=tab.replica,
                              why="catalog")
            return _counted(DeltaReply(state="unknown", full=False),
                            "session_unknown")
        try:
            reply, outcome = self._apply_delta_step(
                entry, info, pods, provisioners, instance_types,
                kwargs, reseed, trace, _counted)
            # every incremental reply echoes the chain's identity nonce
            # so the client keeps sending the right one across reseeds
            # and guard-trip fallbacks (the chain object is the same)
            reply.nonce = entry.nonce
            if self._draining and reply.state == "ok":
                # drain handshake: the step was served (warm, committed),
                # its chain is handed off to the shared spool (record at
                # the acked epoch, lease RELEASED, entry dropped), and
                # the reply carries the DRAINING hint so the client
                # re-homes before this pod dies — the adopting sibling
                # serves the session's next delta warm.  Lifecycle span:
                # the handoff is the journey event that explains the
                # replica change the next hop's adopt span completes.
                t0h = trace.now()
                tab.handoff(sid, self._spool_dir)
                trace.record("session_drain_handoff", t0h, trace.now(),
                             session_id=sid, replica_id=tab.replica,
                             epoch=reply.epoch)
                reply.state = "draining"
            return reply, outcome
        # ktlint: allow[KT005] re-raised after eviction: the RPC thread
        # gets the real error, the poisoned chain never serves again
        except BaseException:
            # the step raised MID-APPLY: the chain may be half-mutated at
            # an unchanged epoch, and the client's cumulative retry would
            # pass the epoch check and re-apply onto a corrupted base —
            # evict, so the client re-establishes from scratch.  The
            # recovery outcome is counted whether the fault was injected
            # or organic (docs/RESILIENCE.md invariant: errors are typed,
            # recoveries are visible).
            tab.drop(sid, "error")
            faults_mod.count_recovery(self.registry, "delta_step",
                                      "evicted")
            raise

    def _apply_delta_step(self, entry: SessionEntry, info: dict, pods,
                          provisioners, instance_types, kwargs: dict,
                          reseed: bool, trace, _counted):
        """Apply one incremental step onto a live chain (dispatcher- or
        inline-thread, under _sched_lock either way).  Mutates the entry;
        the caller owns eviction if anything below raises."""
        # mid-mutation marker: from here until the epoch increments, this
        # chain must never be snapshotted (the spool writer skips it) —
        # set BEFORE the first mutation below, cleared after the commit
        entry.in_step = True
        if self._faults:
            effect = self._faults.fire("delta_step")
            if effect is not None and effect.kind == "slow_step":
                # injected latency while in_step is True: the adversary a
                # SIGTERM-mid-mutation snapshot must survive
                self._faults.sleep(effect)
        if reseed:
            entry.instance_types = instance_types
            if provisioners:
                entry.provisioners = provisioners
            entry.catalog_epoch = info["catalog_epoch"]
        prev = entry.prev
        # the step's watch set — every pod whose placement can change:
        # the adds, the removals, everything previously unplaced (removals
        # free capacity and re-offer them), and pods displaced off
        # reclaimed nodes.  The incremental tiers never move any other
        # pod (warmstart.py's by-construction contract), so the reply
        # only has to carry these.
        watch = {p.name for p in pods}
        watch.update(info["removed"])
        if gangmod.gang_enabled() and info["removed"]:
            # a member removal retracts the WHOLE gang (ISSUE 20): the
            # comembers' seats change too, so the delta reply must carry
            # them — the scheduler's own expansion decides their fate
            watch.update(gangmod.expand_gang_removals(
                prev, info["removed"])[0])
        watch.update(prev.infeasible)
        meta = getattr(prev, "_warmstart_meta", None)
        if meta is not None:
            watch.update(meta.unplaced)
        if info["reclaimed"]:
            by_name = {n.name: n
                       for n in list(prev.existing_nodes) + list(prev.nodes)}
            for rname in info["reclaimed"]:
                node = by_name.get(rname)
                if node is not None:
                    watch.update(p.name for p in node.pods)
        # ICE'd offerings accumulate on the ENTRY, not just the chain meta:
        # a guard-trip full fallback drops the meta, and the rebuild must
        # not forget offerings iced three steps ago
        entry.unavailable.update(tuple(u)
                                 for u in kwargs.get("unavailable") or ())
        outcome = self.scheduler.solve_delta(
            prev, added=pods, removed=info["removed"],
            iced=list(info["reclaimed"]),
            provisioners=entry.provisioners,
            instance_types=entry.instance_types,
            daemonsets=entry.daemonsets,
            unavailable=set(entry.unavailable) or None,
            force_full=reseed, trace=trace,
        )
        entry.prev = outcome.result
        if self._faults:
            # the half-mutated adversary: prev already replaced, epoch not
            # yet acked — a raise HERE must evict, never snapshot
            self._faults.fire("delta_commit")
        entry.epoch += 1
        entry.in_step = False
        if protocol._SINK is not None:
            # the COMMIT transition: the step is applied, the epoch is
            # acked — the event conformance checks against the model
            protocol.emit(entry.session_id, "commit",
                          replica=self._delta_tab.replica,
                          epoch=entry.epoch)
        if reseed:
            return _counted(
                _full_reply(outcome.result, entry.epoch, "reseed"), "reseed")
        if outcome.fell_back:
            # a warm-start guard tripped (KT_DELTA_MAX_FRAC, constraint
            # coupling, vocabulary miss): the step was served by the full
            # re-solve from the stripped base — correct, slower, and the
            # session survives; the reply is full-shaped
            return _counted(_full_reply(outcome.result, entry.epoch, "full"),
                            "fallback_full")
        res = outcome.result
        # node churn comes from the outcome's INCREMENTAL bookkeeping
        # (warmstart maintains created/pruned per step) — never a diff
        # over the fleet's node set, which would put an O(cluster) scan
        # on every sub-ms RPC
        meta2 = getattr(res, "_warmstart_meta", None)
        created = []
        if meta2 is not None:
            created = [meta2.nodes[meta2.node_idx[nm]].snapshot()
                       for nm in outcome.created_nodes
                       if nm in meta2.node_idx]
        reply = DeltaReply(
            state="ok", epoch=entry.epoch, mode=outcome.mode, full=False,
            assignments={n: res.assignments[n] for n in watch
                         if n in res.assignments},
            infeasible={n: res.infeasible[n] for n in watch
                        if n in res.infeasible},
            nodes=created,
            removed_nodes=list(outcome.pruned_nodes),
            solve_ms=outcome.solve_ms,
        )
        return _counted(reply, "delta")

    def _next_item(self, timeout: float):
        """Pop the next request from whichever front door is active.
        Admission path: priority-ordered pop + queue-delay accounting +
        the pre-dispatch deadline check — an expired ticket is rejected
        HERE, before any tensorize or device work happened for it."""
        if self._adm is None:
            return self._q.get(timeout=timeout)  # raises queue.Empty
        while True:
            ticket = self._adm.get(timeout=timeout)
            if ticket is None:
                raise queue.Empty
            self._adm.observe_dispatch(ticket)
            self._adm.breaker.poll()
            kwargs, fut, t_enq, t_wall = ticket.item
            if ticket.expired(self._adm.clock.now()):
                _resolve(fut, exc=self._adm.expire(ticket))
                timeout = 0.0  # deadline sheds must not reset the wait
                continue
            return kwargs, fut, t_enq, t_wall

    def _clamp_slots(self, n: int) -> int:
        """Bound a slot-cap ask against the global ladder and (meshed
        schedulers) floor/cap it at the mesh's device count / largest
        in-ladder rung — the ONE slot-clamp used at construction and at
        every live knob application, so a tuned cap can never flush
        half-empty shards or overflow the sharded program."""
        n = max(1, min(MEGA_MAX_SLOTS, int(n)))
        mesh = getattr(self.scheduler, "mesh", None)
        if mesh is not None and n > 1:
            n_dev = int(mesh.devices.size)
            if n_dev <= MEGA_MAX_SLOTS:
                n = min(max(n, n_dev), max_mega_slots(mesh))
        return n

    def _apply_knobs(self) -> None:
        """Dispatcher-owned knob application (caller holds _sched_lock):
        ONE atomic registry snapshot per iteration drives the coalescer's
        wait/slots, the brownout ladder's parameters, and the delta
        inline gate.  A knob the registry never overrode keeps its
        construction-time value byte-identically; a tuner update lands
        WHOLE at the next iteration, never mid-flush (ISSUE 19).  The
        brownout ladder's rungs then overlay the (possibly tuned) bases:
        rung 1+ zeroes the wait, rung 2+ caps the slots; back at level 0
        both revert."""
        snap = self.knobs.snapshot()
        self._knob_snap = snap
        base_wait = (max(0.0, snap.max_wait_ms) / 1000.0
                     if snap.is_overridden("max_wait_ms") else self.max_wait)
        base_slots = (self._clamp_slots(snap.max_slots)
                      if snap.is_overridden("max_slots") else self.max_slots)
        if self._adm is None:
            self._coal.max_wait = base_wait
            self._coal.max_slots = base_slots
            return
        if snap.is_overridden("brownout_ms"):
            self._adm.brownout.retune(
                step_s=max(0.0, snap.brownout_ms) / 1000.0)
        if snap.is_overridden("brownout_slot_cap"):
            self._adm.brownout.retune(slot_cap=int(snap.brownout_slot_cap))
        self._coal.max_wait = self._adm.brownout.max_wait(base_wait)
        self._coal.max_slots = self._adm.brownout.slot_cap(base_slots)

    def _effective_max_wait(self) -> float:
        snap = self._knob_snap
        base = (max(0.0, snap.max_wait_ms) / 1000.0
                if snap.is_overridden("max_wait_ms") else self.max_wait)
        if self._adm is None:
            return base
        return self._adm.brownout.max_wait(base)

    def _loop(self) -> None:
        while not self._stop.is_set():
            deadline = self._coal.deadline()
            if deadline is not None:
                timeout = min(0.1, max(0.0, deadline - self._clock.now()))
            else:
                timeout = 0.1
            try:
                kwargs, fut, t_enq, t_wall = self._next_item(timeout)
            except queue.Empty:
                if self._adm is not None:
                    # decay the brownout EWMA + poll the breaker feeds so
                    # recovery doesn't need traffic to make progress
                    self._adm.observe_idle()
                with self._sched_lock:
                    # tuned knobs (and brownout recovery) must land on
                    # idle ticks too — a quiet pipeline still converges
                    self._apply_knobs()
                    for reason, _key, batch in self._coal.poll():
                        self._flush(batch, reason)
                    if not len(self._coal):
                        self._drain(self._inflight.pop_to(0))
                    # idle tick: chains quiescent under _sched_lock — keep
                    # the spool fresh even when delta traffic rides the
                    # inline shortcut between dispatcher wakeups
                    self._maybe_snapshot()
                continue
            # in hand from pop to resolution (_flush/_finalize remove
            # it); coalescer-held requests stay in the ledger so a
            # stop() mid-hold fails them instead of stranding their
            # RPC threads.  A fut parked in _inflight is in the ledger
            # too — stop() may fail it twice (once per structure),
            # which _resolve absorbs.  Appended BEFORE acquiring the
            # ownership lock: the inline shortcut's _inline_ok reads
            # _in_hand, and appending later would open a window where a
            # just-popped request is invisible and an arriving delta
            # could overtake it.
            self._in_hand.append(fut)
            # every scheduler-touching section of an iteration holds the
            # ownership lock (the blocking queue wait above deliberately
            # does NOT): while the dispatcher works, the delta fast path's
            # inline shortcut cannot acquire and routes through the queue
            with self._sched_lock:
                self._apply_knobs()
                # close the queue-wait phase on the request's trace:
                # enqueue (RPC thread) -> pickup (this dispatcher)
                trace = kwargs.get("trace") or NULL_TRACE
                trace.record("window", t_enq, trace.now(),
                             inflight=len(self._inflight),
                             coalesced=len(self._coal))
                if "_delta" in kwargs:
                    # session-routed request: the delta fast path (bypasses
                    # the coalescer AND host routing — see _dispatch_delta;
                    # admission already ticketed it in its class)
                    kwargs.pop("_pclass", None)
                    self._dispatch_delta(kwargs, fut, t_enq, t_wall)
                    if self._inbound_idle() and not len(self._coal):
                        self._drain(self._inflight.pop_to(0))
                    continue
                if self._adm is not None:
                    pclass = kwargs.pop("_pclass", "") or ""
                    if pclass:
                        # remember the admitted class for the forwarding
                        # shim: a foreign-slot re-dispatch must carry it,
                        # or the owning host re-admits an already-admitted
                        # critical request as default-class and can shed
                        # it (cleared by _unhand on every resolution path)
                        self._fwd_pclass[fut] = pclass
                    host_reason = self._adm.route_host(pclass)
                    if host_reason is not None:
                        # breaker open / brownout rung 3+: this solve takes
                        # the host FFD tier — flush anything held first so
                        # response FIFO order survives, then dispatch on
                        # the single path
                        trace.annotate(host_routed=host_reason)
                        for reason, _key, batch in self._coal.flush("bucket"):
                            self._flush(batch, reason)
                        self._host_futs.add(fut)
                        self._dispatch_single(
                            kwargs, fut, t_enq, t_wall,
                            scheduler=self._host_scheduler())
                        continue
                key = self._bucket_of(kwargs)
                for reason, _key, batch in self._coal.add(
                        key, (kwargs, fut, t_enq, t_wall)):
                    self._flush(batch, reason)
                if len(self._coal) and self._inbound_idle() \
                        and self._effective_max_wait() <= 0.0:
                    # queue went idle with no wait configured: flush NOW so
                    # a lone request's latency matches the unbatched path;
                    # under real concurrency the queue is non-empty here
                    # and slots keep filling
                    for reason, _key, batch in self._coal.flush("deadline"):
                        self._flush(batch, reason)
        with self._sched_lock:
            for reason, _key, batch in self._coal.flush("deadline"):
                self._flush(batch, reason)
            self._drain(self._inflight.pop_to(0))


class SolverService:
    def __init__(self, scheduler: Optional[BatchScheduler] = None,
                 registry: Optional[Registry] = None,
                 tracer: Optional[Tracer] = None,
                 max_slots: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 knobs: Optional[Knobs] = None) -> None:
        self.registry = registry or default_registry
        self.scheduler = scheduler or BatchScheduler(registry=self.registry)
        # serving knobs for every pipeline this service constructs (None:
        # KT_MAX_SLOTS / KT_MAX_WAIT_MS env, then the module defaults)
        self.max_slots = max_slots
        self.max_wait_ms = max_wait_ms
        # per-RPC traces; default to the scheduler's tracer so the sidecar's
        # /tracez sees exactly what its scheduler recorded
        self.tracer = tracer or getattr(
            self.scheduler, "tracer", None) or tracer_for(self.registry)
        self._schedulers = {"": self.scheduler}  # guarded-by: _direct_lock
        # KT_SOLVE_PIPELINE=0 falls back to direct, lock-serialized solves
        self._pipelined = os.environ.get("KT_SOLVE_PIPELINE", "1") != "0"
        if not self._pipelined and admission_enabled():
            # admission control rides the pipeline's queue; the direct
            # debug path has none — say so loudly instead of letting the
            # operator believe overload protection is active while inert
            logging.getLogger(__name__).warning(
                "KT_SOLVE_PIPELINE=0: direct solves bypass admission "
                "control entirely (no priority queue, no deadline "
                "shedding, no breaker/brownout — docs/ADMISSION.md)")
        self._pipelines: dict = {}               # guarded-by: _direct_lock
        self._closed = False                     # guarded-by: _direct_lock
        self._direct_lock = threading.Lock()
        # time-resolved telemetry (ISSUE 18): the background registry
        # sampler (NULL_SAMPLER when KT_TS_INTERVAL_S <= 0), the span-
        # stream occupancy accountant publishing its gauges on the
        # sampler's tick, and the per-class SLO burn-rate engine whose
        # windowed numbers come off the sampler's rings
        self.sampler = sampler_for(self.registry, clock=self.tracer.clock)
        self._occupancy = OccupancyAccountant(
            self.registry, clock=self.tracer.clock,
            sample_every=self.tracer.sample_every)
        self.tracer.add_sink(self._occupancy.on_trace)
        self.slo = SloEngine(self.registry, sampler=self.sampler,
                             clock=self.tracer.clock,
                             replica=self.tracer.replica)
        # self-tuning (ISSUE 19, docs/TUNING.md): the live knob registry
        # is always on (it changes nothing until a knob is set); the
        # feedback controller arms only with KT_TUNE=1 AND a live
        # sampler — it rides the sampler's tick like the occupancy
        # accountant, so FakeClock harnesses drive it deterministically.
        # An injected registry keeps a tuned bench/test service from
        # leaking overrides into the process-global singleton.
        self.knobs = knobs if knobs is not None else global_knobs()
        tuning_zero_init(self.registry)
        self.tuner: Optional[TuningController] = None
        if tune_enabled() and self.sampler:
            self.tuner = TuningController(
                self.knobs, self.registry, sampler=self.sampler,
                slo=self.slo, tracer=self.tracer)
            self.sampler.add_hook(self.tuner.on_tick)
        if self.sampler:
            self.sampler.add_hook(self._occupancy.tick)
            self.sampler.start()

    def _scheduler_for(self, backend: str) -> BatchScheduler:
        if backend and backend != self.scheduler.backend:
            # locked check-then-create: two concurrent first RPCs for the
            # same backend must share ONE scheduler (and therefore one
            # pipeline — _pipeline_for keys on the scheduler instance; a
            # lost race here would leak a live dispatcher thread forever)
            with self._direct_lock:
                if backend not in self._schedulers:
                    self._schedulers[backend] = BatchScheduler(
                        backend=backend, registry=self.registry
                    )
                return self._schedulers[backend]
        return self.scheduler

    def _pipeline_for(self, sched: BatchScheduler) -> SolvePipeline:
        with self._direct_lock:  # concurrent first RPCs must share one pipe
            if self._closed:
                # a Solve racing close() must not construct a fresh pipeline
                # AFTER close()'s snapshot — its dispatcher thread would
                # outlive the service with nothing left to stop it
                raise RuntimeError("solver service closed")
            pipe = self._pipelines.get(id(sched))
            if pipe is None:
                pipe = SolvePipeline(sched, registry=self.registry,
                                     max_slots=self.max_slots,
                                     max_wait_ms=self.max_wait_ms,
                                     knobs=self.knobs)
                self._pipelines[id(sched)] = pipe
            return pipe

    def drain(self) -> None:
        """Graceful-drain every pipeline (the serve SIGTERM handshake):
        new sessions are refused with the DRAINING hint, served deltas
        hand their chains to the shared spool, clients re-home to
        siblings — call :meth:`close` after the drain window to stop."""
        with self._direct_lock:
            pipes = list(self._pipelines.values())
        for pipe in pipes:
            pipe.drain()

    def statusz_extra(self) -> dict:
        """The serving layer's /statusz extension (ISSUE 15): this
        replica's identity plus the per-session block — chain epoch,
        last-delta age, lease owner, adopted-from — aggregated over every
        backend pipeline's session table.  Handed to
        :func:`obs.export.statusz` / ``serve(extra=...)`` so obs/ never
        imports service/."""
        out: dict = {"replica_id": self.tracer.replica,
                     "draining": False}
        sessions: dict = {}
        with self._direct_lock:
            pipes = list(self._pipelines.values())
        for pipe in pipes:
            out["draining"] = out["draining"] or pipe.draining()
            tab = pipe._delta_tab
            if tab is not None:
                sessions.update(tab.sessions_status())
        if sessions:
            out["sessions"] = sessions
        return out

    def sloz(self) -> dict:
        """The /sloz document provider (obs.export.serve(sloz=...)):
        the burn-rate evaluation plus the occupancy gauges and the
        sampler's coverage, so one page answers both 'are we meeting
        the objectives' and 'are we provisioned for them'."""
        doc = self.slo.evaluate()
        doc["occupancy"] = {
            "device_busy_share":
                self.registry.gauge(OCCUPANCY_DEVICE_BUSY).get(),
            "megabatch_slot_fill":
                self.registry.gauge(OCCUPANCY_SLOT_FILL).get(),
            "delta_inline_fraction":
                self.registry.gauge(OCCUPANCY_DELTA_INLINE).get(),
        }
        doc["sampler"] = {
            "enabled": bool(self.sampler),
            "interval_s": self.sampler.interval_s,
            "series": self.sampler.series_count(),
            "coverage_s": self.sampler.coverage(
                window_s=max(s for _, s in SLO_WINDOWS)),
        }
        return doc

    def tunez(self) -> dict:
        """The /tunez document provider (obs.export.serve(tunez=...)):
        the live knob table — value, default, lattice, freeze/override
        state — plus the controller's recent decision ring when the
        feedback loop is armed (KT_TUNE=1)."""
        if self.tuner is not None:
            return self.tuner.tunez()
        return {"enabled": False, "knobs": self.knobs.describe(),
                "decisions": []}

    def close(self) -> None:
        # latch closed + snapshot under the lock (a late first RPC racing
        # shutdown must neither resize the dict mid-iteration nor construct
        # a never-stopped pipeline after the snapshot), stop outside it —
        # stop() joins the dispatcher, and a join under _direct_lock would
        # deadlock against a dispatcher-path call that takes the lock
        with self._direct_lock:
            self._closed = True
            pipes = list(self._pipelines.values())
        for pipe in pipes:
            pipe.stop()
        self.sampler.stop()
        self.tracer.remove_sink(self._occupancy.on_trace)

    # ---- RPC methods -----------------------------------------------------
    @staticmethod
    def _deadline_of(request: pb.SolveRequest, context) -> Optional[float]:
        """The caller's remaining deadline budget, seconds: an explicit
        ``deadline_ms`` wins, else the propagated gRPC deadline
        (``context.time_remaining()``), else None — the admission policy's
        ``KT_DEFAULT_DEADLINE_MS`` applies.  ``getattr`` fallbacks keep an
        old-proto request (no new fields) decoding to 'no deadline'."""
        ms = float(getattr(request, "deadline_ms", 0.0) or 0.0)
        if ms > 0:
            return ms / 1000.0
        if context is not None:
            remaining = getattr(context, "time_remaining", None)
            if callable(remaining):
                rem = remaining()
                if rem is not None:
                    return max(0.0, float(rem))
        return None

    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        kwargs = codec.decode_request(request)
        # gang audit at the door (ISSUE 20, docs/GANGS.md): a malformed
        # gang (members disagreeing on gang_size, oversubscribed roster)
        # refuses WHOLE with INVALID_ARGUMENT before admission ever queues
        # it — the gang is one ticket, so refusal is all-or-nothing too.
        # A well-formed request stays one admission unit either way: a
        # shed sheds the whole request, gangs included.
        try:
            gangmod.validate_batch(kwargs.get("pods", ()))
        except gangmod.GangValidationError as err:
            if context is None:
                raise
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(err))
        sess = codec.decode_delta_fields(request)
        sched = self._scheduler_for(request.backend)
        pclass = parse_class(getattr(request, "priority_class", ""))
        deadline_s = self._deadline_of(request, context)
        wire_trace, wire_parent = codec.decode_trace_fields(request)
        # one trace per RPC, threaded through the pipeline's dispatch/
        # finalize boundary via the kwargs dict (the dispatcher records the
        # queue-wait "window" span on it; the scheduler opens tensorize/
        # dispatch/fence/reseat under it); "respond" covers the encode back
        # onto the wire.  A request carrying a wire trace context ADOPTS
        # the remote parent (start_remote): the hop keeps the ORIGIN's
        # trace id, so a request crossing replicas — establishment here,
        # deltas on a steal-adopting sibling, a forwarded foreign slot —
        # renders as ONE tree in /fleetz.
        # SLO accounting (obs/slo.py): every Solve lands in exactly one
        # outcome bucket for its class — 'ok' served, 'shed' a typed
        # admission/deadline refusal (the protection worked, the caller
        # still wasn't served), 'error' anything unexpected (including a
        # context.abort raised for non-SLO reasons) — recorded in the
        # finally so aborts (which raise) are counted too.
        slo_outcome = "error"
        slo_ms = None
        try:
            with self.tracer.start_remote(
                "solve", wire_trace, wire_parent,
                rpc="Solve", backend=sched.backend,
                n_pods=len(kwargs.get("pods", ())), priority_class=pclass,
                delta=bool(sess and sess["delta"]),
                **({"session_id": sess["session_id"]} if sess else {}),
                # gang-bearing batches record their admission-unit count
                # (each gang = ONE ticket): n_pods vs gang_units is the
                # trace-visible gang compression of the request
                **({"gang_units": gangmod.admission_units(
                        kwargs.get("pods", ()))}
                   if gangmod.gang_enabled()
                   and gangmod.has_gangs(kwargs.get("pods", ())) else {}),
            ) as trace:
                kwargs["trace"] = trace
                if self._pipelined:
                    pipe = self._pipeline_for(sched)
                    if sess is not None and pipe.delta_live():
                        # session-routed: the pipeline's delta fast path
                        # resolves with a DeltaReply (still one admission
                        # ticket in its class — sheds surface here exactly
                        # like classic solves)
                        kwargs["_delta"] = sess
                        result = pipe.solve(kwargs, pclass=pclass,
                                            deadline_s=deadline_s)
                    elif sess is not None and sess["delta"]:
                        # delta request against a delta-off server: there
                        # is no chain to apply it to — tell the client to
                        # fall back to full solves (KT_DELTA=0 contract:
                        # no session state, no behavior change otherwise)
                        result = DeltaReply(state="unknown", full=False)
                    else:
                        result = pipe.solve(kwargs, pclass=pclass,
                                            deadline_s=deadline_s)
                else:
                    if sess is not None and sess["delta"]:
                        # the direct debug path (KT_SOLVE_PIPELINE=0) has
                        # no dispatcher and therefore no session table
                        result = DeltaReply(state="unknown", full=False)
                    else:
                        with self._direct_lock:
                            result = sched.solve(
                                kwargs.pop("pods"),
                                kwargs.pop("provisioners"),
                                kwargs.pop("instance_types"), **kwargs,
                            )
                with trace.span("respond"):
                    if isinstance(result, DeltaReply):
                        resp = codec.encode_delta_reply(result)
                    else:
                        resp = codec.encode_response(result)
                    # which replica served: failover-aware clients stamp
                    # this on their "remote" span, and offline dump
                    # correlation keys on it
                    resp.replica_id = self.tracer.replica
            slo_outcome = "ok"
            slo_ms = float(getattr(result, "solve_ms", 0.0) or 0.0) or None
        except SolveDeadlineError as err:
            # shed BEFORE tensorize/dispatch: the wire contract is
            # DEADLINE_EXCEEDED for expired budgets, RESOURCE_EXHAUSTED for
            # everything else admission refused (client.py maps both back
            # to the typed errors — no silent retry into an overloaded
            # server).  Direct callers (context=None) get the typed raise.
            slo_outcome = "shed"
            if context is None:
                raise
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(err))
        except SolveShedError as err:
            slo_outcome = "shed"
            if context is None:
                raise
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(err))
        finally:
            self.slo.record(pclass, slo_outcome, solve_ms=slo_ms)
        return resp

    def Warm(self, request: pb.WarmRequest, context) -> pb.WarmResponse:
        """Forwarded warm_startup: the operator ships its live provisioners,
        catalog, and cluster snapshots; compiles run behind on the sidecar's
        chips (BatchScheduler.warm_startup semantics, including signature
        dedupe, so repeated Warm calls are cheap)."""
        kwargs = codec.decode_warm_request(request)
        sched = self._scheduler_for(request.backend)
        started = sched.warm_startup(
            kwargs.pop("provisioners"), kwargs.pop("instance_types"), **kwargs
        )
        return pb.WarmResponse(started=started)

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        return pb.HealthResponse(
            ok=True, backend=jax.default_backend(), devices=len(jax.devices())
        )


def make_server(
    service: Optional[SolverService] = None,
    port: int = 0,
    # enough RPC threads to fill a full megabatch: handlers just block on
    # the pipeline's futures (the dispatcher does the work), so idle-parked
    # threads are cheap — but 4 workers would cap the coalescer's reachable
    # occupancy at 4 no matter how many clients queue
    max_workers: int = MEGA_MAX_SLOTS + 4,
    host: str = "127.0.0.1",
) -> "tuple[grpc.Server, int]":
    """``host`` may also be a ``unix:`` address (``unix:/run/kt/solver.sock``)
    — the same-pod sidecar topology: a reconciler sharing the pod dials the
    socket instead of paying TCP loopback per RPC (the delta fast path's
    steady-state RPCs are sub-millisecond, so transport RTT is a visible
    fraction of them).  Unix binds return port 0; dial the address itself."""
    service = service or SolverService()
    handlers = {
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.Solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        "Warm": grpc.unary_unary_rpc_method_handler(
            service.Warm,
            request_deserializer=pb.WarmRequest.FromString,
            response_serializer=pb.WarmResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.Health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                 ("grpc.max_send_message_length", 256 * 1024 * 1024)],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
    if host.startswith("unix:"):
        server.add_insecure_port(host)
        bound = 0  # no TCP port; clients dial the unix address
    else:
        bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="karpenter-tpu-solver")
    parser.add_argument("--port", type=int, default=50151)
    # 0.0.0.0: the deployed topology dials this across pods
    # (deploy/operator.yaml -> Service karpenter-tpu-solver); loopback would
    # strand the operator on its local fallback forever
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--backend", default="auto", choices=["auto", "tpu", "oracle"])
    parser.add_argument("--obs-port", type=int, default=0,
                        help="observability HTTP port (/tracez, /statusz, "
                             "/metrics); 0 disables")
    parser.add_argument("--max-slots", type=int, default=None,
                        help="megabatch request slots per coalescer flush "
                             f"(default KT_MAX_SLOTS or {DEFAULT_MAX_SLOTS}; "
                             "1 disables cross-request batching)")
    parser.add_argument("--max-wait-ms", type=float, default=None,
                        help="max hold before a partial batch flushes "
                             f"(default KT_MAX_WAIT_MS or "
                             f"{DEFAULT_MAX_WAIT_MS:g}; 0 flushes the "
                             "moment the inbound queue idles)")
    parser.add_argument("--warmup", action="store_true",
                        help="block until the AOT bucket-grid precompile "
                             "lands (single-solve ladder + megabatch slot "
                             "rungs against the generated catalog) before "
                             "accepting traffic; pair with --jit-cache-dir "
                             "to skip even this across restarts")
    parser.add_argument("--small", action="store_true",
                        help="--warmup against the 20-type catalog")
    parser.add_argument("--admission", choices=["on", "off"], default=None,
                        help="admission control & overload protection "
                             "(docs/ADMISSION.md): bounded priority queue, "
                             "deadline shedding, circuit breaker, brownout "
                             "(default KT_ADMISSION, on)")
    parser.add_argument("--default-priority", default=None,
                        choices=["critical", "batch", "best_effort"],
                        help="priority class for requests that carry none "
                             "(KT_DEFAULT_PRIORITY_CLASS; default batch)")
    parser.add_argument("--default-deadline-ms", type=float, default=None,
                        help="enqueue deadline applied when the RPC "
                             "carries none (KT_DEFAULT_DEADLINE_MS; 0 = "
                             "no deadline)")
    parser.add_argument("--session-dir", default=None,
                        help="delta-session snapshot spool "
                             "(KT_SESSION_DIR): chains spool here on "
                             "graceful shutdown and every "
                             "KT_SESSION_SNAPSHOT_S seconds, and are "
                             "restored at startup so a restarted replica "
                             "serves surviving sessions warm "
                             "(docs/RESILIENCE.md); empty disables")
    args = parser.parse_args(argv)
    # admission knobs land in the env so every pipeline the service lazily
    # constructs (per backend) picks them up uniformly
    if args.admission is not None:
        os.environ["KT_ADMISSION"] = "1" if args.admission == "on" else "0"
    if args.default_priority is not None:
        os.environ["KT_DEFAULT_PRIORITY_CLASS"] = args.default_priority
    if args.default_deadline_ms is not None:
        os.environ["KT_DEFAULT_DEADLINE_MS"] = str(args.default_deadline_ms)
    if args.session_dir is not None:
        # env, not a ctor param: every pipeline the service lazily
        # constructs (per backend) picks the spool up uniformly
        os.environ["KT_SESSION_DIR"] = args.session_dir
    service = SolverService(BatchScheduler(backend=args.backend),
                            max_slots=args.max_slots,
                            max_wait_ms=args.max_wait_ms)
    if args.warmup:
        from ..models.catalog import generate_catalog
        from ..models.provisioner import Provisioner

        print("warmup: AOT bucket-grid precompile running "
              "(single ladder + megabatch rungs)...", flush=True)
        # warm the slot cap this server will actually SERVE: a configured
        # --max-slots / KT_MAX_SLOTS above the default rung grid would
        # otherwise hit its first full flush cold and pay the megabatch
        # compile inline (KT014 pins this plumbing)
        cap = args.max_slots if args.max_slots is not None else int(
            os.environ.get("KT_MAX_SLOTS", str(DEFAULT_MAX_SLOTS)))
        cap = max(1, min(MEGA_MAX_SLOTS, cap))
        # the doubling ladder up to the cap, derived — not a literal that
        # rots the day MEGA_MAX_SLOTS moves (the KT014 drift class)
        grid, r = {cap}, 2
        while r < cap:
            grid.add(r)
            r *= 2
        n = service.scheduler.precompile_buckets(
            [Provisioner(name="default").with_defaults()],
            generate_catalog(full=not args.small),
            mega_slots=tuple(sorted(grid)),
            wait=True,
        )
        print(f"warmup: {n} bucket programs compiled; serving", flush=True)
    server, port = make_server(service, port=args.port, host=args.host)
    # admission rides the pipeline: with KT_SOLVE_PIPELINE=0 it is inert,
    # and the startup line must not claim otherwise
    admission_live = admission_enabled() and service._pipelined
    delta_live = delta_enabled() and service._pipelined
    print(f"solver sidecar listening on {args.host}:{port} "
          f"(backend={args.backend}, admission="
          f"{'on' if admission_live else 'off'}, delta="
          f"{'on' if delta_live else 'off'})")
    if args.obs_port:
        from ..obs import default_flight
        from ..obs.export import serve as obs_serve

        flight = service.tracer.flight or default_flight()
        # a unix: gRPC address is not a TCP hostname — the obs HTTP
        # server stays on loopback in the same-pod sidecar topology
        obs_host = ("127.0.0.1" if args.host.startswith("unix:")
                    else args.host)
        # the session block rides /statusz and KT_OBS_PEERS arms the
        # /fleetz fan-out (docs/OBSERVABILITY.md fleet tracing)
        _obs_server, obs_port = obs_serve(
            service.registry, flight, port=args.obs_port, host=obs_host,
            extra=service.statusz_extra, sloz=service.sloz,
            tunez=service.tunez)
        print(f"observability on http://{obs_host}:{obs_port}/tracez "
              f"(+/statusz /sloz /tunez /fleetz /metrics)")
    # graceful shutdown (ISSUE 12/13, docs/RESILIENCE.md): SIGTERM — the
    # kubelet's pod-termination signal, reinforced by deploy/solver.yaml's
    # preStop sleep — first enters the DRAIN handshake: new sessions are
    # refused with a session_state="draining" hint, every served delta
    # hands its chain to the KT_SESSION_DIR spool (lease released) on the
    # same reply, and clients proactively re-home to sibling replicas.
    # After KT_DRAIN_GRACE_S (or a second signal) the service stops, which
    # spools any remaining chains and releases their leases — whichever
    # replica each client lands on serves its next delta WARM.
    stop_ev = threading.Event()
    drain_ev = threading.Event()
    drain_grace = float(os.environ.get("KT_DRAIN_GRACE_S", "2"))

    def _graceful(signum, _frame):
        if not drain_ev.is_set():
            print(f"signal {signum}: draining — new sessions refused, "
                  f"chains handed to the session spool; exiting in "
                  f"{drain_grace:g}s (signal again to exit now)",
                  flush=True)
            drain_ev.set()
        else:
            stop_ev.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        while not drain_ev.wait(timeout=3600):
            pass
        service.drain()
        stop_ev.wait(timeout=drain_grace)
    except KeyboardInterrupt:
        pass
    print("drain window closed: snapshotting remaining delta sessions",
          flush=True)
    server.stop(grace=2.0)
    service.close()
    for sched in service._schedulers.values():
        sched.stop_warms()
    print("solver sidecar stopped", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
