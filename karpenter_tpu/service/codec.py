"""proto <-> model conversion for the solver service."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..models.instancetype import InstanceType, Offering, Overhead
from ..models.machine import Machine
from ..models.pod import (
    LabelSelector,
    PodAffinityTerm,
    PodSpec,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from ..models.provisioner import KubeletConfiguration, Provisioner
from ..models.requirements import Requirement, Requirements
from ..solver.types import SimNode, SolveResult
from . import solver_pb2 as pb

# ---------------------------------------------------------------------------
# encode (model -> proto)
# ---------------------------------------------------------------------------


def _q(resource: str, value: float) -> pb.Quantity:
    return pb.Quantity(resource=resource, value=value)


def _quantities(d) -> List[pb.Quantity]:
    return [_q(k, v) for k, v in sorted(d.items())]


def _req(r: Requirement) -> pb.Requirement:
    return pb.Requirement(key=r.key, op=r.operator, values=list(r.values))


def _selector(s: LabelSelector) -> pb.LabelSelector:
    out = pb.LabelSelector()
    for k, v in s.match_labels:
        out.match_labels[k] = v
    out.match_expressions.extend(_req(r) for r in s.match_expressions)
    return out


def encode_pod(p: PodSpec) -> pb.Pod:
    out = pb.Pod(
        name=p.name, namespace=p.namespace, priority=p.priority,
        deletion_cost=p.deletion_cost, owner=p.owner_key,
        gang_id=p.gang_id, gang_size=p.gang_size,
    )
    for k, v in p.labels.items():
        out.labels[k] = v
    out.requests.extend(_quantities(p.requests))
    for k, v in p.node_selector.items():
        out.node_selector[k] = v
    for term in p.required_affinity_terms:
        out.required_affinity.append(pb.RequirementTerm(requirements=[_req(r) for r in term]))
    out.tolerations.extend(
        pb.Toleration(key=t.key, op=t.operator, value=t.value, effect=t.effect)
        for t in p.tolerations
    )
    out.spread.extend(
        pb.TopologySpread(max_skew=t.max_skew, topology_key=t.topology_key,
                          hard=t.hard, selector=_selector(t.label_selector))
        for t in p.topology_spread
    )
    out.affinity.extend(
        pb.AffinityTerm(selector=_selector(t.label_selector),
                        topology_key=t.topology_key, anti=t.anti)
        for t in p.affinity_terms
    )
    out.volume_zone_requirements.extend(_req(r) for r in p.volume_zone_requirements)
    return out


def encode_instance_type(it: InstanceType) -> pb.InstanceType:
    out = pb.InstanceType(name=it.name)
    out.requirements.extend(_req(r) for r in it.requirements.to_list())
    out.offerings.extend(
        pb.Offering(zone=o.zone, capacity_type=o.capacity_type,
                    price=o.price, available=o.available)
        for o in it.offerings
    )
    out.capacity.extend(_quantities(it.capacity))
    out.overhead.extend(_quantities(it.overhead.total()))  # legacy decoders
    out.overhead_kube.extend(_quantities(it.overhead.kube_reserved))
    out.overhead_system.extend(_quantities(it.overhead.system_reserved))
    out.overhead_eviction.extend(_quantities(it.overhead.eviction_threshold))
    out.has_overhead_components = True
    return out


def encode_provisioner(p: Provisioner) -> pb.Provisioner:
    out = pb.Provisioner(
        name=p.name, weight=p.weight, consolidation_enabled=p.consolidation_enabled,
    )
    out.requirements.extend(_req(r) for r in p.requirements)
    out.taints.extend(pb.Taint(key=t.key, value=t.value, effect=t.effect) for t in p.taints)
    out.startup_taints.extend(
        pb.Taint(key=t.key, value=t.value, effect=t.effect) for t in p.startup_taints
    )
    for k, v in p.labels.items():
        out.labels[k] = v
    out.limits.extend(_quantities(p.limits))
    if p.kubelet is not None:
        kc = p.kubelet
        out.kubelet.CopyFrom(pb.KubeletConfiguration(
            has_max_pods=kc.max_pods is not None,
            max_pods=kc.max_pods or 0,
            has_pods_per_core=kc.pods_per_core is not None,
            pods_per_core=kc.pods_per_core or 0,
        ))
        out.kubelet.system_reserved.extend(_quantities(kc.system_reserved))
        out.kubelet.kube_reserved.extend(_quantities(kc.kube_reserved))
        for k, v in kc.eviction_hard.items():
            out.kubelet.eviction_hard[k] = v
        for k, v in kc.eviction_soft.items():
            out.kubelet.eviction_soft[k] = v
    return out


def encode_node(n: SimNode) -> pb.ExistingNode:
    out = pb.ExistingNode(
        name=n.name, instance_type=n.instance_type, provisioner=n.provisioner,
        zone=n.zone, capacity_type=n.capacity_type, price=n.price,
    )
    out.allocatable.extend(_quantities(n.allocatable))
    for k, v in n.labels.items():
        out.labels[k] = v
    out.taints.extend(pb.Taint(key=t.key, value=t.value, effect=t.effect) for t in n.taints)
    out.pods.extend(encode_pod(p) for p in n.pods)
    return out


def encode_request(
    pods: Sequence[PodSpec],
    provisioners: Sequence[Provisioner],
    instance_types: Sequence[InstanceType],
    existing_nodes: Sequence[SimNode] = (),
    daemonsets: Sequence[PodSpec] = (),
    unavailable: Optional[Set[tuple]] = None,
    allow_new_nodes: bool = True,
    max_new_nodes: Optional[int] = None,
    backend: str = "",
    priority: str = "",
    deadline_ms: Optional[float] = None,
    session_id: str = "",
    base_epoch: int = 0,
    delta: bool = False,
    removed_pods: Sequence[str] = (),
    reclaimed_nodes: Sequence[str] = (),
    catalog_epoch: int = 0,
    trace_id: str = "",
    parent_span: str = "",
    session_nonce: str = "",
) -> pb.SolveRequest:
    # admission fields (docs/ADMISSION.md): "" / 0 are the backward-
    # compatible wire defaults — the server folds them into its configured
    # default class / deadline, so an old client is indistinguishable from
    # one that sent nothing.  The delta-session fields (ARCHITECTURE.md
    # round 14) default the same way: an empty session_id is a classic
    # full solve; delta=True reuses `pods` for the ADDED pods and
    # `unavailable` for the newly ICE'd offerings.  The trace context
    # (ISSUE 15) defaults to "no context": the server roots locally.
    req = pb.SolveRequest(allow_new_nodes=allow_new_nodes, backend=backend,
                          priority_class=priority or "",
                          deadline_ms=float(deadline_ms or 0.0),
                          session_id=session_id or "",
                          base_epoch=int(base_epoch or 0),
                          delta=bool(delta),
                          catalog_epoch=int(catalog_epoch or 0),
                          trace_id=trace_id or "",
                          parent_span=parent_span or "",
                          session_nonce=session_nonce or "")
    req.removed_pods.extend(removed_pods)
    req.reclaimed_nodes.extend(reclaimed_nodes)
    req.pods.extend(encode_pod(p) for p in pods)
    req.provisioners.extend(encode_provisioner(p) for p in provisioners)
    req.instance_types.extend(encode_instance_type(t) for t in instance_types)
    req.existing_nodes.extend(encode_node(n) for n in existing_nodes)
    req.daemonsets.extend(encode_pod(p) for p in daemonsets)
    for (t, z, c) in sorted(unavailable or ()):
        req.unavailable.append(pb.UnavailableOffering(instance_type=t, zone=z, capacity_type=c))
    if max_new_nodes is not None:
        req.has_max_new_nodes = True
        req.max_new_nodes = max_new_nodes
    return req


def encode_warm_request(
    provisioners: Sequence[Provisioner],
    instance_types: Sequence[InstanceType],
    daemonsets: Sequence[PodSpec] = (),
    existing_nodes: Sequence[SimNode] = (),
    backend: str = "",
) -> pb.WarmRequest:
    req = pb.WarmRequest(backend=backend)
    req.provisioners.extend(encode_provisioner(p) for p in provisioners)
    req.instance_types.extend(encode_instance_type(t) for t in instance_types)
    req.daemonsets.extend(encode_pod(p) for p in daemonsets)
    req.existing_nodes.extend(encode_node(n) for n in existing_nodes)
    return req


def encode_response(result: SolveResult) -> pb.SolveResponse:
    out = pb.SolveResponse(solve_ms=result.solve_ms)
    for n in result.nodes:
        out.nodes.append(pb.NewNode(
            name=n.name, instance_type=n.instance_type, provisioner=n.provisioner,
            zone=n.zone, capacity_type=n.capacity_type, price=n.price,
            pod_names=[p.name for p in n.pods],
        ))
    for k, v in result.assignments.items():
        out.assignments[k] = v
    for k, v in result.infeasible.items():
        out.infeasible[k] = v
    return out


# ---------------------------------------------------------------------------
# decode (proto -> model)
# ---------------------------------------------------------------------------


def _qdict(qs) -> Dict[str, float]:
    return {q.resource: q.value for q in qs}


def _dreq(r: pb.Requirement) -> Requirement:
    return Requirement(r.key, r.op, list(r.values))


def _dselector(s: pb.LabelSelector) -> LabelSelector:
    return LabelSelector(
        tuple(sorted(s.match_labels.items())),
        tuple(_dreq(r) for r in s.match_expressions),
    )


def decode_pod(p: pb.Pod) -> PodSpec:
    return PodSpec(
        name=p.name,
        namespace=p.namespace or "default",
        labels=dict(p.labels),
        requests=_qdict(p.requests),
        node_selector=dict(p.node_selector),
        required_affinity_terms=[[_dreq(r) for r in t.requirements] for t in p.required_affinity],
        tolerations=[Toleration(t.key, t.op or "Equal", t.value, t.effect) for t in p.tolerations],
        topology_spread=[
            TopologySpreadConstraint(
                t.max_skew, t.topology_key,
                "DoNotSchedule" if t.hard else "ScheduleAnyway",
                _dselector(t.selector),
            )
            for t in p.spread
        ],
        affinity_terms=[
            PodAffinityTerm(_dselector(t.selector), t.topology_key, t.anti)
            for t in p.affinity
        ],
        priority=p.priority,
        deletion_cost=p.deletion_cost or 1.0,
        owner_key=p.owner,
        volume_zone_requirements=[_dreq(r) for r in p.volume_zone_requirements],
        # old wire bytes carry no gang tags and decode to ""/0 = ungrouped
        gang_id=p.gang_id,
        gang_size=p.gang_size,
    )


def decode_instance_type(it: pb.InstanceType) -> InstanceType:
    return InstanceType(
        name=it.name,
        requirements=Requirements([_dreq(r) for r in it.requirements]),
        offerings=[
            Offering(o.zone, o.capacity_type, o.price, o.available) for o in it.offerings
        ],
        capacity=_qdict(it.capacity),
        overhead=(
            Overhead(
                kube_reserved=_qdict(it.overhead_kube),
                system_reserved=_qdict(it.overhead_system),
                eviction_threshold=_qdict(it.overhead_eviction),
            )
            if it.has_overhead_components
            # older encoders: field 5 carries either the pre-summed total
            # (original wire format; fields 6/7 empty) or kube-reserved with
            # system/eviction in 6/7 — reading 6/7 here is correct for both
            # (empty lists decode to {} for the original format)
            else Overhead(
                kube_reserved=_qdict(it.overhead),
                system_reserved=_qdict(it.overhead_system),
                eviction_threshold=_qdict(it.overhead_eviction),
            )
        ),
    )


def decode_provisioner(p: pb.Provisioner) -> Provisioner:
    kubelet = None
    if p.HasField("kubelet"):
        kc = p.kubelet
        kubelet = KubeletConfiguration(
            max_pods=kc.max_pods if kc.has_max_pods else None,
            pods_per_core=kc.pods_per_core if kc.has_pods_per_core else None,
            system_reserved=_qdict(kc.system_reserved),
            kube_reserved=_qdict(kc.kube_reserved),
            eviction_hard=dict(kc.eviction_hard),
            eviction_soft=dict(kc.eviction_soft),
        )
    return Provisioner(
        name=p.name,
        requirements=[_dreq(r) for r in p.requirements],
        taints=[Taint(t.key, t.effect, t.value) for t in p.taints],
        startup_taints=[Taint(t.key, t.effect, t.value) for t in p.startup_taints],
        labels=dict(p.labels),
        limits=_qdict(p.limits),
        weight=p.weight,
        consolidation_enabled=p.consolidation_enabled,
        kubelet=kubelet,
    )


def decode_node(n: pb.ExistingNode) -> SimNode:
    return SimNode(
        instance_type=n.instance_type,
        provisioner=n.provisioner,
        zone=n.zone,
        capacity_type=n.capacity_type,
        price=n.price,
        allocatable=_qdict(n.allocatable),
        labels=dict(n.labels),
        taints=[Taint(t.key, t.effect, t.value) for t in n.taints],
        pods=[decode_pod(p) for p in n.pods],
        existing=True,
        name=n.name,
    )


def decode_request(req: pb.SolveRequest):
    return dict(
        pods=[decode_pod(p) for p in req.pods],
        provisioners=[decode_provisioner(p) for p in req.provisioners],
        instance_types=[decode_instance_type(t) for t in req.instance_types],
        existing_nodes=[decode_node(n) for n in req.existing_nodes],
        daemonsets=[decode_pod(p) for p in req.daemonsets],
        unavailable={(u.instance_type, u.zone, u.capacity_type) for u in req.unavailable},
        allow_new_nodes=req.allow_new_nodes,
        max_new_nodes=req.max_new_nodes if req.has_max_new_nodes else None,
    )


def decode_trace_fields(req: pb.SolveRequest) -> "Tuple[str, str]":
    """The wire trace context of a SolveRequest: ``(trace_id,
    parent_span)``.  ``("", "")`` — old clients, unsampled origins —
    means "no remote parent"; every server entry that reads this must
    open its trace through ``Tracer.start_remote`` (ktlint KT019), which
    maps the empty context to a plain local start."""
    return (getattr(req, "trace_id", "") or "",
            getattr(req, "parent_span", "") or "")


def decode_delta_fields(req: pb.SolveRequest) -> Optional[dict]:
    """The delta-session envelope of a SolveRequest, or None for a classic
    (sessionless) solve.  Kept OUT of :func:`decode_request`'s dict — that
    dict feeds ``scheduler.solve(**kwargs)`` verbatim, and an old decoder
    reading new-field defaults must keep behaving like a plain solve."""
    sid = getattr(req, "session_id", "")
    if not sid:
        return None
    return dict(
        session_id=sid,
        base_epoch=int(getattr(req, "base_epoch", 0)),
        delta=bool(getattr(req, "delta", False)),
        removed=list(getattr(req, "removed_pods", ())),
        reclaimed=list(getattr(req, "reclaimed_nodes", ())),
        catalog_epoch=int(getattr(req, "catalog_epoch", 0)),
        # chain-identity nonce (ISSUE 17 divergence fix): "" from an old
        # client is the legacy wildcard — the server's nonce check only
        # fires when BOTH sides carry one
        nonce=str(getattr(req, "session_nonce", "") or ""),
    )


def encode_delta_reply(reply) -> pb.SolveResponse:
    """service/delta.DeltaReply -> wire.  Incremental replies carry only
    the step's changes; ``session_state``/``session_epoch``/``delta_mode``
    tell the client how to merge (service/client.DeltaSession)."""
    out = pb.SolveResponse(
        solve_ms=reply.solve_ms,
        session_epoch=int(reply.epoch),
        session_state=reply.state,
        delta_mode=reply.mode,
        session_nonce=getattr(reply, "nonce", "") or "",
    )
    for n in reply.nodes:
        out.nodes.append(pb.NewNode(
            name=n.name, instance_type=n.instance_type,
            provisioner=n.provisioner, zone=n.zone,
            capacity_type=n.capacity_type, price=n.price,
            pod_names=[p.name for p in n.pods],
        ))
    for k, v in reply.assignments.items():
        out.assignments[k] = v
    for k, v in reply.infeasible.items():
        out.infeasible[k] = v
    out.removed_nodes.extend(reply.removed_nodes)
    return out


#: delta_mode values whose reply carries the WHOLE solution (the client
#: replaces its ledger wholesale instead of merging the step's changes)
FULL_REPLY_MODES = ("establish", "reseed", "full", "")


def decode_delta_reply(resp: pb.SolveResponse):
    """wire -> service/delta.DeltaReply (node pods are name-stub PodSpecs,
    like :func:`decode_response`; DeltaSession re-attaches its ledger's
    real objects)."""
    from .delta import DeltaReply

    nodes = []
    for n in resp.nodes:
        node = SimNode(
            instance_type=n.instance_type, provisioner=n.provisioner,
            zone=n.zone, capacity_type=n.capacity_type, price=n.price,
            allocatable={}, name=n.name,
        )
        node.pods = [PodSpec(name=pn) for pn in n.pod_names]
        nodes.append(node)
    mode = getattr(resp, "delta_mode", "")
    return DeltaReply(
        state=getattr(resp, "session_state", ""),
        epoch=int(getattr(resp, "session_epoch", 0)),
        mode=mode,
        full=mode in FULL_REPLY_MODES,
        assignments=dict(resp.assignments),
        infeasible=dict(resp.infeasible),
        nodes=nodes,
        removed_nodes=list(getattr(resp, "removed_nodes", ())),
        solve_ms=resp.solve_ms,
        nonce=str(getattr(resp, "session_nonce", "") or ""),
    )


def decode_warm_request(req: pb.WarmRequest):
    return dict(
        provisioners=[decode_provisioner(p) for p in req.provisioners],
        instance_types=[decode_instance_type(t) for t in req.instance_types],
        daemonsets=[decode_pod(p) for p in req.daemonsets],
        existing_nodes=[decode_node(n) for n in req.existing_nodes],
    )


def decode_response(resp: pb.SolveResponse) -> SolveResult:
    nodes = []
    for n in resp.nodes:
        node = SimNode(
            instance_type=n.instance_type, provisioner=n.provisioner, zone=n.zone,
            capacity_type=n.capacity_type, price=n.price, allocatable={},
            name=n.name,
        )
        node.pods = [PodSpec(name=pn) for pn in n.pod_names]
        nodes.append(node)
    return SolveResult(
        nodes=nodes,
        assignments=dict(resp.assignments),
        infeasible=dict(resp.infeasible),
        solve_ms=resp.solve_ms,
    )
