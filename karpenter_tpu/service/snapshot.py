"""Session spool — versioned, checksummed, SESSION-ADDRESSABLE storage of
live delta chains, plus the ownership-lease API that makes it multi-writer
safe (ISSUE 12 tentpole; fleet handoff reworked in ISSUE 13 —
docs/RESILIENCE.md).

PR 10 made steady-state serving session-stateful; a replica restart then
destroys every ``_warmstart_meta`` chain and costs one full re-establishing
solve PER CLIENT.  PR 12 spooled the whole table to one file so a replica
RESTART resumes warm; this revision makes the spool the FLEET's handoff
medium: each session is its own record file under
``KT_SESSION_DIR/<backend>/sessions/``, guarded by a lease file under
``.../leases/``, so ANY replica sharing the volume (a shared PVC) can
restore a specific session on demand (``DeltaSessionTable.adopt``) — not
just its own table at boot — while the lease protocol guarantees two
replicas can never both adopt one chain.

Record layout (one file per session, ``sessions/<sid>.snap``)::

    MAGIC(8) | version(>I) | payload_len(>Q) | sha256(payload)(32) | payload

``payload`` is a pickle of ``{"schema": ..., "catalog_epoch": ...,
"entries": [one entry blob]}`` — pickle is the right tool here because the
spool is written and read by the SAME binary (the chain carries numpy
residual matrices and the full SimNode graph, and pickle preserves the
node-object identity sharing between ``result.nodes`` and ``meta.nodes``
that the warm-start tiers rely on).  What makes it safe is the envelope:

- **Atomic**: write-temp + fsync + rename — a SIGKILL mid-write leaves
  the previous spool intact, never a torn file.
- **Checksummed**: a flipped byte anywhere in the payload fails the
  sha256 and the restore refuses (``corrupt``).
- **Length-framed**: a truncated payload is detected BEFORE the checksum
  (``truncated``) so operators can tell disk-full from bit-rot.
- **Versioned twice**: the format version (:data:`SNAPSHOT_VERSION`) and
  a schema fingerprint derived from the live dataclass fields of
  ``SolveResult`` + ``warmstart._Meta`` — a refactor that changes the
  chain shape auto-invalidates old spools (``version``) instead of
  unpickling into a subtly different world.
- **Catalog-gated**: a spool whose catalog epoch DIFFERS from the
  configured ``KT_CATALOG_EPOCH`` is refused whole (``catalog_epoch``)
  — older or newer, a chain packed against another epoch's prices must
  not serve warm.

Every refusal is a COLD START plus a counted reason
(``karpenter_solver_session_snapshot_restore_total{outcome}``), never a
crash and never a diverged chain.

The lease protocol (``leases/<sid>.lease``, JSON ``{owner, expires_at}``):

- **Claim** (:func:`claim_lease`) — an ``O_CREAT|O_EXCL`` create: exactly
  one creator wins on a shared POSIX volume.  Claiming your OWN lease
  renews it (write-temp + rename, safe because you own it).
- **Refusal** — an unexpired lease held by another owner raises the typed
  :class:`LeaseHeld`; the caller counts it and answers the client
  ``session_unknown`` (one re-establish, the PR-10 floor) instead of
  splitting the chain's ownership.
- **Steal after expiry** — an EXPIRED foreign lease is stolen by renaming
  it to a per-claimant tombstone (two concurrent stealers race the
  rename; exactly one wins, the loser re-reads and refuses) and then
  re-claimed with the same exclusive create.  A live owner renews on
  every record write, so only a dead (or wedged-past-TTL) replica's
  sessions are stealable — the failover-warmness window IS the lease TTL
  (``KT_SESSION_LEASE_S``).

Ownership is verified on every record write: a zombie replica whose lease
was stolen gets :class:`LeaseHeld` back from its renewal and must DROP the
chain (counted ``lease_lost``) — it can neither serve another epoch of it
nor clobber the adopter's newer record.

ktlint **KT017** pins this file (plus the ``DeltaSessionTable`` facade in
``service/delta.py``) as the ONLY place in ``service/`` allowed to touch
the record/lease primitives — a drive-by ``open()`` of a spool path from
the server or client layer would bypass the exactly-one-owner protocol.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import struct
import time as _time
from typing import Dict, List, Optional, Tuple

MAGIC = b"KTSESS1\n"
#: bump when the envelope layout changes (the schema fingerprint below
#: covers chain-SHAPE drift automatically)
SNAPSHOT_VERSION = 1
_HEADER = struct.Struct(">IQ")  # version, payload length
#: legacy PR-12 whole-table spool file name (reworked to per-session
#: records in ISSUE 13; the name survives for the tombstone check below)
SPOOL_NAME = "sessions.snap"
#: per-session record files live here, one ``<sid>.snap`` each
SESSIONS_SUBDIR = "sessions"
#: per-session ownership leases live here, one ``<sid>.lease`` each
LEASES_SUBDIR = "leases"
RECORD_SUFFIX = ".snap"
LEASE_SUFFIX = ".lease"
#: default ownership-lease TTL, seconds (KT_SESSION_LEASE_S).  A dead
#: replica's sessions become stealable this long after its last record
#: write — the fleet's failover-warmness window.  Graceful paths (drain,
#: SIGTERM shutdown) RELEASE leases so adoption is instant.
DEFAULT_LEASE_S = 10.0

_REPLICA_ID: Optional[str] = None


def replica_id() -> str:
    """This process's stable spool-owner identity: ``KT_REPLICA_ID`` (the
    deploy sets the pod name) or a generated ``<host>-<pid>-<rand>``.
    Cached per process, so a restarted in-process service (tests, the
    single-replica topology) self-renews its own leases and resumes warm
    without waiting out the TTL."""
    global _REPLICA_ID
    env = os.environ.get("KT_REPLICA_ID", "")
    if env:
        return env
    if _REPLICA_ID is None:
        import socket
        import uuid

        _REPLICA_ID = (f"{socket.gethostname()}-{os.getpid()}-"
                       f"{uuid.uuid4().hex[:8]}")
    return _REPLICA_ID


class LeaseHeld(Exception):
    """Typed adoption refusal: another replica holds an UNEXPIRED lease on
    this session — exactly one owner per chain, by construction."""

    def __init__(self, session_id: str, owner: str,
                 expires_at: float) -> None:
        super().__init__(
            f"session {session_id!r} lease held by {owner!r} "
            f"until {expires_at:.3f}")
        self.session_id = session_id
        self.owner = owner
        self.expires_at = expires_at


class SnapshotRefused(Exception):
    """A spool file that must not be restored.  ``reason`` is one of the
    ``SNAPSHOT_RESTORE_OUTCOMES`` labels (corrupt / truncated / version /
    catalog_epoch) — the caller counts it and cold-starts."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(f"session snapshot refused ({reason}): {detail}")
        self.reason = reason


def chain_schema() -> str:
    """Fingerprint of the live chain shape: the dataclass fields of the
    result and warm-start bookkeeping the spool pickles.  Computed from
    the RUNNING code, so a refactor that adds/renames a field refuses old
    spools without anyone remembering to bump a constant."""
    from ..solver.types import SimNode, SolveResult
    from ..solver.warmstart import _Meta

    names = "|".join(
        ",".join(sorted(cls.__dataclass_fields__))
        for cls in (SolveResult, _Meta, SimNode)
        if hasattr(cls, "__dataclass_fields__"))
    return hashlib.sha256(names.encode()).hexdigest()[:16]


def spool_path(dir_path: str) -> str:
    return os.path.join(dir_path, SPOOL_NAME)


def pack_entry(entry: dict) -> bytes:
    """One session entry -> its own pickle blob.  Entries are pickled
    INDIVIDUALLY so the table can serialize them without any scheduler
    lock: a chain that mutates under the pickler corrupts (or tears)
    only its own blob, which the caller detects via the epoch/in_step
    re-check and discards — the spool never carries a torn chain."""
    return pickle.dumps(entry, protocol=pickle.HIGHEST_PROTOCOL)


def unpack_entry(blob: bytes) -> dict:
    return pickle.loads(blob)


def pack(entries: list, catalog_epoch: int = 0) -> bytes:
    """Serialize per-entry blobs (from :func:`pack_entry`) into one
    framed, checksummed spool blob."""
    payload = pickle.dumps(
        {"schema": chain_schema(), "catalog_epoch": int(catalog_epoch),
         "entries": entries},
        protocol=pickle.HIGHEST_PROTOCOL)
    buf = io.BytesIO()
    buf.write(MAGIC)
    buf.write(_HEADER.pack(SNAPSHOT_VERSION, len(payload)))
    buf.write(hashlib.sha256(payload).digest())
    buf.write(payload)
    return buf.getvalue()


def unpack(blob: bytes,
           expected_catalog_epoch: Optional[int] = None) -> Tuple[list, int]:
    """Validate + deserialize a spool blob -> (entries, catalog_epoch).

    Raises :class:`SnapshotRefused` with the counted reason on every
    adversarial shape: wrong magic / failed checksum / undecodable
    (``corrupt``), short payload (``truncated``), format-version or
    chain-schema drift (``version``), stale catalog (``catalog_epoch``).
    """
    head_len = len(MAGIC) + _HEADER.size + 32
    if len(blob) < head_len:
        raise SnapshotRefused("truncated",
                              f"{len(blob)}B < {head_len}B header")
    if blob[:len(MAGIC)] != MAGIC:
        raise SnapshotRefused("corrupt", "bad magic")
    version, length = _HEADER.unpack_from(blob, len(MAGIC))
    if version != SNAPSHOT_VERSION:
        raise SnapshotRefused(
            "version", f"format v{version}, want v{SNAPSHOT_VERSION}")
    digest = blob[len(MAGIC) + _HEADER.size:head_len]
    payload = blob[head_len:]
    if len(payload) < length:
        raise SnapshotRefused(
            "truncated", f"payload {len(payload)}B < declared {length}B")
    payload = payload[:length]
    if hashlib.sha256(payload).digest() != digest:
        raise SnapshotRefused("corrupt", "payload checksum mismatch")
    try:
        doc = pickle.loads(payload)
    # ktlint: allow[KT005] any undecodable payload is the same outcome: a
    # refused snapshot, counted 'corrupt', cold start
    except Exception as err:  # noqa: BLE001
        raise SnapshotRefused("corrupt", f"unpickle failed: {err}") from err
    if not isinstance(doc, dict) or "entries" not in doc:
        raise SnapshotRefused("corrupt", "payload is not a snapshot doc")
    if doc.get("schema") != chain_schema():
        raise SnapshotRefused(
            "version", "chain schema drift (warm-start bookkeeping shape "
            "changed since this spool was written)")
    epoch = int(doc.get("catalog_epoch", 0))
    if (expected_catalog_epoch is not None
            and epoch != int(expected_catalog_epoch)):
        raise SnapshotRefused(
            "catalog_epoch",
            f"spool catalog epoch {epoch} != configured "
            f"{expected_catalog_epoch}")
    return list(doc["entries"]), epoch


def _atomic_write(path: str, blob: bytes) -> str:
    """The one atomic file-install primitive every spool write rides:
    write-temp + fsync + rename.  The temp lives in the SAME directory
    so the rename is atomic on one mount, and carries a per-writer
    (pid + thread) suffix so concurrent writers can never interleave
    inside one temp file."""
    import threading

    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as fh:
        fh.write(blob)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    return path


def write_atomic(dir_path: str, blob: bytes) -> str:
    """Legacy whole-table spool write: either the complete new snapshot
    or the complete previous one — never a torn file."""
    os.makedirs(dir_path, exist_ok=True)
    return _atomic_write(spool_path(dir_path), blob)


def read(dir_path: str) -> Optional[bytes]:
    """The legacy whole-table spool's bytes, or None when no snapshot
    exists (plain cold start, counted 'missing')."""
    try:
        with open(spool_path(dir_path), "rb") as fh:
            return fh.read()
    except FileNotFoundError:
        return None


# ---------------------------------------------------------------------------
# session-addressable records (ISSUE 13: the fleet's shared-spool layout)
# ---------------------------------------------------------------------------

def _safe_name(session_id: str) -> str:
    """Filesystem-safe encoding of a session id.  Ids are uuid hex in
    production, but the spool must not trust the wire: anything outside
    ASCII [A-Za-z0-9._-] is escaped as fixed-width per-UTF-8-byte
    ``%xx`` (collision-free — '%' itself escapes, and fixed width keeps
    the decoding unambiguous so two distinct hostile ids can never
    collide onto one record/lease file), so an id can neither traverse
    out of the spool directory nor alias another session's files."""
    out = []
    for ch in session_id:
        if ch.isascii() and (ch.isalnum() or ch in "._-"):
            out.append(ch)
        else:
            out.extend(f"%{b:02x}" for b in ch.encode("utf-8"))
    return "".join(out) or "%00"


def _unsafe_name(encoded: str) -> str:
    """Inverse of :func:`_safe_name` (record filename -> session id)."""
    buf = bytearray()
    i = 0
    while i < len(encoded):
        if encoded[i] == "%" and i + 3 <= len(encoded):
            try:
                buf.append(int(encoded[i + 1:i + 3], 16))
                i += 3
                continue
            except ValueError:
                pass
        buf.extend(encoded[i].encode("utf-8"))
        i += 1
    return buf.decode("utf-8", errors="replace")


def session_path(dir_path: str, session_id: str) -> str:
    return os.path.join(dir_path, SESSIONS_SUBDIR,
                        _safe_name(session_id) + RECORD_SUFFIX)


def lease_path(dir_path: str, session_id: str) -> str:
    return os.path.join(dir_path, LEASES_SUBDIR,
                        _safe_name(session_id) + LEASE_SUFFIX)


def list_sessions(dir_path: str) -> List[str]:
    """Session ids with a record under the spool (encoded filenames
    decoded back), oldest record first so boot-time adoption under a
    capacity bound keeps the fleet's most senior chains deterministic."""
    sess_dir = os.path.join(dir_path, SESSIONS_SUBDIR)
    entries = []
    try:
        listing = list(os.scandir(sess_dir))
    except FileNotFoundError:
        return []
    for e in listing:
        if not e.name.endswith(RECORD_SUFFIX):
            continue
        try:
            # per-entry: a sibling consuming (unlinking) ONE record
            # mid-scan must not blank the whole listing — the shared
            # spool is contended by design
            if e.is_file():
                entries.append((e.stat().st_mtime, e.name))
        except FileNotFoundError:
            continue
    return [_unsafe_name(name[:-len(RECORD_SUFFIX)])
            for _mtime, name in sorted(entries)]


def write_record(dir_path: str, session_id: str, blob: bytes) -> str:
    """One session's framed record (from :func:`pack`), installed
    atomically."""
    final = session_path(dir_path, session_id)
    os.makedirs(os.path.dirname(final), exist_ok=True)
    return _atomic_write(final, blob)


def record_exists(dir_path: str, session_id: str) -> bool:
    """Cheap existence probe — the adopt-on-miss fast path checks this
    BEFORE paying the lease-claim file ops, since the common miss (a
    genuinely unknown session) has no record at all."""
    return os.path.exists(session_path(dir_path, session_id))


def record_age_s(dir_path: str, session_id: str) -> Optional[float]:
    """Seconds since the record's bytes were last refreshed (wall clock —
    a live owner rewrites its records every snapshot pass, so a large
    age means the writer is gone), or None when the record is absent."""
    try:
        mtime = os.stat(session_path(dir_path, session_id)).st_mtime
    except OSError:
        return None
    # ktlint: allow[KT002] cross-process spool freshness is wall-clock
    # infrastructure, like the lease-mutex staleness breaker
    return max(0.0, _time.time() - mtime)


def read_record(dir_path: str, session_id: str) -> Optional[bytes]:
    try:
        with open(session_path(dir_path, session_id), "rb") as fh:
            return fh.read()
    except FileNotFoundError:
        return None


def remove_record(dir_path: str, session_id: str) -> None:
    try:
        os.unlink(session_path(dir_path, session_id))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# the ownership-lease API (exactly one adopter per chain)
# ---------------------------------------------------------------------------

def _read_lease(path: str) -> Optional[Dict]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.loads(fh.read())
        if isinstance(doc, dict) and "owner" in doc:
            return doc
    except (OSError, ValueError):
        pass
    return None


#: how long a claim-mutex directory may exist before it is presumed
#: abandoned (a claimant died INSIDE the microseconds-long critical
#: section) and broken by the next claimant.  Generous on purpose: the
#: mkdir mtime is stamped by the STORAGE server on a shared volume, and
#: the margin must swallow realistic client/server clock skew — a
#: breaker that fires on a fresh mutex would let two claimants run the
#: read-decide-write concurrently.  A genuinely wedged mutex only delays
#: adoption (typed refusal -> one client re-establish), never serving.
_MUTEX_STALE_S = 30.0


class _LeaseMutex:
    """Per-lease critical section: an ``os.mkdir`` of ``<lease>.lock`` —
    atomic on a shared POSIX volume, exactly one winner — serializes
    every lease MUTATION (claim / renew / steal / release).  This is what
    makes the protocol's read-decide-write sequences actually atomic:
    rename-based steal schemes can yank a fresh lease a faster claimant
    just installed (observed in the contention tests), while a mutexed
    read-decide-write cannot.  The critical section is microseconds of
    file I/O; a mutex older than ``_MUTEX_STALE_S`` means its holder died
    inside it and is broken (rmdir races resolve to one winner)."""

    def __init__(self, path: str) -> None:
        self._dir = path + ".lock"

    def __enter__(self):
        for _ in range(2000):  # ~4s worst case at 2ms per spin
            try:
                os.mkdir(self._dir)
                return self
            except FileExistsError:
                try:
                    st = os.stat(self._dir)
                    # ktlint: allow[KT002] mutex staleness is wall-clock
                    # infrastructure shared ACROSS processes — an
                    # injectable test clock has no meaning for a sibling
                    # replica's mkdir timestamp
                    age = _time.time() - st.st_mtime
                except OSError:
                    continue  # released between the mkdir and the stat
                if age > _MUTEX_STALE_S:
                    try:
                        # re-verify at the last instant: if the dir was
                        # re-created since our stat (its identity moved),
                        # this rmdir would break a FRESH claimant's mutex
                        # — the decide-then-break window is narrowed to
                        # the microseconds between these two syscalls
                        st2 = os.stat(self._dir)
                        if st2.st_mtime == st.st_mtime \
                                and st2.st_ino == st.st_ino:
                            os.rmdir(self._dir)  # break the orphan
                    except OSError:
                        pass
                else:
                    _time.sleep(0.002)
        raise OSError(f"lease mutex {self._dir} wedged")

    def __exit__(self, *exc):
        try:
            os.rmdir(self._dir)
        except OSError:
            pass


def _write_lease(path: str, payload: bytes) -> None:
    """Atomic lease install (caller holds the mutex)."""
    _atomic_write(path, payload)


def claim_lease(dir_path: str, session_id: str, owner: str, now: float,
                ttl_s: float, force: bool = False) -> str:
    """Claim (or renew, or steal-after-expiry) the session's ownership
    lease, atomically (read-decide-write under the per-lease mutex).
    Returns ``"claimed"`` (was free), ``"renewed"`` (already ours), or
    ``"stolen"`` (the previous owner's lease had expired).  Raises
    :class:`LeaseHeld` when another owner's UNEXPIRED lease stands — the
    typed refusal that keeps adoption exactly-once.

    ``force=True`` steals even an unexpired foreign lease — reserved for
    session ESTABLISHMENT (``DeltaSessionTable.own``): the client just
    re-established the chain HERE, so whatever incarnation the old lease
    guarded is obsolete by the client's own authority; the old owner's
    next renewal refuses and it drops its zombie entry (``lease_lost``)
    instead of livelocking the session between two replicas."""
    path = lease_path(dir_path, session_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = json.dumps({"owner": owner,
                          "expires_at": now + max(0.0, ttl_s)}).encode()
    with _LeaseMutex(path):
        cur = _read_lease(path)
        if cur is None:
            # free (never claimed, released, or unreadable garbage — a
            # corrupt lease must not wedge its session forever)
            _write_lease(path, payload)
            return "claimed"
        if cur.get("owner") == owner:
            _write_lease(path, payload)
            return "renewed"
        if not force and float(cur.get("expires_at", 0.0)) > now:
            raise LeaseHeld(session_id, str(cur.get("owner")),
                            float(cur.get("expires_at", 0.0)))
        _write_lease(path, payload)
        return "stolen"


def release_lease(dir_path: str, session_id: str, owner: str) -> None:
    """Release the lease iff we still own it (a stolen lease belongs to
    the new owner — never delete it out from under them).  The
    owner-check + unlink runs under the same per-lease mutex as claims,
    so a release racing a steal cannot delete the thief's fresh lease."""
    path = lease_path(dir_path, session_id)
    if not os.path.exists(path):
        return
    try:
        with _LeaseMutex(path):
            cur = _read_lease(path)
            if cur is not None and cur.get("owner") == owner:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    except OSError:
        pass  # wedged mutex: leave the lease to expire on its own


def lease_state(dir_path: str, session_id: str) -> Optional[Dict]:
    """The lease document ({owner, expires_at}) or None — observability
    only (statusz, tests); never a correctness input."""
    return _read_lease(lease_path(dir_path, session_id))
