"""Session-table snapshot spool — versioned, checksummed serialization of
live delta chains (ISSUE 12 tentpole, docs/RESILIENCE.md).

PR 10 made steady-state serving session-stateful; a replica restart then
destroys every ``_warmstart_meta`` chain and costs one full re-establishing
solve PER CLIENT.  This module is the durability half of the fix: the
``DeltaSessionTable`` serializes its chains to a spool file under
``KT_SESSION_DIR`` (the jit-cache PVC precedent — mount the same pod-local
or shared volume) on graceful shutdown and periodically at epoch
boundaries, and a restarted replica rehydrates the table so every
surviving session's next delta is served WARM.

File layout (one file, ``sessions.snap``)::

    MAGIC(8) | version(>I) | payload_len(>Q) | sha256(payload)(32) | payload

``payload`` is a pickle of ``{"schema": ..., "catalog_epoch": ...,
"entries": [...]}`` — pickle is the right tool here because the spool is
written and read by the SAME binary (the chain carries numpy residual
matrices and the full SimNode graph, and pickle preserves the node-object
identity sharing between ``result.nodes`` and ``meta.nodes`` that the
warm-start tiers rely on).  What makes it safe is the envelope:

- **Atomic**: write-temp + fsync + rename — a SIGKILL mid-write leaves
  the previous spool intact, never a torn file.
- **Checksummed**: a flipped byte anywhere in the payload fails the
  sha256 and the restore refuses (``corrupt``).
- **Length-framed**: a truncated payload is detected BEFORE the checksum
  (``truncated``) so operators can tell disk-full from bit-rot.
- **Versioned twice**: the format version (:data:`SNAPSHOT_VERSION`) and
  a schema fingerprint derived from the live dataclass fields of
  ``SolveResult`` + ``warmstart._Meta`` — a refactor that changes the
  chain shape auto-invalidates old spools (``version``) instead of
  unpickling into a subtly different world.
- **Catalog-gated**: a spool whose catalog epoch DIFFERS from the
  configured ``KT_CATALOG_EPOCH`` is refused whole (``catalog_epoch``)
  — older or newer, a chain packed against another epoch's prices must
  not serve warm.

Every refusal is a COLD START plus a counted reason
(``karpenter_solver_session_snapshot_restore_total{outcome}``), never a
crash and never a diverged chain.
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import struct
from typing import Optional, Tuple

MAGIC = b"KTSESS1\n"
#: bump when the envelope layout changes (the schema fingerprint below
#: covers chain-SHAPE drift automatically)
SNAPSHOT_VERSION = 1
_HEADER = struct.Struct(">IQ")  # version, payload length
#: spool file name under KT_SESSION_DIR
SPOOL_NAME = "sessions.snap"


class SnapshotRefused(Exception):
    """A spool file that must not be restored.  ``reason`` is one of the
    ``SNAPSHOT_RESTORE_OUTCOMES`` labels (corrupt / truncated / version /
    catalog_epoch) — the caller counts it and cold-starts."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(f"session snapshot refused ({reason}): {detail}")
        self.reason = reason


def chain_schema() -> str:
    """Fingerprint of the live chain shape: the dataclass fields of the
    result and warm-start bookkeeping the spool pickles.  Computed from
    the RUNNING code, so a refactor that adds/renames a field refuses old
    spools without anyone remembering to bump a constant."""
    from ..solver.types import SimNode, SolveResult
    from ..solver.warmstart import _Meta

    names = "|".join(
        ",".join(sorted(cls.__dataclass_fields__))
        for cls in (SolveResult, _Meta, SimNode)
        if hasattr(cls, "__dataclass_fields__"))
    return hashlib.sha256(names.encode()).hexdigest()[:16]


def spool_path(dir_path: str) -> str:
    return os.path.join(dir_path, SPOOL_NAME)


def pack_entry(entry: dict) -> bytes:
    """One session entry -> its own pickle blob.  Entries are pickled
    INDIVIDUALLY so the table can serialize them without any scheduler
    lock: a chain that mutates under the pickler corrupts (or tears)
    only its own blob, which the caller detects via the epoch/in_step
    re-check and discards — the spool never carries a torn chain."""
    return pickle.dumps(entry, protocol=pickle.HIGHEST_PROTOCOL)


def unpack_entry(blob: bytes) -> dict:
    return pickle.loads(blob)


def pack(entries: list, catalog_epoch: int = 0) -> bytes:
    """Serialize per-entry blobs (from :func:`pack_entry`) into one
    framed, checksummed spool blob."""
    payload = pickle.dumps(
        {"schema": chain_schema(), "catalog_epoch": int(catalog_epoch),
         "entries": entries},
        protocol=pickle.HIGHEST_PROTOCOL)
    buf = io.BytesIO()
    buf.write(MAGIC)
    buf.write(_HEADER.pack(SNAPSHOT_VERSION, len(payload)))
    buf.write(hashlib.sha256(payload).digest())
    buf.write(payload)
    return buf.getvalue()


def unpack(blob: bytes,
           expected_catalog_epoch: Optional[int] = None) -> Tuple[list, int]:
    """Validate + deserialize a spool blob -> (entries, catalog_epoch).

    Raises :class:`SnapshotRefused` with the counted reason on every
    adversarial shape: wrong magic / failed checksum / undecodable
    (``corrupt``), short payload (``truncated``), format-version or
    chain-schema drift (``version``), stale catalog (``catalog_epoch``).
    """
    head_len = len(MAGIC) + _HEADER.size + 32
    if len(blob) < head_len:
        raise SnapshotRefused("truncated",
                              f"{len(blob)}B < {head_len}B header")
    if blob[:len(MAGIC)] != MAGIC:
        raise SnapshotRefused("corrupt", "bad magic")
    version, length = _HEADER.unpack_from(blob, len(MAGIC))
    if version != SNAPSHOT_VERSION:
        raise SnapshotRefused(
            "version", f"format v{version}, want v{SNAPSHOT_VERSION}")
    digest = blob[len(MAGIC) + _HEADER.size:head_len]
    payload = blob[head_len:]
    if len(payload) < length:
        raise SnapshotRefused(
            "truncated", f"payload {len(payload)}B < declared {length}B")
    payload = payload[:length]
    if hashlib.sha256(payload).digest() != digest:
        raise SnapshotRefused("corrupt", "payload checksum mismatch")
    try:
        doc = pickle.loads(payload)
    # ktlint: allow[KT005] any undecodable payload is the same outcome: a
    # refused snapshot, counted 'corrupt', cold start
    except Exception as err:  # noqa: BLE001
        raise SnapshotRefused("corrupt", f"unpickle failed: {err}") from err
    if not isinstance(doc, dict) or "entries" not in doc:
        raise SnapshotRefused("corrupt", "payload is not a snapshot doc")
    if doc.get("schema") != chain_schema():
        raise SnapshotRefused(
            "version", "chain schema drift (warm-start bookkeeping shape "
            "changed since this spool was written)")
    epoch = int(doc.get("catalog_epoch", 0))
    if (expected_catalog_epoch is not None
            and epoch != int(expected_catalog_epoch)):
        raise SnapshotRefused(
            "catalog_epoch",
            f"spool catalog epoch {epoch} != configured "
            f"{expected_catalog_epoch}")
    return list(doc["entries"]), epoch


def write_atomic(dir_path: str, blob: bytes) -> str:
    """write-temp + fsync + rename: the spool is either the complete new
    snapshot or the complete previous one — never a torn file.  The temp
    lives in the SAME directory so the rename is atomic on one mount,
    and carries a per-writer suffix so a background periodic write and a
    shutdown write can never interleave inside one temp file."""
    import threading

    os.makedirs(dir_path, exist_ok=True)
    final = spool_path(dir_path)
    tmp = f"{final}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as fh:
        fh.write(blob)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, final)
    return final


def read(dir_path: str) -> Optional[bytes]:
    """The spool's bytes, or None when no snapshot exists (plain cold
    start, counted 'missing')."""
    try:
        with open(spool_path(dir_path), "rb") as fh:
            return fh.read()
    except FileNotFoundError:
        return None
