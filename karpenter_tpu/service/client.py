"""Solver service client — a BatchScheduler-compatible remote scheduler.

``RemoteScheduler`` is a drop-in for ``solver.scheduler.BatchScheduler`` so
controllers can point at a sidecar instead of solving in-process (the
reconciler <-> solver split of the north star; the reference consumes its
remote boundary the same way — ``cloudprovider.New(awsCtx)`` at
cmd/controller/main.go:44 is handed to every control loop).  The facade
contract (same methods, same signatures) is asserted by
tests/test_service.py::TestFacadeContract (test_signatures_match /
test_shared_attributes) so any drift between the two schedulers fails CI,
not production.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Set

import grpc

from collections import OrderedDict

from .. import faults as faults_mod
from .. import gang as gangmod
from ..admission import SolveDeadlineError, SolveShedError, parse_class
from ..metrics import (
    FLEET_ENDPOINTS,
    FLEET_FAILOVER_REASONS,
    FLEET_FAILOVERS,
    Registry,
    registry as default_registry,
)
from ..utils.clock import Clock
from ..models.instancetype import InstanceType
from ..models.pod import PodSpec
from ..obs.trace import NULL_TRACE
from ..models.provisioner import Provisioner
from ..solver.scheduler import BatchScheduler
from ..solver.types import SimNode, SolveResult
from . import codec
from . import solver_pb2 as pb
from .delta import DeltaSessionUnknown, delta_enabled
from .server import SERVICE

logger = logging.getLogger(__name__)

from ..metrics import REMOTE_DEGRADED, REMOTE_FALLBACK_SOLVES  # noqa: E402
# (names + help text live in metrics.INVENTORY so docs/METRICS.md covers them)


class SolveRetriesExhausted(grpc.RpcError):
    """Transport UNAVAILABLE outlived the bounded retry budget — the
    replica is not merely restarting, it is gone.  Typed (the PR-5
    surface: callers back off / re-plan, never silent-retry), and still a
    ``grpc.RpcError`` with an UNAVAILABLE ``code()`` so availability-first
    facades (``RemoteScheduler``) keep their degrade-to-local-fallback
    behavior unchanged."""

    def __init__(self, msg: str, attempts: int) -> None:
        super().__init__(msg)
        self.attempts = attempts

    def code(self):
        return grpc.StatusCode.UNAVAILABLE

    def details(self) -> str:
        return str(self.args[0]) if self.args else ""


class SolveStepFailed(Exception):
    """A delta step failed server-side mid-apply (gRPC INTERNAL on a
    session call).  The server evicted the session (the half-mutated
    chain must never serve another epoch — service/server.py
    ``_serve_delta``); the client keeps its ledger + pending perturbation,
    and the NEXT ``solve_delta`` call re-establishes transparently via the
    session_unknown path — one full solve, never a diverged chain, never
    an untyped transport error through the facade."""


class SolverDraining(Exception):
    """The replica refused a session establishment because it is
    gracefully draining (``session_state="draining"``,
    docs/RESILIENCE.md).  A fleet-aware client never surfaces this — the
    :class:`FleetClient` re-routes the establishment to a sibling — but a
    single-endpoint ``DeltaSession`` pointed at a draining pod has
    nowhere to go: typed, the session ledger + pending perturbation
    survive, and the next call retries (against the replacement pod once
    it lands)."""


#: retry budget for transport UNAVAILABLE (KT_RPC_RETRIES): how many
#: RE-attempts one solve_raw pays before the typed give-up.  1 = ride
#: through a single replica restart; 0 disables ride-through.
DEFAULT_RPC_RETRIES = 1
#: base backoff before a retry, ms (KT_RPC_BACKOFF_MS); the actual sleep
#: is base * (1 + jitter) with jitter from the faults facade so a
#: restart storm's retries decorrelate
DEFAULT_RPC_BACKOFF_MS = 200.0


class SolverClient:
    def __init__(self, target: str, timeout: float = 60.0,
                 clock: Optional[Clock] = None,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 registry: Optional[Registry] = None) -> None:
        self.target = target
        self.timeout = timeout
        # injectable clock: tests drive the backoff without real sleeps
        self.clock = clock or Clock()
        if retries is None:
            retries = int(os.environ.get("KT_RPC_RETRIES",
                                         str(DEFAULT_RPC_RETRIES)))
        if backoff_s is None:
            backoff_s = float(os.environ.get(
                "KT_RPC_BACKOFF_MS", str(DEFAULT_RPC_BACKOFF_MS))) / 1000.0
        self.retries = max(0, retries)
        self.backoff_s = max(0.0, backoff_s)
        # transport fault site (docs/RESILIENCE.md): injected UNAVAILABLE/
        # reset errors exercise the retry path through real handling.
        # Recovery outcomes land in the registry the EMBEDDING hands us
        # (RemoteScheduler/DeltaSession pass theirs through), so the
        # site x outcome partition stays whole on custom registries.
        self._faults = faults_mod.plane()
        self._registry = registry or default_registry
        faults_mod.zero_init_recovery(self._registry)
        self._connect()

    def _connect(self) -> None:
        self.channel = grpc.insecure_channel(
            self.target,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)],
        )
        self._solve = self.channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._warm = self.channel.unary_unary(
            f"/{SERVICE}/Warm",
            request_serializer=pb.WarmRequest.SerializeToString,
            response_deserializer=pb.WarmResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def reset(self) -> None:
        """Drop and rebuild the channel.  A grpc channel whose connection
        attempts started while the server was down can wedge in a
        reconnect-backoff state that outlives the outage (observed on this
        host as endless 'tcp handshaker shutdown' UNAVAILABLE errors against
        a LISTENING server); a fresh channel connects on its first try, so
        the degraded-path health probe resets after every failed attempt."""
        self.close()
        self._connect()

    def health(self, timeout: Optional[float] = None) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=timeout or self.timeout)

    def solve_raw(self, request: pb.SolveRequest,
                  timeout: Optional[float] = None) -> pb.SolveResponse:
        """One Solve RPC with restart ride-through (ISSUE 12 satellite):
        transport UNAVAILABLE — the exact shape of a replica restart —
        retries ONCE per budget unit (KT_RPC_RETRIES, default 1) after a
        jittered backoff on a fresh channel, then surfaces the typed
        :class:`SolveRetriesExhausted`.  Typed sheds are NEVER retried:
        RESOURCE_EXHAUSTED / DEADLINE_EXCEEDED mean the sidecar is
        protecting itself — overload is not an outage (the PR-5
        invariant), and a retry storm into an overloaded server is how
        outages are made."""
        # every path out of this loop returns or raises: the final
        # iteration's except always raises (attempt + 1 >= attempts
        # matches every error on the last pass)
        attempts = self.retries + 1
        for attempt in range(attempts):
            try:
                if self._faults:
                    self._faults.fire("transport")
                return self._solve(request, timeout=timeout or self.timeout)
            except grpc.RpcError as err:
                code = (err.code()
                        if callable(getattr(err, "code", None)) else None)
                if code != grpc.StatusCode.UNAVAILABLE \
                        or attempt + 1 >= attempts:
                    if code == grpc.StatusCode.UNAVAILABLE:
                        faults_mod.count_recovery(
                            self._registry, "transport", "failed")
                        raise SolveRetriesExhausted(
                            f"solver {self.target} unavailable after "
                            f"{attempts} attempt(s): "
                            f"{getattr(err, 'details', lambda: '')() or err}",
                            attempts) from err
                    raise
                # replica restarting: fresh channel (a channel that began
                # connecting mid-outage can wedge in backoff — see reset),
                # jittered pause, one more try.  Counted whether the
                # UNAVAILABLE was injected or organic.
                faults_mod.count_recovery(
                    self._registry, "transport", "retried")
                logger.debug(
                    "solver %s UNAVAILABLE (attempt %d/%d); retrying "
                    "after backoff", self.target, attempt + 1, attempts)
                self.reset()
                if self.backoff_s > 0:
                    self.clock.sleep(
                        self.backoff_s * (1.0 + faults_mod.jitter()))

    def warm_raw(self, request: pb.WarmRequest) -> pb.WarmResponse:
        return self._warm(request, timeout=self.timeout)

    def close(self) -> None:
        self.channel.close()


class FleetClient:
    """Endpoint-set transport over N solver replicas — session-affinity
    routing with warm failover (ISSUE 13, docs/RESILIENCE.md).

    Duck-types the slice of :class:`SolverClient` the session facades use
    (``solve_raw`` / ``timeout`` / ``reset`` / ``close``), so
    ``DeltaSession(..., client=FleetClient(...))`` is the whole wiring.
    Routing reads the REQUEST: ``session_id`` rendezvous-hashes over the
    live endpoints (highest-random-weight, so one replica death re-homes
    ONLY that replica's sessions and every client agrees on the target
    without coordination); sessionless solves ride the same hash of "".

    Failure handling, per RPC:

    - transport ``UNAVAILABLE`` surviving the per-endpoint retry budget
      -> the endpoint is marked DEAD (counted failover ``death``), the
      request re-routes to the next endpoint in rendezvous order and is
      re-sent.  For a delta step that is safe: the dead replica either
      never applied it, or applied it without replying — in which case
      the adopting replica's spool record is one epoch ahead, the epoch
      check answers ``session_unknown``, and the client pays the PR-10
      exactly-one re-establish instead of ever diverging.  With the
      shared spool current, the adopting replica serves the step WARM.
    - ``session_state="draining"`` on an ESTABLISHMENT -> the endpoint is
      marked DRAINING (counted failover ``drain``), the establishment
      re-sends to a sibling.  On a DELTA reply the served result is
      returned as-is and the endpoint marked, so the session's next RPC
      proactively re-homes before the pod dies.
    - typed sheds / deadline / INTERNAL pass through untouched — overload
      and step failures are per-replica postures, not routing events.

    Dead endpoints are re-probed (Health, ``PROBE_TIMEOUT``) at most once
    per ``reconnect_interval`` when routing wants them; a probe that
    answers revives the endpoint (a replaced pod on the same address).
    Draining endpoints revive the same way once their replacement serves.

    CLASSIC (session-less) solves route by BUCKET AFFINITY (ISSUE 14
    satellite, ROADMAP item 1 remnant): the request's compile-signature
    proxy — pod-count rung, catalog rung, provisioner count — rendezvous-
    hashes over the fleet, so repeat shapes land on the replica whose jit
    cache and tensorize cache already warmed them, instead of every
    sessionless solve hashing ``""`` onto one replica.  When the affinity
    home is dead/draining the request falls back to the LEAST-LOADED
    healthy endpoint (fewest in-flight RPCs through this client) rather
    than piling onto the next rendezvous winner.
    ``KT_FLEET_BUCKET_AFFINITY=0`` restores the legacy hash-of-"" route.

    Knobs: ``KT_FLEET_ENDPOINTS`` (comma-separated targets) when no
    explicit endpoint list is given.  Endpoint states are exported as
    ``karpenter_fleet_endpoints{state}`` and re-homes as
    ``karpenter_fleet_failovers_total{reason}``.
    """

    RECONNECT_INTERVAL = 5.0
    PROBE_TIMEOUT = 2.0

    def __init__(self, endpoints: Optional[Sequence[str]] = None,
                 timeout: float = 60.0,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 registry: Optional[Registry] = None,
                 clock: Optional[Clock] = None,
                 reconnect_interval: float = RECONNECT_INTERVAL) -> None:
        if endpoints is None:
            env = os.environ.get("KT_FLEET_ENDPOINTS", "")
            endpoints = [e.strip() for e in env.split(",") if e.strip()]
        if not endpoints:
            raise ValueError(
                "FleetClient needs at least one endpoint (pass endpoints= "
                "or set KT_FLEET_ENDPOINTS)")
        self.endpoints = list(endpoints)
        self.timeout = timeout
        self.clock = clock or Clock()
        self._registry = registry or default_registry
        self.reconnect_interval = reconnect_interval
        self._clients: Dict[str, SolverClient] = {
            ep: SolverClient(ep, timeout=timeout, clock=self.clock,
                             retries=retries, backoff_s=backoff_s,
                             registry=self._registry)
            for ep in self.endpoints
        }
        #: endpoint -> "healthy" | "dead" | "draining"
        self._state: Dict[str, str] = {ep: "healthy"
                                       for ep in self.endpoints}
        self._last_probe: Dict[str, float] = {ep: 0.0
                                              for ep in self.endpoints}
        #: classic-solve bucket affinity (KT_FLEET_BUCKET_AFFINITY)
        self._bucket_affinity = (
            os.environ.get("KT_FLEET_BUCKET_AFFINITY", "1") != "0")
        #: endpoint -> RPCs in flight through THIS client (the
        #: least-loaded fallback's signal); guarded-by: _load_lock
        self._inflight: Dict[str, int] = {ep: 0 for ep in self.endpoints}
        self._load_lock = threading.Lock()
        faults_mod.zero_init_recovery(self._registry)
        fo = self._registry.counter(FLEET_FAILOVERS)
        for reason in FLEET_FAILOVER_REASONS:
            if not fo.has({"reason": reason}):
                fo.inc({"reason": reason}, value=0.0)
        self._export_states()

    # ---- endpoint state --------------------------------------------------
    def _export_states(self) -> None:
        gauge = self._registry.gauge(FLEET_ENDPOINTS)
        states = list(self._state.values())
        gauge.set(float(len(states)), {"state": "known"})
        gauge.set(float(states.count("healthy")), {"state": "healthy"})
        gauge.set(float(states.count("draining")), {"state": "draining"})

    def _mark(self, endpoint: str, state: str) -> bool:
        """Transition an endpoint's state; True iff it actually changed
        (failover counting keys on the TRANSITION — a whole-fleet drain
        serving deltas through the last-resort path must not re-count
        every reply)."""
        if self._state.get(endpoint) == state:
            return False
        logger.warning("fleet endpoint %s -> %s", endpoint, state)
        self._state[endpoint] = state
        if state in ("dead", "draining"):
            # arm the revival probe a FULL interval out: an immediate
            # probe would flip a still-answering drainer straight back to
            # healthy and ping-pong the very sessions the hint re-homed
            # ktlint: allow[KT002] transport-health stopwatch, see
            # _revive_due
            self._last_probe[endpoint] = time.monotonic()
        self._export_states()
        return True

    def states(self) -> Dict[str, str]:
        """Endpoint -> state snapshot (observability/tests)."""
        return dict(self._state)

    def _revive_due(self, endpoint: str) -> bool:
        # ktlint: allow[KT002] transport-health stopwatch, the
        # RemoteScheduler._remote_ok precedent: probe pacing must follow
        # real wall progress, not an injected test clock
        now = time.monotonic()
        if now - self._last_probe.get(endpoint, 0.0) \
                < self.reconnect_interval:
            return False
        self._last_probe[endpoint] = now
        return True

    def _probe(self, endpoint: str) -> bool:
        client = self._clients[endpoint]
        try:
            ok = bool(client.health(timeout=self.PROBE_TIMEOUT).ok)
        except grpc.RpcError:
            # arm the NEXT probe with a fresh channel (the wedged-channel
            # class SolverClient.reset documents); a DRAINING pod that
            # stopped answering has died — dead-state probing now owns
            # its revival once the replacement serves
            client.reset()
            self._mark(endpoint, "dead")
            return False
        if ok:
            self._mark(endpoint, "healthy")
        return ok

    # ---- routing ---------------------------------------------------------
    @staticmethod
    def _weight(session_id: str, endpoint: str) -> int:
        import hashlib

        return int.from_bytes(
            hashlib.sha256(f"{session_id}|{endpoint}".encode()).digest()[:8],
            "big")

    def rendezvous(self, session_id: str) -> List[str]:
        """Every endpoint, best first (highest-random-weight hash of
        (session, endpoint)): the session's home is the first LIVE entry,
        and failover walks the same order on every client."""
        return sorted(self.endpoints,
                      key=lambda ep: self._weight(session_id, ep),
                      reverse=True)

    def endpoint_for(self, session_id: str,
                     exclude: Optional[set] = None) -> Optional[str]:
        """The session's current home: the first HEALTHY endpoint in
        rendezvous order.  Draining endpoints are routed around — the
        hint already handed the chain to the spool, so the next RPC must
        land on the sibling that will adopt it, not ping-pong back into
        the drainer — and serve only as a last resort when the whole
        fleet drains at once (they still answer deltas correctly; an
        establishment there is refused and retried).  Dead endpoints get
        a paced revival probe on the way.  None when everything is
        excluded or dead."""
        exclude = exclude or set()
        fallback = None
        for ep in self.rendezvous(session_id):
            if ep in exclude:
                continue
            state = self._state[ep]
            if state in ("dead", "draining") and self._revive_due(ep):
                # paced revival probe.  Dead: the replacement pod on the
                # same address answers -> healthy.  Draining: the pod
                # either still drains (probe ok -> healthy; one RPC will
                # re-mark it the moment it answers another hint — a
                # bounded mislabel, never a wrong result) or has died
                # (probe fails -> dead, and the dead path picks up its
                # replacement).  Without this, a drained-and-replaced
                # endpoint would stay excluded forever.
                self._probe(ep)
                state = self._state[ep]
            if state == "healthy":
                return ep
            if state == "draining" and fallback is None:
                fallback = ep  # an all-draining fleet still serves deltas
        return fallback

    @staticmethod
    def bucket_affinity_key(request) -> str:
        """Compile-signature PROXY of a classic solve request, computed
        client-side: pod-count rung (power of two — the shape-bucketing
        direction the server's solve_dims rungs quantize), instance-type
        rung, provisioner count, and whether new nodes are allowed.  Two
        requests with the same proxy very likely share server-side
        compile buckets and tensorize-cache shapes, so routing repeat
        shapes to one replica rides its warm programs; a proxy collision
        merely shares a replica, never a wrong result."""
        n_pods = len(getattr(request, "pods", ()) or ())
        n_types = len(getattr(request, "instance_types", ()) or ())
        n_provs = len(getattr(request, "provisioners", ()) or ())
        g = 1 << (n_pods - 1).bit_length() if n_pods > 0 else 0
        c = 1 << (n_types - 1).bit_length() if n_types > 0 else 0
        allow = getattr(request, "allow_new_nodes", True)
        return f"bucket:g{g}:c{c}:p{n_provs}:a{int(bool(allow))}"

    def _least_loaded(self, exclude: set) -> Optional[str]:
        """The healthy endpoint with the fewest in-flight RPCs through
        this client (ties broken by endpoint order) — the classic-solve
        fallback when the affinity home is down: spreading by load beats
        piling every orphaned bucket onto the next rendezvous winner."""
        with self._load_lock:
            loads = dict(self._inflight)
        best = None
        for ep in self.endpoints:
            if ep in exclude or self._state.get(ep) != "healthy":
                continue
            if best is None or loads.get(ep, 0) < loads.get(best, 0):
                best = ep
        return best

    def _classic_endpoint(self, key: str,
                          exclude: set) -> Optional[str]:
        """Routing for session-LESS solves: the bucket-affinity home
        (rendezvous winner for the request's compile-signature proxy)
        when it is healthy, else the least-loaded healthy endpoint
        (affinity miss), else the standard walk (drain fallbacks +
        revival probes)."""
        order = self.rendezvous(key)
        home = next((ep for ep in order if ep not in exclude), None)
        if home is not None:
            state = self._state[home]
            if state in ("dead", "draining") and self._revive_due(home):
                self._probe(home)
                state = self._state[home]
            if state == "healthy":
                return home
        fallback = self._least_loaded(exclude)
        if fallback is not None:
            return fallback
        return self.endpoint_for(key, exclude=exclude)

    # ---- SolverClient surface -------------------------------------------
    def solve_raw(self, request: pb.SolveRequest,
                  timeout: Optional[float] = None) -> pb.SolveResponse:
        sid = getattr(request, "session_id", "")
        establish = bool(sid) and not bool(getattr(request, "delta", False))
        classic_key = None
        if not sid and self._bucket_affinity:
            classic_key = self.bucket_affinity_key(request)
        tried: set = set()
        while True:
            if classic_key is not None:
                ep = self._classic_endpoint(classic_key, tried)
            else:
                ep = self.endpoint_for(sid, exclude=tried)
            if ep is None:
                raise SolveRetriesExhausted(
                    f"no live solver endpoint (of {len(self.endpoints)}) "
                    f"for session {sid or '<none>'}", len(tried))
            try:
                with self._load_lock:
                    self._inflight[ep] = self._inflight.get(ep, 0) + 1
                try:
                    resp = self._clients[ep].solve_raw(request,
                                                       timeout=timeout)
                finally:
                    with self._load_lock:
                        self._inflight[ep] = max(
                            0, self._inflight.get(ep, 0) - 1)
            except grpc.RpcError as err:
                code = (err.code()
                        if callable(getattr(err, "code", None)) else None)
                if code == grpc.StatusCode.UNAVAILABLE:
                    # the replica is gone (the per-endpoint retry budget
                    # already rode through a mere restart): fail the
                    # session over — the next endpoint adopts its chain
                    # from the shared spool and serves WARM.  Counted on
                    # the state TRANSITION, not per failing RPC.
                    if self._mark(ep, "dead"):
                        self._registry.counter(FLEET_FAILOVERS).inc(
                            {"reason": "death"})
                    faults_mod.count_recovery(
                        self._registry, "transport", "fallback")
                    tried.add(ep)
                    continue
                raise  # sheds / deadline / INTERNAL: per-replica posture
            if getattr(resp, "session_state", "") == "draining":
                if self._mark(ep, "draining"):
                    self._registry.counter(FLEET_FAILOVERS).inc(
                        {"reason": "drain"})
                if establish:
                    # the handshake's refusal half: nothing was served —
                    # re-home the establishment to a sibling.  When the
                    # WHOLE fleet is draining at once (rolling restart
                    # tail) there is no sibling: return the refusal so
                    # the session facade raises the typed, retriable
                    # SolverDraining — the replicas are alive and
                    # protecting their handoffs, which is not an outage
                    if self.endpoint_for(sid,
                                         exclude=tried | {ep}) is None:
                        return resp
                    tried.add(ep)
                    continue
                # a served delta carrying the hint: return it; the next
                # RPC for this session routes to a live sibling, which
                # adopts the handed-off chain warm
            return resp

    def reset(self) -> None:
        for client in self._clients.values():
            client.reset()

    def health(self, timeout: Optional[float] = None):
        """Health of the session-less routing target (facade parity)."""
        ep = self.endpoint_for("") or self.endpoints[0]
        return self._clients[ep].health(timeout=timeout)

    def close(self) -> None:
        for client in self._clients.values():
            client.close()


class RemoteScheduler:
    """BatchScheduler-compatible facade over the sidecar.

    Availability semantics: when the sidecar is unreachable, ``solve`` falls
    back to a LOCAL solve (oracle backend by default) so the control plane
    keeps reconciling — scale-up must not stall on a solver rollout.  After a
    failure the remote path is considered degraded; it is retried only
    through a cheap Health probe at most once per ``reconnect_interval``
    seconds (health-gated reconnect), so a down sidecar costs one probe per
    interval, not one deadline-wait per solve.
    """

    #: seconds between Health probes while degraded
    RECONNECT_INTERVAL = 5.0
    #: deadline for the Health probe itself — must be snappy: it sits on the
    #: reconcile path while degraded
    PROBE_TIMEOUT = 2.0

    def __init__(
        self,
        target: str,
        backend: str = "",
        timeout: float = 60.0,
        *,
        fallback: Optional[BatchScheduler] = None,
        reconnect_interval: float = RECONNECT_INTERVAL,
        registry: Optional[Registry] = None,
        priority: str = "",
        deadline_s: Optional[float] = None,
        shed_fallback: bool = False,
    ) -> None:
        self.client = SolverClient(target, timeout=timeout,
                                   registry=registry)
        self.target = target
        self.backend = backend
        # admission identity (docs/ADMISSION.md): every Solve this facade
        # sends carries the caller's priority class and deadline budget.
        # Constructor-level (not per-call) so the BatchScheduler facade
        # contract (tests/test_service.py::TestFacadeContract) stays
        # byte-for-byte — a control loop IS one priority class.
        self.priority = parse_class(priority) if priority else ""
        self.deadline_s = deadline_s
        # shed posture: library callers get the typed SolveShedError /
        # SolveDeadlineError (back off, re-plan); an availability-first
        # control loop (the operator's reconciler — it has no backoff
        # story, a raised shed would kill the whole loop) sets
        # shed_fallback=True: the shed is logged + counted and THIS solve
        # is served locally, WITHOUT latching the degraded path — the
        # sidecar is healthy and protecting itself, so the next solve
        # goes remote again.
        self.shed_fallback = shed_fallback
        self.mesh = None  # the device mesh lives sidecar-side
        self.registry = registry or default_registry
        self.fallback = fallback or BatchScheduler(
            backend="oracle", registry=self.registry
        )
        self.reconnect_interval = reconnect_interval
        self._degraded_since: Optional[float] = None
        self._last_probe = 0.0
        # zero-init so the series exists from the first scrape (inc(0)
        # creates the sample; construction alone does not)
        self.registry.counter(REMOTE_FALLBACK_SOLVES).inc(value=0.0)
        self.registry.gauge(REMOTE_DEGRADED).set(0)
        faults_mod.zero_init_recovery(self.registry)

    #: RPC status codes that mean "the sidecar is not reachable right now".
    #: Anything else (UNIMPLEMENTED from an older sidecar's missing Warm
    #: handler, INTERNAL on one bad request, ...) must NOT poison the Solve
    #: path: that call falls back / returns 0, the next one goes remote.
    TRANSPORT_CODES = (grpc.StatusCode.UNAVAILABLE,
                       grpc.StatusCode.DEADLINE_EXCEEDED)

    # ---- degradation state ------------------------------------------------
    def degraded(self) -> bool:
        return self._degraded_since is not None

    def _transport_failure(self, err: grpc.RpcError) -> bool:
        code = err.code() if callable(getattr(err, "code", None)) else None
        return code in self.TRANSPORT_CODES

    def _mark_degraded(self, err: Exception) -> None:
        if self._degraded_since is None:
            logger.warning("solver sidecar %s unreachable (%s); "
                           "falling back to local %s solves", self.target,
                           getattr(err, "code", lambda: err)(),
                           self.fallback.backend)
        # ktlint: allow[KT002] transport-health stopwatch: reconnect pacing
        # must follow real wall progress, not the operator's injected clock
        # (a FakeClock-driven test advancing hours would hot-loop probes)
        self._degraded_since = time.monotonic()
        self._last_probe = self._degraded_since
        self.registry.gauge(REMOTE_DEGRADED).set(1)

    def _remote_ok(self) -> bool:
        """True when the remote path should be attempted: healthy, or
        degraded but due for a (successful) health probe."""
        if self._degraded_since is None:
            return True
        now = time.monotonic()  # ktlint: allow[KT002] see _mark_degraded
        if now - self._last_probe < self.reconnect_interval:
            return False
        self._last_probe = now
        try:
            ok = bool(self.client.health(timeout=self.PROBE_TIMEOUT).ok)
        except grpc.RpcError:
            # arm the NEXT probe with a fresh channel: a channel that began
            # connecting while the sidecar was down can stay wedged after it
            # comes back (see SolverClient.reset) — without this the remote
            # path would never recover on affected stacks
            self.client.reset()
            return False
        if ok:
            logger.info("solver sidecar %s back after %.1fs; resuming remote "
                        "solves", self.target,
                        now - (self._degraded_since or now))
            self._degraded_since = None
            self.registry.gauge(REMOTE_DEGRADED).set(0)
        return ok

    # ---- BatchScheduler surface -------------------------------------------
    def solve(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
        trace=None,
        relax: Optional[bool] = None,
    ) -> SolveResult:
        # ``relax`` mirrors BatchScheduler.solve for facade parity; the
        # rung is a server-side refinement governed by the sidecar's own
        # KT_RELAX policy (the wire carries no per-request override), so
        # only the local-fallback solve below honors the caller's value
        #
        # gang audit client-side (ISSUE 20): a malformed gang would only
        # bounce off the server's INVALID_ARGUMENT — raise the same typed
        # error here, before paying the round trip (and identically on the
        # degraded local path, which skips the server's door check)
        gangmod.validate_batch(pods)
        trace = trace or NULL_TRACE
        if self._remote_ok():
            # fleet-wide tracing (ISSUE 15): the "remote" span's wire
            # context crosses with the request, so the sidecar's trace
            # opens as a CHILD of this span (same trace id, remote parent
            # linked) instead of an unrelated tree — /fleetz renders the
            # operator hop and the sidecar hop as one request
            with trace.span("remote", target=self.target) as span:
                wire_tid, wire_parent = trace.wire_context()
                req = codec.encode_request(
                    pods, provisioners, instance_types,
                    existing_nodes=existing_nodes, daemonsets=daemonsets,
                    unavailable=unavailable, allow_new_nodes=allow_new_nodes,
                    max_new_nodes=max_new_nodes, backend=self.backend,
                    priority=self.priority,
                    deadline_ms=(self.deadline_s * 1000.0
                                 if self.deadline_s else None),
                    trace_id=wire_tid, parent_span=wire_parent,
                )
                # the wire deadline budget also bounds the RPC itself: a
                # caller with 250ms left must not block 60s on the channel
                rpc_timeout = (min(self.client.timeout, self.deadline_s)
                               if self.deadline_s else None)
                try:
                    resp = self.client.solve_raw(req, timeout=rpc_timeout)
                except grpc.RpcError as err:
                    code = (err.code()
                            if callable(getattr(err, "code", None)) else None)
                    span.annotate(transport_error=str(code or err))
                    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                        # the sidecar SHED this request (admission queue
                        # full / rate limit / brownout).  Overload is not
                        # an outage — NEVER latch the degraded path (the
                        # sidecar is healthy, it is protecting itself).
                        # Library callers get the typed error so they back
                        # off; an availability-first reconcile loop
                        # (shed_fallback=True) logs it and serves THIS
                        # solve locally, next one goes remote again.
                        detail = getattr(err, "details", lambda: "")() or ""
                        if not self.shed_fallback:
                            # ktlint: allow[KT009] client-side re-map of a
                            # shed the serving side already counted in
                            # karpenter_admission_shed_total
                            raise SolveShedError(
                                f"solver sidecar shed this solve: {detail}",
                                pclass=self.priority, reason="remote_shed",
                            ) from err
                        logger.warning(
                            "solver sidecar shed this solve (%s); serving "
                            "it from the local fallback", detail)
                    elif (code == grpc.StatusCode.DEADLINE_EXCEEDED
                            and self.deadline_s is not None):
                        # the caller CONFIGURED a deadline budget and it is
                        # spent — whether in the sidecar's queue (its
                        # DEADLINE_EXCEEDED shed) or on the wire (the
                        # rpc_timeout above).  Latching degraded would hide
                        # sustained overload as an outage; a local solve
                        # blows the budget, so typed error by default —
                        # the reconcile loop (shed_fallback=True) prefers
                        # a late local answer over no answer.
                        # Without a configured budget, DEADLINE_EXCEEDED
                        # keeps its pre-admission meaning (the 60s channel
                        # timeout = sidecar unreachable -> degrade).
                        detail = getattr(err, "details", lambda: "")() or ""
                        if not self.shed_fallback:
                            # ktlint: allow[KT009] client-side re-map of a
                            # deadline the serving side already counted
                            raise SolveDeadlineError(
                                f"solve deadline budget "
                                f"({self.deadline_s:g}s) spent: {detail}",
                                pclass=self.priority, reason="deadline",
                            ) from err
                        logger.warning(
                            "solve deadline budget (%gs) spent (%s); "
                            "serving this solve from the local fallback",
                            self.deadline_s, detail)
                    elif self._transport_failure(err):
                        self._mark_degraded(err)
                    else:
                        logger.warning("remote solve failed (%s); serving this "
                                       "solve from the local fallback",
                                       err.code(), exc_info=True)
                else:
                    # which replica actually served (after any fleet
                    # failover re-route): stamped on the span so the
                    # client-side tree names the serving hop
                    served_by = getattr(resp, "replica_id", "") or ""
                    if served_by:
                        span.annotate(replica=served_by)
                    result = codec.decode_response(resp)
                    # re-attach real PodSpecs to returned nodes (wire carries
                    # names only)
                    by_name = {p.name: p for p in pods}
                    for node in result.nodes:
                        node.pods = [by_name.get(p.name, p) for p in node.pods]
                    return result
        self.registry.counter(REMOTE_FALLBACK_SOLVES).inc()
        # recovery-outcome funnel (KT016): every local-fallback serve IS a
        # recovery from a transport-path failure, injected or organic
        faults_mod.count_recovery(self.registry, "transport", "fallback")
        trace.annotate(remote_fallback=True)
        return self.fallback.solve(
            pods, provisioners, instance_types,
            existing_nodes=existing_nodes, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=allow_new_nodes,
            max_new_nodes=max_new_nodes, trace=trace, relax=relax,
        )

    def warm_startup(
        self,
        provisioners,
        instance_types,
        daemonsets: Sequence[PodSpec] = (),
        existing_nodes: Sequence[SimNode] = (),
        profiles=None,
    ) -> int:
        """Forward the live cluster shape to the sidecar so IT pre-compiles
        the ladder (compiles belong next to the chips).  Best-effort like the
        local warmup: an unreachable sidecar degrades the remote path and
        returns 0 — solves still work via the fallback.  ``profiles`` stays
        sidecar-side (the wire carries the cluster, not the rungs)."""
        if not self._remote_ok():
            return 0
        req = codec.encode_warm_request(
            provisioners, instance_types, daemonsets=daemonsets,
            existing_nodes=existing_nodes, backend=self.backend,
        )
        try:
            return int(self.client.warm_raw(req).started)
        except grpc.RpcError as err:
            if self._transport_failure(err):
                self._mark_degraded(err)
            else:
                # e.g. UNIMPLEMENTED from a pre-Warm sidecar during a rolling
                # upgrade: warmup is best-effort, Solve still works — do not
                # degrade the solve path over it
                logger.debug("remote warm_startup failed (%s); skipping",
                             err.code())
            return 0

    def stop_warms(self) -> None:
        """Operator shutdown: stop the LOCAL fallback's background compiles.
        The sidecar owns its own compile lifecycle (it stops warms when its
        process stops), so nothing is sent remotely."""
        self.fallback.stop_warms()

    def close(self) -> None:
        self.client.close()


class DeltaSession:
    """Session-stateful delta client over the Solve RPC — warm start over
    the wire (docs/ARCHITECTURE.md round 14).

    ``solve()`` establishes the session with one classic full solve;
    ``solve_delta()`` then ships only the PERTURBATION (pod adds/removes,
    ICE'd offerings, node reclaims, catalog-epoch bumps) and merges the
    server's delta-shaped reply into a local ledger — steady-state churn
    costs O(delta) on the wire and sub-milliseconds on the server instead
    of re-shipping and re-solving the cluster.

    Divergence safety: the server acks an epoch per applied step, and the
    client sends its last ack as ``base_epoch``.  Any mismatch — evicted
    session, server restart, a response lost to a deadline — is answered
    ``session_state="unknown"``, and the client transparently re-sends the
    full cluster AT MOST ONCE per call (no retry loop against a flapping
    server; the full solve re-establishes the session).  Unacked
    perturbations accumulate until a step is acked, so a shed/deadline'd
    delta is simply retried cumulatively on the next call — never lost,
    never double-applied.

    Shed posture (the PR-5 typed surface): ``RESOURCE_EXHAUSTED`` maps to
    :class:`SolveShedError` and a budgeted ``DEADLINE_EXCEEDED`` to
    :class:`SolveDeadlineError` WITHOUT consuming the session — the
    sidecar is protecting itself, not forgetting the chain; back off and
    call again.  Transport ``UNAVAILABLE`` (a replica restarting under
    us) rides through ONE bounded jittered-backoff retry inside
    ``SolverClient.solve_raw`` (KT_RPC_RETRIES), then surfaces the typed
    :class:`SolveRetriesExhausted`; the session is KEPT either way — a
    snapshot-restoring replacement replica serves the next delta warm,
    and a replacement without our chain answers ``unknown`` for exactly
    one re-establishing full solve (docs/RESILIENCE.md).

    Fleet posture (ISSUE 13): pass ``client=FleetClient([...])`` and the
    session rides the whole replica fleet — rendezvous affinity routing,
    failover on replica death (the sibling ADOPTS the chain from the
    shared spool and serves the next delta warm), and proactive
    re-homing on the graceful-drain ``session_state="draining"`` hint,
    which this facade treats as a served step.

    ``KT_DELTA=0`` (client-side) turns the facade into a plain full-solve
    client: every call re-ships the cluster with NO session fields on the
    wire — byte-identical requests to pre-delta serving.

    Results are VIEWS: the returned :class:`SolveResult` shares the
    session's ledger containers (same ownership contract as
    ``solver/warmstart.delta_solve`` consuming ``prev``); snapshot before
    mutating.  Single-threaded by contract, like the scheduler facades.
    """

    def __init__(self, target: str, *, session_id: Optional[str] = None,
                 timeout: float = 60.0, backend: str = "",
                 priority: str = "", deadline_s: Optional[float] = None,
                 client: Optional[SolverClient] = None,
                 registry: Optional[Registry] = None) -> None:
        import uuid

        self.client = client or SolverClient(target, timeout=timeout,
                                             registry=registry)
        self.session_id = session_id or uuid.uuid4().hex
        self.backend = backend
        self.priority = parse_class(priority) if priority else ""
        self.deadline_s = deadline_s
        self.enabled = delta_enabled()
        # fleet-wide tracing (ISSUE 15): the session's JOURNEY trace id —
        # one stable, origin-prefixed id for the session's whole life, so
        # every hop it touches (establish on its home, deltas on a
        # steal-adopting sibling after a kill, drain handoffs) adopts the
        # same id server-side and /fleetz renders the journey as ONE
        # timeline.  The SAMPLING decision is made HERE, at the origin,
        # at session granularity: the server-side facade deliberately
        # bypasses sampling for adopted contexts (a half-sampled tree is
        # worse than none), so an unconditional journey id would defeat
        # KT_TRACE_SAMPLE_EVERY on the sub-ms delta hot path entirely.
        # 1-in-N SESSIONS trace their whole journey, decided
        # deterministically from the session id so a client restart (or
        # a second client of the same session) keeps the same decision.
        # KT_TRACE=0 client-side sends no context at all.
        self._trace_id = ""
        if os.environ.get("KT_TRACE", "1") != "0":
            import hashlib

            from ..obs.trace import replica_id as _origin_id

            every = max(1, int(os.environ.get("KT_TRACE_SAMPLE_EVERY",
                                              "1")))
            digest = int.from_bytes(
                hashlib.sha256(self.session_id.encode()).digest()[:8],
                "big")
            if digest % every == 0:
                self._trace_id = (
                    f"{_origin_id()}-sess-{self.session_id[:12]}")
        #: which replica served the last RPC (SolveResponse.replica_id) —
        #: "" against pre-tracing servers
        self.last_replica = ""
        # --- cluster ledger (ground truth the caller has asserted) ---
        self._pods: Optional[Dict[str, PodSpec]] = None  # None: no solve yet
        self._provisioners: List[Provisioner] = []
        self._instance_types: List[InstanceType] = []
        self._existing: List[SimNode] = []
        #: pod name -> existing-node NAME for pods pre-seated on shipped
        #: existing nodes (never in _pods/_assignments): removals of those
        #: pods must unseat them from the _existing ledger too, or a later
        #: re-establish ships phantom pods as seated ground truth
        self._preseated: Dict[str, str] = {}
        self._existing_by_name: Dict[str, SimNode] = {}
        self._daemonsets: List[PodSpec] = []
        self._unavailable: set = set()
        self._allow_new_nodes = True
        self._max_new_nodes: Optional[int] = None
        self._it_by_name: Dict[str, InstanceType] = {}
        self._catalog_epoch = 0
        # --- solution ledger (merged from replies) ---
        self._assignments: Dict[str, str] = {}
        self._infeasible: Dict[str, str] = {}
        self._nodes: "OrderedDict[str, SimNode]" = OrderedDict()
        self._last_ms = 0.0
        # --- session wire state ---
        self._established = False
        self._epoch = 0
        # chain-identity nonce, minted by the server at establishment and
        # echoed on every delta: lets the server reject a delta whose
        # base_epoch collides with a DIFFERENT chain lineage (spool
        # rollback) instead of silently applying it.  "" until the first
        # establishment — and forever against a pre-nonce server, which
        # both sides treat as the legacy wildcard.
        self._nonce = ""
        # --- unacked perturbation (cumulative since the last ack; kept
        # across typed sheds so nothing is lost, cleared on ack) ---
        self._pend_add: Dict[str, PodSpec] = {}
        self._pend_rm: Dict[str, None] = {}
        self._pend_reclaim: List[str] = []
        self._pend_ice: set = set()
        self._catalog_dirty = False
        #: full-solve resends this session performed (tests pin the
        #: at-most-once-per-call contract on it)
        self.full_resends = 0
        #: delta RPCs attempted (ack'd or not)
        self.delta_rpcs = 0

    @property
    def established(self) -> bool:
        return self._established

    @property
    def epoch(self) -> int:
        return self._epoch

    # ---- public API -----------------------------------------------------
    def solve(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
        catalog_epoch: int = 0,
    ) -> SolveResult:
        """(Re-)establish the session: full solve, full cluster on the
        wire, ledger reset to the arguments."""
        # same fail-fast gang audit as the server door (ISSUE 20)
        gangmod.validate_batch(pods)
        self._pods = {p.name: p for p in pods}
        self._provisioners = list(provisioners)
        self._instance_types = list(instance_types)
        self._it_by_name = {it.name: it for it in self._instance_types}
        self._existing = list(existing_nodes)
        self._existing_by_name = {n.name: n for n in self._existing}
        self._preseated = {p.name: n.name
                           for n in self._existing for p in n.pods}
        self._daemonsets = list(daemonsets)
        self._unavailable = set(unavailable or ())
        self._allow_new_nodes = allow_new_nodes
        self._max_new_nodes = max_new_nodes
        self._catalog_epoch = int(catalog_epoch)
        self._clear_pending()
        return self._reestablish()

    def solve_delta(
        self,
        added: Sequence[PodSpec] = (),
        removed: Sequence[str] = (),
        iced: Sequence[object] = (),
        *,
        catalog_epoch: Optional[int] = None,
        provisioners: Optional[Sequence[Provisioner]] = None,
        instance_types: Optional[Sequence[InstanceType]] = None,
    ) -> SolveResult:
        """One churn step: ``added`` pods join, ``removed`` pod names
        leave, ``iced`` entries are offering tuples newly unavailable or
        node NAMES reclaimed (their pods re-place).  A ``catalog_epoch``
        bump (price/catalog change) must ship the new ``instance_types``;
        the server then re-seeds the chain from the stripped base instead
        of cold-starting the session."""
        if self._pods is None:
            raise DeltaSessionUnknown(
                "DeltaSession.solve() must establish the session before "
                "solve_delta()")
        # an added gang is one perturbation — audit it before it enters
        # the ledger, same typed error as the server door (ISSUE 20)
        gangmod.validate_batch(added)
        # 1. fold the perturbation into the cluster ledger + pending set.
        # Removals BEFORE adds, matching the server's apply order
        # (warmstart unseats removals first, then places adds), so a
        # same-call replace (removed=[X], added=[X']) keeps both halves.
        for name in removed:
            self._pods.pop(name, None)
            if name in self._pend_add:
                del self._pend_add[name]  # the server never saw the add
            else:
                self._pend_rm[name] = None
        for p in added:
            self._pods[p.name] = p
            self._pend_add[p.name] = p
            # a pending REMOVAL of the same name stays pending: the
            # server's old pod is still seated until the removal lands,
            # and dropping it here would double-book the old node with
            # a silently diverging chain (the server applies removed
            # before added, so sending both is exactly right)
        for entry in iced:
            if isinstance(entry, str):
                self._reclaim_locally(entry)
                self._pend_reclaim.append(entry)
            else:
                self._unavailable.add(tuple(entry))
                self._pend_ice.add(tuple(entry))
        if catalog_epoch is not None and catalog_epoch != self._catalog_epoch:
            if instance_types is None:
                raise ValueError(
                    "a catalog_epoch bump must carry the new instance_types")
            self._catalog_epoch = int(catalog_epoch)
            self._instance_types = list(instance_types)
            self._it_by_name = {it.name: it for it in self._instance_types}
            if provisioners is not None:
                self._provisioners = list(provisioners)
            self._catalog_dirty = True
        # 2. dispatch: delta when the session is live, else ONE full solve
        if not self.enabled or not self._established:
            return self._reestablish()
        req = codec.encode_request(
            list(self._pend_add.values()),
            self._provisioners if self._catalog_dirty else (),
            self._instance_types if self._catalog_dirty else (),
            unavailable=set(self._pend_ice),
            backend=self.backend, priority=self.priority,
            deadline_ms=(self.deadline_s * 1000.0
                         if self.deadline_s else None),
            session_id=self.session_id, base_epoch=self._epoch, delta=True,
            removed_pods=list(self._pend_rm),
            reclaimed_nodes=list(self._pend_reclaim),
            catalog_epoch=self._catalog_epoch,
            session_nonce=self._nonce,
            # "s1" = the establishment hop's root (root span ids are "s1"
            # by construction): every delta hop attaches under the
            # journey's establishing hop in the /fleetz tree — including
            # hops served by an ADOPTING sibling after failover, which is
            # what makes the whole journey ONE remote-parent-linked tree
            trace_id=self._trace_id, parent_span="s1" if self._trace_id
            else "",
        )
        self.delta_rpcs += 1
        reply = codec.decode_delta_reply(self._rpc(req))
        if reply.state not in ("ok", "draining"):
            # SESSION_UNKNOWN (evicted / epoch mismatch / delta-off
            # server): exactly ONE transparent full resend re-establishes
            # — never a retry loop, never a silently diverged chain
            self._established = False
            return self._reestablish()
        # "draining" is a SERVED step plus a hint (the graceful fleet
        # handshake): the replica applied this delta, spooled the chain
        # and released its lease — the session stays established, and a
        # fleet-aware transport routes the next RPC to a sibling, which
        # adopts the chain and serves it warm (docs/RESILIENCE.md)
        self._epoch = reply.epoch
        if reply.nonce:
            self._nonce = reply.nonce
        if reply.full:
            self._apply_full(reply)
        else:
            self._apply_delta(reply)
        self._clear_pending()
        self._last_ms = reply.solve_ms
        return self.result()

    def result(self) -> SolveResult:
        """The session's current solution VIEW (shared containers — valid
        until the next call; snapshot to keep)."""
        return SolveResult(
            nodes=list(self._nodes.values()),
            assignments=self._assignments,
            infeasible=self._infeasible,
            existing_nodes=list(self._existing),
            solve_ms=self._last_ms,
        )

    def close(self) -> None:
        self.client.close()

    # ---- internals ------------------------------------------------------
    def _clear_pending(self) -> None:
        self._pend_add.clear()
        self._pend_rm.clear()
        self._pend_reclaim = []
        self._pend_ice = set()
        self._catalog_dirty = False

    def _reclaim_locally(self, name: str) -> None:
        """A node reclaim mutates the cluster ledger NOW (the node is
        gone, that is ground truth); its displaced pods become offered
        pods so a later full re-establish still schedules them.  The
        SOLUTION ledger only changes when a reply is acked."""
        kept = []
        for n in self._existing:
            if n.name == name:
                for p in n.pods:
                    self._preseated.pop(p.name, None)
                    if not p.is_daemon:
                        self._pods[p.name] = p
            else:
                kept.append(n)
        self._existing = kept
        self._existing_by_name.pop(name, None)

    def _rpc(self, req: pb.SolveRequest) -> pb.SolveResponse:
        """solve_raw with the PR-5 typed shed surface.  Typed sheds do NOT
        consume the session (pending perturbation + epoch survive for the
        next call); transport failures KEEP it too (ISSUE 12): a
        snapshot-restoring replacement replica serves the next delta
        warm, and one without our chain answers session_unknown for
        exactly one re-establishing full solve."""
        rpc_timeout = (min(self.client.timeout, self.deadline_s)
                       if self.deadline_s else None)
        try:
            resp = self.client.solve_raw(req, timeout=rpc_timeout)
            # the serving replica's identity (stamped server-side): after
            # a fleet failover this names the ADOPTING sibling — the
            # client-visible half of the session's journey timeline
            self.last_replica = getattr(resp, "replica_id", "") or ""
            return resp
        except grpc.RpcError as err:
            code = (err.code()
                    if callable(getattr(err, "code", None)) else None)
            detail = getattr(err, "details", lambda: "")() or ""
            if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                # ktlint: allow[KT009] client-side re-map of a shed the
                # serving side already counted in karpenter_admission_shed_total
                raise SolveShedError(
                    f"solver sidecar shed this delta solve: {detail}",
                    pclass=self.priority, reason="remote_shed") from err
            if (code == grpc.StatusCode.DEADLINE_EXCEEDED
                    and self.deadline_s is not None):
                # ktlint: allow[KT009] client-side re-map of a deadline the
                # serving side already counted
                raise SolveDeadlineError(
                    f"solve deadline budget ({self.deadline_s:g}s) spent: "
                    f"{detail}", pclass=self.priority,
                    reason="deadline") from err
            if code == grpc.StatusCode.INTERNAL or code == getattr(
                    grpc.StatusCode, "UNKNOWN", None):
                # the server failed MID-STEP (it evicted our session; the
                # dispatcher re-raised into the RPC).  Typed surface: the
                # session ledger + pending perturbation survive, and the
                # next call re-establishes via session_unknown — exactly
                # one full solve (docs/RESILIENCE.md invariant: errors
                # are typed, recovery cost is bounded)
                faults_mod.count_recovery(
                    self.client._registry, "delta_step", "failed")
                raise SolveStepFailed(
                    f"delta step failed server-side: {detail}") from err
            # transport failure after the client's bounded ride-through
            # retry (SolverClient.solve_raw): rebuild the channel, KEEP
            # the session — the replacement replica restores the
            # KT_SESSION_DIR spool and serves our next delta WARM
            # (docs/RESILIENCE.md).  Keeping it is safe either way: if
            # the restart lost (or half-applied) our chain, the epoch
            # check answers session_unknown and the next call pays
            # exactly ONE re-establishing full solve — the pre-snapshot
            # behavior, never a diverged chain.
            self.client.reset()
            raise

    def _reestablish(self) -> SolveResult:
        """ONE full solve from the cluster ledger; establishes the session
        when both sides have delta serving on."""
        session_kw = {}
        if self.enabled:
            session_kw = dict(session_id=self.session_id, delta=False,
                              catalog_epoch=self._catalog_epoch)
        req = codec.encode_request(
            list(self._pods.values()), self._provisioners,
            self._instance_types,
            existing_nodes=self._existing, daemonsets=self._daemonsets,
            unavailable=self._unavailable or None,
            allow_new_nodes=self._allow_new_nodes,
            max_new_nodes=self._max_new_nodes,
            backend=self.backend, priority=self.priority,
            deadline_ms=(self.deadline_s * 1000.0
                         if self.deadline_s else None),
            trace_id=self._trace_id,
            **session_kw,
        )
        self.full_resends += 1
        reply = codec.decode_delta_reply(self._rpc(req))
        if self.enabled and reply.state == "draining":
            # an establishment REFUSED by a draining replica, and the
            # transport had no sibling to re-route to (single-endpoint
            # client, or the whole fleet draining at once).  Nothing was
            # solved; ledger + pending perturbation survive for a retry
            # against the replacement pod.
            raise SolverDraining(
                "solver is draining and refused the session "
                "establishment; retry shortly (a FleetClient re-homes "
                "this automatically)")
        self._established = reply.state == "ok"
        self._epoch = reply.epoch
        # the establishment reply carries the chain's fresh identity;
        # a pre-nonce server leaves it "" (wildcard) and nothing changes
        self._nonce = reply.nonce if self._established else ""
        self._apply_full(reply)
        self._clear_pending()
        self._last_ms = reply.solve_ms
        return self.result()

    def _attach(self, node: SimNode) -> SimNode:
        """Re-attach the ledger's real PodSpecs (the wire carries names)
        and re-hydrate node fidelity from the ledger's catalog: the wire's
        NewNode is placement-only (type/zone/ct/price/pod names), but
        callers — and the ground-truth validator — read allocatable and
        labels off the session's view."""
        node.pods = [self._pods.get(p.name, p) for p in node.pods]
        it = self._it_by_name.get(node.instance_type)
        if it is not None and not node.allocatable:
            node.allocatable = dict(it.allocatable)
        node.stamp_labels()
        return node

    def _apply_full(self, reply) -> None:
        self._assignments = dict(reply.assignments)
        self._infeasible = dict(reply.infeasible)
        self._nodes = OrderedDict(
            (n.name, self._attach(n)) for n in reply.nodes)

    def _apply_delta(self, reply) -> None:
        """Merge one acked incremental step into the solution ledger, in
        the same order the server applied it: removals unseat, reclaims
        and pruned proposals drop nodes, new nodes appear, then the
        step's (re)placements land."""
        # removals: targeted scan-and-delete of the ONE departing pod per
        # node (the merge runs on every delta RPC — a full pods-list
        # rebuild per removal would cost O(delta x node width))
        for name in self._pend_rm:
            old = self._assignments.pop(name, None)
            self._infeasible.pop(name, None)
            if old is None:
                # a pod PRE-SEATED on a shipped existing node (never in
                # assignments): unseat it from the _existing ledger too —
                # a re-establish ships those pods as seated ground truth,
                # and a phantom would make the server pack around
                # capacity the departed pod no longer uses
                old = self._preseated.pop(name, None)
                node = (self._existing_by_name.get(old)
                        if old is not None else None)
            else:
                node = self._nodes.get(old)
            if node is not None:
                for i, p in enumerate(node.pods):
                    if p.name == name:
                        del node.pods[i]
                        break
        for rname in self._pend_reclaim:
            node = self._nodes.pop(rname, None)
            for p in (node.pods if node is not None else ()):
                self._assignments.pop(p.name, None)
            # a reclaimed EXISTING node left the ledger at call time; any
            # OTHER placement that pointed at it (a delta-placed pod) is
            # superseded by this reply — every displaced pod arrives in
            # reply.assignments or reply.infeasible (the server's watch
            # set), so no O(cluster) sweep of the assignments dict is
            # needed here
        for rname in reply.removed_nodes:
            self._nodes.pop(rname, None)
        for node in reply.nodes:
            self._nodes[node.name] = self._attach(node)
        # the step's placements: every watch pod was UNSEATED before this
        # step placed it (adds were never seated, re-offers were
        # infeasible, reclaim-displaced pods lost their node above, and
        # the incremental tiers never move any other pod), and a node
        # arriving in reply.nodes already carries its pods — so appends
        # below need no membership scan
        new_names = {n.name for n in reply.nodes}
        for name, target in reply.assignments.items():
            old = self._assignments.get(name)
            if old is not None and old != target:
                onode = self._nodes.get(old)  # robustness: never expected
                if onode is not None:
                    onode.pods = [p for p in onode.pods if p.name != name]
            self._assignments[name] = target
            self._infeasible.pop(name, None)
            if target not in new_names:
                tnode = self._nodes.get(target)
                if tnode is not None:
                    tnode.pods.append(
                        self._pods.get(name, PodSpec(name=name)))
        for name, why in reply.infeasible.items():
            if name in self._pods:
                self._infeasible[name] = why
                # a pod that WAS placed and is now unplaceable (its node
                # reclaimed, nowhere to go) must not keep a stale entry
                self._assignments.pop(name, None)
