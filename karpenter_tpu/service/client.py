"""Solver service client — a BatchScheduler-compatible remote scheduler.

``RemoteScheduler`` is a drop-in for ``solver.scheduler.BatchScheduler`` so
controllers can point at a sidecar instead of solving in-process (the
reconciler <-> solver split of the north star).
"""

from __future__ import annotations

from typing import Optional, Sequence, Set

import grpc

from ..models.instancetype import InstanceType
from ..models.pod import PodSpec
from ..models.provisioner import Provisioner
from ..solver.types import SimNode, SolveResult
from . import codec
from . import solver_pb2 as pb
from .server import SERVICE


class SolverClient:
    def __init__(self, target: str, timeout: float = 60.0) -> None:
        self.channel = grpc.insecure_channel(
            target,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)],
        )
        self.timeout = timeout
        self._solve = self.channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def health(self) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=self.timeout)

    def solve_raw(self, request: pb.SolveRequest) -> pb.SolveResponse:
        return self._solve(request, timeout=self.timeout)

    def close(self) -> None:
        self.channel.close()


class RemoteScheduler:
    """BatchScheduler-compatible facade over the sidecar."""

    def __init__(self, target: str, backend: str = "", timeout: float = 60.0) -> None:
        self.client = SolverClient(target, timeout=timeout)
        self.backend = backend

    def solve(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
    ) -> SolveResult:
        req = codec.encode_request(
            pods, provisioners, instance_types,
            existing_nodes=existing_nodes, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=allow_new_nodes,
            max_new_nodes=max_new_nodes, backend=self.backend,
        )
        resp = self.client.solve_raw(req)
        result = codec.decode_response(resp)
        # re-attach real PodSpecs to returned nodes (wire carries names only)
        by_name = {p.name: p for p in pods}
        for node in result.nodes:
            node.pods = [by_name.get(p.name, p) for p in node.pods]
        return result
