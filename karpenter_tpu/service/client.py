"""Solver service client — a BatchScheduler-compatible remote scheduler.

``RemoteScheduler`` is a drop-in for ``solver.scheduler.BatchScheduler`` so
controllers can point at a sidecar instead of solving in-process (the
reconciler <-> solver split of the north star; the reference consumes its
remote boundary the same way — ``cloudprovider.New(awsCtx)`` at
cmd/controller/main.go:44 is handed to every control loop).  The facade
contract (same methods, same signatures) is asserted by
tests/test_service.py::TestFacadeContract (test_signatures_match /
test_shared_attributes) so any drift between the two schedulers fails CI,
not production.
"""

from __future__ import annotations

import logging
import time
from typing import Optional, Sequence, Set

import grpc

from ..admission import SolveDeadlineError, SolveShedError, parse_class
from ..metrics import Registry, registry as default_registry
from ..models.instancetype import InstanceType
from ..models.pod import PodSpec
from ..obs.trace import NULL_TRACE
from ..models.provisioner import Provisioner
from ..solver.scheduler import BatchScheduler
from ..solver.types import SimNode, SolveResult
from . import codec
from . import solver_pb2 as pb
from .server import SERVICE

logger = logging.getLogger(__name__)

from ..metrics import REMOTE_DEGRADED, REMOTE_FALLBACK_SOLVES  # noqa: E402
# (names + help text live in metrics.INVENTORY so docs/METRICS.md covers them)


class SolverClient:
    def __init__(self, target: str, timeout: float = 60.0) -> None:
        self.target = target
        self.timeout = timeout
        self._connect()

    def _connect(self) -> None:
        self.channel = grpc.insecure_channel(
            self.target,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)],
        )
        self._solve = self.channel.unary_unary(
            f"/{SERVICE}/Solve",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._warm = self.channel.unary_unary(
            f"/{SERVICE}/Warm",
            request_serializer=pb.WarmRequest.SerializeToString,
            response_deserializer=pb.WarmResponse.FromString,
        )
        self._health = self.channel.unary_unary(
            f"/{SERVICE}/Health",
            request_serializer=pb.HealthRequest.SerializeToString,
            response_deserializer=pb.HealthResponse.FromString,
        )

    def reset(self) -> None:
        """Drop and rebuild the channel.  A grpc channel whose connection
        attempts started while the server was down can wedge in a
        reconnect-backoff state that outlives the outage (observed on this
        host as endless 'tcp handshaker shutdown' UNAVAILABLE errors against
        a LISTENING server); a fresh channel connects on its first try, so
        the degraded-path health probe resets after every failed attempt."""
        self.close()
        self._connect()

    def health(self, timeout: Optional[float] = None) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=timeout or self.timeout)

    def solve_raw(self, request: pb.SolveRequest,
                  timeout: Optional[float] = None) -> pb.SolveResponse:
        return self._solve(request, timeout=timeout or self.timeout)

    def warm_raw(self, request: pb.WarmRequest) -> pb.WarmResponse:
        return self._warm(request, timeout=self.timeout)

    def close(self) -> None:
        self.channel.close()


class RemoteScheduler:
    """BatchScheduler-compatible facade over the sidecar.

    Availability semantics: when the sidecar is unreachable, ``solve`` falls
    back to a LOCAL solve (oracle backend by default) so the control plane
    keeps reconciling — scale-up must not stall on a solver rollout.  After a
    failure the remote path is considered degraded; it is retried only
    through a cheap Health probe at most once per ``reconnect_interval``
    seconds (health-gated reconnect), so a down sidecar costs one probe per
    interval, not one deadline-wait per solve.
    """

    #: seconds between Health probes while degraded
    RECONNECT_INTERVAL = 5.0
    #: deadline for the Health probe itself — must be snappy: it sits on the
    #: reconcile path while degraded
    PROBE_TIMEOUT = 2.0

    def __init__(
        self,
        target: str,
        backend: str = "",
        timeout: float = 60.0,
        *,
        fallback: Optional[BatchScheduler] = None,
        reconnect_interval: float = RECONNECT_INTERVAL,
        registry: Optional[Registry] = None,
        priority: str = "",
        deadline_s: Optional[float] = None,
        shed_fallback: bool = False,
    ) -> None:
        self.client = SolverClient(target, timeout=timeout)
        self.target = target
        self.backend = backend
        # admission identity (docs/ADMISSION.md): every Solve this facade
        # sends carries the caller's priority class and deadline budget.
        # Constructor-level (not per-call) so the BatchScheduler facade
        # contract (tests/test_service.py::TestFacadeContract) stays
        # byte-for-byte — a control loop IS one priority class.
        self.priority = parse_class(priority) if priority else ""
        self.deadline_s = deadline_s
        # shed posture: library callers get the typed SolveShedError /
        # SolveDeadlineError (back off, re-plan); an availability-first
        # control loop (the operator's reconciler — it has no backoff
        # story, a raised shed would kill the whole loop) sets
        # shed_fallback=True: the shed is logged + counted and THIS solve
        # is served locally, WITHOUT latching the degraded path — the
        # sidecar is healthy and protecting itself, so the next solve
        # goes remote again.
        self.shed_fallback = shed_fallback
        self.mesh = None  # the device mesh lives sidecar-side
        self.registry = registry or default_registry
        self.fallback = fallback or BatchScheduler(
            backend="oracle", registry=self.registry
        )
        self.reconnect_interval = reconnect_interval
        self._degraded_since: Optional[float] = None
        self._last_probe = 0.0
        # zero-init so the series exists from the first scrape (inc(0)
        # creates the sample; construction alone does not)
        self.registry.counter(REMOTE_FALLBACK_SOLVES).inc(value=0.0)
        self.registry.gauge(REMOTE_DEGRADED).set(0)

    #: RPC status codes that mean "the sidecar is not reachable right now".
    #: Anything else (UNIMPLEMENTED from an older sidecar's missing Warm
    #: handler, INTERNAL on one bad request, ...) must NOT poison the Solve
    #: path: that call falls back / returns 0, the next one goes remote.
    TRANSPORT_CODES = (grpc.StatusCode.UNAVAILABLE,
                       grpc.StatusCode.DEADLINE_EXCEEDED)

    # ---- degradation state ------------------------------------------------
    def degraded(self) -> bool:
        return self._degraded_since is not None

    def _transport_failure(self, err: grpc.RpcError) -> bool:
        code = err.code() if callable(getattr(err, "code", None)) else None
        return code in self.TRANSPORT_CODES

    def _mark_degraded(self, err: Exception) -> None:
        if self._degraded_since is None:
            logger.warning("solver sidecar %s unreachable (%s); "
                           "falling back to local %s solves", self.target,
                           getattr(err, "code", lambda: err)(),
                           self.fallback.backend)
        # ktlint: allow[KT002] transport-health stopwatch: reconnect pacing
        # must follow real wall progress, not the operator's injected clock
        # (a FakeClock-driven test advancing hours would hot-loop probes)
        self._degraded_since = time.monotonic()
        self._last_probe = self._degraded_since
        self.registry.gauge(REMOTE_DEGRADED).set(1)

    def _remote_ok(self) -> bool:
        """True when the remote path should be attempted: healthy, or
        degraded but due for a (successful) health probe."""
        if self._degraded_since is None:
            return True
        now = time.monotonic()  # ktlint: allow[KT002] see _mark_degraded
        if now - self._last_probe < self.reconnect_interval:
            return False
        self._last_probe = now
        try:
            ok = bool(self.client.health(timeout=self.PROBE_TIMEOUT).ok)
        except grpc.RpcError:
            # arm the NEXT probe with a fresh channel: a channel that began
            # connecting while the sidecar was down can stay wedged after it
            # comes back (see SolverClient.reset) — without this the remote
            # path would never recover on affected stacks
            self.client.reset()
            return False
        if ok:
            logger.info("solver sidecar %s back after %.1fs; resuming remote "
                        "solves", self.target,
                        now - (self._degraded_since or now))
            self._degraded_since = None
            self.registry.gauge(REMOTE_DEGRADED).set(0)
        return ok

    # ---- BatchScheduler surface -------------------------------------------
    def solve(
        self,
        pods: Sequence[PodSpec],
        provisioners: Sequence[Provisioner],
        instance_types: Sequence[InstanceType],
        *,
        existing_nodes: Sequence[SimNode] = (),
        daemonsets: Sequence[PodSpec] = (),
        unavailable: Optional[Set[tuple]] = None,
        allow_new_nodes: bool = True,
        max_new_nodes: Optional[int] = None,
        trace=None,
    ) -> SolveResult:
        trace = trace or NULL_TRACE
        if self._remote_ok():
            # the trace stays operator-side: the wire carries no context, so
            # the whole RPC is one "remote" span here and the sidecar cuts
            # its own trace (its /tracez has the per-phase breakdown)
            with trace.span("remote", target=self.target) as span:
                req = codec.encode_request(
                    pods, provisioners, instance_types,
                    existing_nodes=existing_nodes, daemonsets=daemonsets,
                    unavailable=unavailable, allow_new_nodes=allow_new_nodes,
                    max_new_nodes=max_new_nodes, backend=self.backend,
                    priority=self.priority,
                    deadline_ms=(self.deadline_s * 1000.0
                                 if self.deadline_s else None),
                )
                # the wire deadline budget also bounds the RPC itself: a
                # caller with 250ms left must not block 60s on the channel
                rpc_timeout = (min(self.client.timeout, self.deadline_s)
                               if self.deadline_s else None)
                try:
                    resp = self.client.solve_raw(req, timeout=rpc_timeout)
                except grpc.RpcError as err:
                    code = (err.code()
                            if callable(getattr(err, "code", None)) else None)
                    span.annotate(transport_error=str(code or err))
                    if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                        # the sidecar SHED this request (admission queue
                        # full / rate limit / brownout).  Overload is not
                        # an outage — NEVER latch the degraded path (the
                        # sidecar is healthy, it is protecting itself).
                        # Library callers get the typed error so they back
                        # off; an availability-first reconcile loop
                        # (shed_fallback=True) logs it and serves THIS
                        # solve locally, next one goes remote again.
                        detail = getattr(err, "details", lambda: "")() or ""
                        if not self.shed_fallback:
                            # ktlint: allow[KT009] client-side re-map of a
                            # shed the serving side already counted in
                            # karpenter_admission_shed_total
                            raise SolveShedError(
                                f"solver sidecar shed this solve: {detail}",
                                pclass=self.priority, reason="remote_shed",
                            ) from err
                        logger.warning(
                            "solver sidecar shed this solve (%s); serving "
                            "it from the local fallback", detail)
                    elif (code == grpc.StatusCode.DEADLINE_EXCEEDED
                            and self.deadline_s is not None):
                        # the caller CONFIGURED a deadline budget and it is
                        # spent — whether in the sidecar's queue (its
                        # DEADLINE_EXCEEDED shed) or on the wire (the
                        # rpc_timeout above).  Latching degraded would hide
                        # sustained overload as an outage; a local solve
                        # blows the budget, so typed error by default —
                        # the reconcile loop (shed_fallback=True) prefers
                        # a late local answer over no answer.
                        # Without a configured budget, DEADLINE_EXCEEDED
                        # keeps its pre-admission meaning (the 60s channel
                        # timeout = sidecar unreachable -> degrade).
                        detail = getattr(err, "details", lambda: "")() or ""
                        if not self.shed_fallback:
                            # ktlint: allow[KT009] client-side re-map of a
                            # deadline the serving side already counted
                            raise SolveDeadlineError(
                                f"solve deadline budget "
                                f"({self.deadline_s:g}s) spent: {detail}",
                                pclass=self.priority, reason="deadline",
                            ) from err
                        logger.warning(
                            "solve deadline budget (%gs) spent (%s); "
                            "serving this solve from the local fallback",
                            self.deadline_s, detail)
                    elif self._transport_failure(err):
                        self._mark_degraded(err)
                    else:
                        logger.warning("remote solve failed (%s); serving this "
                                       "solve from the local fallback",
                                       err.code(), exc_info=True)
                else:
                    result = codec.decode_response(resp)
                    # re-attach real PodSpecs to returned nodes (wire carries
                    # names only)
                    by_name = {p.name: p for p in pods}
                    for node in result.nodes:
                        node.pods = [by_name.get(p.name, p) for p in node.pods]
                    return result
        self.registry.counter(REMOTE_FALLBACK_SOLVES).inc()
        trace.annotate(remote_fallback=True)
        return self.fallback.solve(
            pods, provisioners, instance_types,
            existing_nodes=existing_nodes, daemonsets=daemonsets,
            unavailable=unavailable, allow_new_nodes=allow_new_nodes,
            max_new_nodes=max_new_nodes, trace=trace,
        )

    def warm_startup(
        self,
        provisioners,
        instance_types,
        daemonsets: Sequence[PodSpec] = (),
        existing_nodes: Sequence[SimNode] = (),
        profiles=None,
    ) -> int:
        """Forward the live cluster shape to the sidecar so IT pre-compiles
        the ladder (compiles belong next to the chips).  Best-effort like the
        local warmup: an unreachable sidecar degrades the remote path and
        returns 0 — solves still work via the fallback.  ``profiles`` stays
        sidecar-side (the wire carries the cluster, not the rungs)."""
        if not self._remote_ok():
            return 0
        req = codec.encode_warm_request(
            provisioners, instance_types, daemonsets=daemonsets,
            existing_nodes=existing_nodes, backend=self.backend,
        )
        try:
            return int(self.client.warm_raw(req).started)
        except grpc.RpcError as err:
            if self._transport_failure(err):
                self._mark_degraded(err)
            else:
                # e.g. UNIMPLEMENTED from a pre-Warm sidecar during a rolling
                # upgrade: warmup is best-effort, Solve still works — do not
                # degrade the solve path over it
                logger.debug("remote warm_startup failed (%s); skipping",
                             err.code())
            return 0

    def stop_warms(self) -> None:
        """Operator shutdown: stop the LOCAL fallback's background compiles.
        The sidecar owns its own compile lifecycle (it stops warms when its
        process stops), so nothing is sent remotely."""
        self.fallback.stop_warms()

    def close(self) -> None:
        self.client.close()
