"""Declarative config: YAML manifests -> admission -> API objects.

The reference is configured almost entirely through YAML — CRD instances
(`pkg/apis/crds/karpenter.sh_provisioners.yaml:37-315`,
`charts/karpenter-crd/`) and the `karpenter-global-settings` ConfigMap.
This module is the framework's ingestion path for the same three kinds:

- ``Provisioner``      (karpenter.sh/v1alpha5-shaped spec)
- ``NodeTemplate``     (the AWSNodeTemplate analog, provider spec)
- ``ConfigMap``        (karpenter-global-settings data)

Every parsed object passes through the admission layer (``webhooks.py``)
before it reaches cluster state — invalid documents are rejected with the
structured admission errors, exactly like the reference's validating
webhooks (`pkg/webhooks/webhooks.go:33-63`).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

import yaml

from dataclasses import replace

from .cloud.templates import BlockDevice, NodeTemplate
from .models import labels as L  # noqa: F401  (manifest docs reference labels)
from .models.pod import Taint
from .models.provisioner import KubeletConfiguration, Provisioner
from .models.requirements import Requirement
from .models.volume import (
    VOLUME_BINDING_IMMEDIATE,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    parse_zone_topology,
)
from .settings import Settings
from .utils.quantity import parse_quantity
from .webhooks import (
    AdmissionError,
    admit_node_template,
    admit_provisioner,
    admit_settings,
)

_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h)?\s*$")
_DURATION_SCALE = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, None: 1.0}


def parse_duration(value) -> float:
    """'10s' / '500ms' / '9.5m' / bare numbers -> seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"invalid duration: {value!r}")
    return float(m.group(1)) * _DURATION_SCALE[m.group(2)]


def _parse_bool(value) -> bool:
    if isinstance(value, bool):
        return value
    return str(value).strip().lower() in ("1", "true", "yes", "on")


# ---------------------------------------------------------------------------
# provisioner (karpenter.sh_provisioners.yaml spec shape)
# ---------------------------------------------------------------------------


def parse_provisioner(doc: dict) -> Provisioner:
    meta = doc.get("metadata", {}) or {}
    spec = doc.get("spec", {}) or {}
    reqs = [
        Requirement(r["key"], r["operator"], list(r.get("values", [])))
        for r in spec.get("requirements", []) or []
    ]
    taints = [
        Taint(t.get("key", ""), t.get("effect", ""), t.get("value", ""))
        for t in spec.get("taints", []) or []
    ]
    startup = [
        Taint(t.get("key", ""), t.get("effect", ""), t.get("value", ""))
        for t in spec.get("startupTaints", []) or []
    ]
    limits = {
        k: parse_quantity(v)
        for k, v in ((spec.get("limits", {}) or {}).get("resources", {}) or {}).items()
    }
    consolidation = spec.get("consolidation", {}) or {}
    provider_ref = spec.get("providerRef", {}) or {}
    kc_doc = spec.get("kubeletConfiguration")
    kubelet = _parse_kubelet(kc_doc) if kc_doc else None
    return Provisioner(
        name=meta.get("name", "default"),
        requirements=reqs,
        taints=taints,
        startup_taints=startup,
        labels=dict(spec.get("labels", {}) or {}),
        limits=limits,
        weight=int(spec.get("weight", 0) or 0),
        consolidation_enabled=_parse_bool(consolidation.get("enabled", False)),
        ttl_seconds_after_empty=(
            float(spec["ttlSecondsAfterEmpty"])
            if spec.get("ttlSecondsAfterEmpty") is not None else None
        ),
        ttl_seconds_until_expired=(
            float(spec["ttlSecondsUntilExpired"])
            if spec.get("ttlSecondsUntilExpired") is not None else None
        ),
        node_template=provider_ref.get("name", "default"),
        kubelet=kubelet,
    )


def _parse_kubelet(doc: dict) -> KubeletConfiguration:
    """spec.kubeletConfiguration (karpenter.sh_provisioners.yaml:56-135):
    reserved maps are resource quantities, eviction signals stay strings
    (percentage-or-quantity is resolved against each node's capacity at
    instance-type specialization time), grace periods are durations."""
    return KubeletConfiguration(
        max_pods=int(doc["maxPods"]) if doc.get("maxPods") is not None else None,
        pods_per_core=(
            int(doc["podsPerCore"]) if doc.get("podsPerCore") is not None else None
        ),
        system_reserved={
            k: parse_quantity(v) for k, v in (doc.get("systemReserved") or {}).items()
        },
        kube_reserved={
            k: parse_quantity(v) for k, v in (doc.get("kubeReserved") or {}).items()
        },
        eviction_hard=dict(doc.get("evictionHard") or {}),
        eviction_soft=dict(doc.get("evictionSoft") or {}),
        eviction_soft_grace_period={
            k: parse_duration(v)
            for k, v in (doc.get("evictionSoftGracePeriod") or {}).items()
        },
        eviction_max_pod_grace_period=(
            int(doc["evictionMaxPodGracePeriod"])
            if doc.get("evictionMaxPodGracePeriod") is not None else None
        ),
        cluster_dns=tuple(doc.get("clusterDNS") or ()),
        container_runtime=doc.get("containerRuntime"),
    )


# ---------------------------------------------------------------------------
# storage objects (PV topology inputs — scheduling.md:378-433)
# ---------------------------------------------------------------------------


def parse_storage_class(doc: dict) -> StorageClass:
    meta = doc.get("metadata", {}) or {}
    exprs = []
    for topo in doc.get("allowedTopologies", []) or []:
        exprs.extend(topo.get("matchLabelExpressions", []) or [])
    zones, errors = parse_zone_topology(exprs)
    if errors:
        raise AdmissionError("StorageClass", meta.get("name", "?"), errors)
    return StorageClass(
        name=meta.get("name", "default"),
        provisioner=doc.get("provisioner", "ebs.csi.tpu"),
        volume_binding_mode=doc.get("volumeBindingMode", VOLUME_BINDING_IMMEDIATE),
        allowed_zones=zones,
    )


def parse_persistent_volume(doc: dict) -> PersistentVolume:
    meta = doc.get("metadata", {}) or {}
    spec = doc.get("spec", {}) or {}
    exprs = []
    required = ((spec.get("nodeAffinity", {}) or {}).get("required", {}) or {})
    for term in required.get("nodeSelectorTerms", []) or []:
        exprs.extend(term.get("matchExpressions", []) or [])
    zones, errors = parse_zone_topology(exprs)
    if errors:
        raise AdmissionError("PersistentVolume", meta.get("name", "?"), errors)
    storage = (spec.get("capacity", {}) or {}).get("storage", 0)
    return PersistentVolume(
        name=meta.get("name", "?"),
        zones=zones,
        storage_class=spec.get("storageClassName", ""),
        capacity=parse_quantity(storage) if storage else 0.0,
    )


def parse_persistent_volume_claim(doc: dict) -> PersistentVolumeClaim:
    meta = doc.get("metadata", {}) or {}
    spec = doc.get("spec", {}) or {}
    requested = (((spec.get("resources", {}) or {}).get("requests", {}) or {})
                 .get("storage", 0))
    return PersistentVolumeClaim(
        name=meta.get("name", "?"),
        namespace=meta.get("namespace", "default"),
        storage_class=spec.get("storageClassName", ""),
        volume_name=spec.get("volumeName", ""),
        requested=parse_quantity(requested) if requested else 0.0,
    )


# ---------------------------------------------------------------------------
# node template (AWSNodeTemplate analog spec shape)
# ---------------------------------------------------------------------------


def parse_node_template(doc: dict) -> NodeTemplate:
    meta = doc.get("metadata", {}) or {}
    spec = doc.get("spec", {}) or {}
    md = spec.get("metadataOptions", {}) or {}
    devices = [
        BlockDevice(
            device_name=d.get("deviceName", "/dev/xvda"),
            size_gib=(
                parse_quantity(d["sizeGiB"]) if "sizeGiB" in d
                else parse_quantity(d.get("volumeSize", "20Gi")) / 1024.0**3
            ),
            volume_type=d.get("volumeType", "gp3"),
            encrypted=_parse_bool(d.get("encrypted", True)),
        )
        for d in spec.get("blockDevices", []) or []
    ]
    return NodeTemplate(
        name=meta.get("name", "default"),
        image_family=spec.get("imageFamily", "standard"),
        image_selector=dict(spec.get("imageSelector", {}) or {}),
        subnet_selector=dict(spec.get("subnetSelector", {}) or {}),
        security_group_selector=dict(spec.get("securityGroupSelector", {}) or {}),
        user_data=spec.get("userData", "") or "",
        instance_profile=spec.get("instanceProfile", "") or "",
        block_devices=devices,
        launch_template_name=spec.get("launchTemplateName"),
        metadata_http_tokens=md.get("httpTokens", "required"),
        metadata_http_endpoint=md.get("httpEndpoint", "enabled"),
        metadata_hop_limit=int(md.get("httpPutResponseHopLimit", 2)),
        tags=dict(spec.get("tags", {}) or {}),
        detailed_monitoring=_parse_bool(spec.get("detailedMonitoring", False)),
    )


# ---------------------------------------------------------------------------
# global-settings ConfigMap (settings.go:40-65 data keys)
# ---------------------------------------------------------------------------

#: data key -> (Settings field, parser)
_SETTINGS_KEYS = {
    "clusterName": ("cluster_name", str),
    "clusterEndpoint": ("cluster_endpoint", str),
    "defaultInstanceProfile": ("default_instance_profile", str),
    "vmMemoryOverheadPercent": ("vm_memory_overhead_percent", float),
    "enablePodENI": ("enable_pod_eni", _parse_bool),
    "enableENILimitedPodDensity": ("enable_eni_limited_pod_density", _parse_bool),
    "isolatedVPC": ("isolated_vpc", _parse_bool),
    "nodeNameConvention": ("node_name_convention", str),
    "interruptionQueueName": ("interruption_queue_name", str),
    "batchMaxDuration": ("batch_max_duration", parse_duration),
    "batchIdleDuration": ("batch_idle_duration", parse_duration),
    "featureGates.driftEnabled": ("drift_enabled", _parse_bool),
    "deprovisioningTTL": ("deprovisioning_ttl", parse_duration),
}


def parse_settings(doc: dict) -> Dict[str, object]:
    """ConfigMap data -> Settings field overrides (unknown keys rejected so
    config typos fail loudly instead of silently doing nothing)."""
    data = doc.get("data", {}) or {}
    out: Dict[str, object] = {}
    unknown = []
    for k, v in data.items():
        if k == "tags" or k.startswith("tags."):
            tags = out.setdefault("tags", {})
            if k == "tags":
                tags.update(yaml.safe_load(v) or {})
            else:
                tags[k.split(".", 1)[1]] = str(v)
            continue
        ent = _SETTINGS_KEYS.get(k)
        if ent is None:
            unknown.append(k)
            continue
        field_name, parser = ent
        out[field_name] = parser(v)
    if unknown:
        raise AdmissionError(
            "ConfigMap", doc.get("metadata", {}).get("name", "settings"),
            [f"unknown settings key {k!r}" for k in sorted(unknown)],
        )
    return out


# ---------------------------------------------------------------------------
# loading + admission
# ---------------------------------------------------------------------------


def load_documents(path) -> List[dict]:
    """All YAML documents under ``path`` (a file, or a directory scanned for
    *.yaml/*.yml in sorted order; multi-document files supported).  Missing
    paths and empty directories are config errors (AdmissionError), not
    silent successes."""
    p = Path(path)
    if not p.exists():
        raise AdmissionError("Manifest", str(p), ["path does not exist"])
    files = (
        sorted(list(p.glob("*.yaml")) + list(p.glob("*.yml")))
        if p.is_dir() else [p]
    )
    if not files:
        raise AdmissionError("Manifest", str(p), ["no *.yaml/*.yml files found"])
    docs: List[dict] = []
    for f in files:
        try:
            for doc in yaml.safe_load_all(f.read_text()):
                if doc:
                    docs.append(doc)
        except (OSError, yaml.YAMLError) as err:
            raise AdmissionError("Manifest", str(f), [f"unreadable: {err}"])
    return docs


def admit_documents(
    docs: Iterable[dict],
    current_settings: Optional[Settings] = None,
) -> Tuple[List[Provisioner], List[NodeTemplate], Dict[str, object], List[object]]:
    """Parse + ADMIT every recognized document; raises AdmissionError on the
    first invalid one.  Unrecognized kinds are skipped (a manifest dir may
    carry Deployments/RBAC alongside the karpenter objects).  Settings
    overrides are judged against ``current_settings`` (the LIVE settings of
    the operator the docs will apply to — a partial override is valid or
    invalid only relative to the values it leaves in place)."""
    provisioners: List[Provisioner] = []
    templates: List[NodeTemplate] = []
    settings: Dict[str, object] = {}
    storage: List[object] = []  # StorageClass | PersistentVolume | PVC
    for doc in docs:
        kind = str(doc.get("kind", ""))
        name = str((doc.get("metadata", {}) or {}).get("name", "?"))
        try:
            if kind == "Provisioner":
                prov = parse_provisioner(doc)
                admit_provisioner(prov)  # default-then-validate; raises
                # store the RAW spec (state.apply_provisioner's convention;
                # controllers call with_defaults() at use time)
                provisioners.append(prov)
            elif kind in ("NodeTemplate", "AWSNodeTemplate"):
                templates.append(admit_node_template(parse_node_template(doc)))
            elif (kind == "ConfigMap" and name == "karpenter-global-settings"):
                settings.update(parse_settings(doc))
            elif kind == "StorageClass":
                storage.append(parse_storage_class(doc))
            elif kind == "PersistentVolume":
                storage.append(parse_persistent_volume(doc))
            elif kind == "PersistentVolumeClaim":
                storage.append(parse_persistent_volume_claim(doc))
        except AdmissionError:
            raise
        except (ValueError, KeyError, TypeError, AttributeError) as err:
            # malformed-but-parseable specs deny with structure, they do not
            # crash the ingestion path (bad quantities, missing requirement
            # keys, non-numeric TTLs, ...)
            raise AdmissionError(kind or "?", name, [f"malformed spec: {err!r}"])
    if settings:
        # judged against the live baseline (apply_objects re-validates under
        # the operator's lock right before mutating)
        admit_settings(replace(current_settings or Settings(), **settings))
    return provisioners, templates, settings, storage


def apply_objects(
    provisioners: List[Provisioner],
    templates: List[NodeTemplate],
    overrides: Dict[str, object],
    storage: List[object] = (),
    *,
    state=None,
    cloud=None,
    settings_store=None,
) -> None:
    """Apply admitted objects to a running operator — the SINGLE apply
    sequence shared by apply_path and the HTTP /admission/apply endpoint.
    Validates the settings against the LIVE store first, so an invalid
    combination denies before any provisioner/template is committed."""
    if settings_store is not None and overrides:
        admit_settings(replace(settings_store.current, **overrides))
    if state is not None:
        for prov in provisioners:
            state.apply_provisioner(prov)
        state.apply_storage_batch(storage)
    if cloud is not None and hasattr(cloud, "templates"):
        for t in templates:
            cloud.templates[t.name] = t
    if settings_store is not None and overrides:
        settings_store.update(**overrides)


def apply_path(path, *, state=None, cloud=None, settings_store=None):
    """Load manifests from ``path`` and apply the admitted objects to a
    running operator's state/cloud/settings.  Returns the admitted tuple."""
    provisioners, templates, overrides, storage = admit_documents(
        load_documents(path),
        current_settings=settings_store.current if settings_store else None,
    )
    apply_objects(provisioners, templates, overrides, storage,
                  state=state, cloud=cloud, settings_store=settings_store)
    return provisioners, templates, overrides, storage
