"""Generic request batching with idle/max windows.

Two batching layers mirror the reference:

1. ``Window`` — the provisioning pod batcher (idle 1s / max 10s,
   concepts/settings.md:41-47): accumulate items until the stream goes idle
   or the max window expires.
2. ``Coalescer`` — pkg/batcher/batcher.go:29-171 semantics: hash-bucketed
   request coalescing for cloud API calls (CreateFleet fan-out,
   DescribeInstances merge); concurrent identical requests share one backend
   call.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Generic, Hashable, List, Optional, TypeVar

from .utils.clock import Clock

T = TypeVar("T")
U = TypeVar("U")

DEFAULT_IDLE_SECONDS = 1.0
DEFAULT_MAX_SECONDS = 10.0


class Window(Generic[T]):
    """Idle/max-duration batching window."""

    def __init__(
        self,
        idle_seconds: float = DEFAULT_IDLE_SECONDS,
        max_seconds: float = DEFAULT_MAX_SECONDS,
        clock: Optional[Clock] = None,
    ) -> None:
        self.idle = idle_seconds
        self.max = max_seconds
        self.clock = clock or Clock()
        self._items: List[T] = []
        self._first_at: Optional[float] = None
        self._last_at: Optional[float] = None

    def add(self, item: T) -> None:
        now = self.clock.now()
        if self._first_at is None:
            self._first_at = now
        self._last_at = now
        self._items.append(item)

    def __len__(self) -> int:
        return len(self._items)

    @property
    def opened_at(self) -> Optional[float]:
        """When the first item of the current batch arrived (None while
        empty) — the start of the trace's "window" span: time pods spent
        waiting for the idle/max batching window to fire is part of their
        caller-visible scheduling latency."""
        return self._first_at

    def ready(self) -> bool:
        if not self._items:
            return False
        now = self.clock.now()
        if now - self._first_at >= self.max:
            return True
        return now - self._last_at >= self.idle

    def pop(self) -> List[T]:
        items, self._items = self._items, []
        self._first_at = self._last_at = None
        return items


class InflightQueue(Generic[T]):
    """Bounded FIFO of in-flight async work — the double-buffer behind the
    solver's pipelined dispatch (service/server.py SolvePipeline).

    ``push(item)`` appends and returns the items evicted past ``depth``
    (oldest first) for the caller to finalize; ``pop_to(target)`` pops down
    to ``target`` for idle drains.  Finalization itself stays with the
    caller — this class only owns the ordering and the depth bound, so a
    finalizer that blocks (a device fence) never runs under any lock here.
    ``on_depth`` fires with the new depth after every change (metrics
    gauge hook).  Single-producer: the pipeline's dispatcher thread.
    """

    def __init__(self, depth: int = 2,
                 on_depth: Optional[Callable[[int], None]] = None) -> None:
        self.depth = max(1, depth)
        self._q: "deque[T]" = deque()
        self._on_depth = on_depth

    def __len__(self) -> int:
        return len(self._q)

    def _notify(self) -> None:
        if self._on_depth is not None:
            self._on_depth(len(self._q))

    def push(self, item: T) -> List[T]:
        self._q.append(item)
        evicted: List[T] = []
        while len(self._q) > self.depth:
            try:
                evicted.append(self._q.popleft())
            except IndexError:  # lost a pop race (see pop_to); len was stale
                break
        self._notify()
        return evicted

    def pop_to(self, target: int = 0) -> List[T]:
        # len-check-then-popleft is not atomic, and the shutdown path runs
        # pop_to concurrently with a merely-slow (not wedged) dispatcher's
        # own drains (SolvePipeline.stop after its join times out).  Each
        # popleft is itself thread-safe; absorb losing the race so the
        # caller's remaining drains still run.
        out: List[T] = []
        while len(self._q) > target:
            try:
                out.append(self._q.popleft())
            except IndexError:
                break  # the racer got it; its owner resolves it
        if out:
            self._notify()
        return out


class SlotCoalescer(Generic[T]):
    """Deadline-aware request-slot coalescer — the continuous-batching front
    of the solver's cross-request megabatch path (service/server.py
    SolvePipeline drives it between the RPC queue and the device dispatch).

    Items arrive tagged with a *bucket key* (the megabatch compile-signature
    bucket; ``None`` = cannot ride a megabatch).  The key is opaque here,
    but by contract it carries everything that picks the compiled program —
    including the scheduler's MESH signature (``TpuSolver.mega_signature``):
    a meshed scheduler's sharded flushes and a single-device scheduler's
    flushes are different buckets, so requests against different device
    layouts can never coalesce into one dispatch.  Consecutive same-key
    items accumulate into one batch of up to ``max_slots``; a batch flushes
    when

    - **full** — it reached ``max_slots``,
    - **bucket** — an arriving item carries a different (or None) key,
    - **deadline** — its oldest item has waited ``max_wait`` seconds
      (``poll``/``flush``, clocked through the injectable Clock so
      FakeClock tests are deterministic).

    **Mixed-bucket unification** (ISSUE 14): an optional ``unify(held_key,
    new_key)`` hook — the scheduler's ``unify_buckets`` — may return a
    MERGED key instead of None when the two compile buckets can share one
    program (one's dims dominate the other's); the arriving item then
    JOINS the held batch under the merged key instead of forcing a
    "bucket" flush, so a host-major mesh dispatch serves both shapes in
    one flush instead of two serial ones.  ``on_unify`` fires per
    unification (metrics hook).  Slot packing stays host-major-contiguous
    by construction: items keep arrival order and the dispatch pads at
    the END, so a partially-full flush lights whole hosts first.

    Single-threaded by contract: the pipeline's dispatcher thread owns it,
    exactly like ``InflightQueue``'s producer side.  The coalescer never
    executes anything — it only decides batch boundaries; the caller
    dispatches and observes the flush metrics."""

    def __init__(
        self,
        max_slots: int = 8,
        max_wait: float = 0.0,
        clock: Optional[Clock] = None,
        unify: Optional[Callable[[Hashable, Hashable],
                                 Optional[Hashable]]] = None,
        on_unify: Optional[Callable[[], None]] = None,
    ) -> None:
        self.max_slots = max(1, max_slots)
        self.max_wait = max(0.0, max_wait)
        self.clock = clock or Clock()
        self.unify = unify
        self.on_unify = on_unify
        self._key: Optional[Hashable] = None
        self._items: List[T] = []
        self._first_at: Optional[float] = None

    def __len__(self) -> int:
        return len(self._items)

    @property
    def key(self) -> Optional[Hashable]:
        return self._key

    def deadline(self) -> Optional[float]:
        """Absolute clock time at which the held batch must flush (None
        while empty) — the dispatcher bounds its queue-poll timeout by it."""
        if not self._items:
            return None
        return self._first_at + self.max_wait

    def _take(self) -> List[T]:
        items, self._items = self._items, []
        self._key = None
        self._first_at = None
        return items

    def add(self, key: Optional[Hashable], item: T):
        """Admit one item; returns the list of ``(reason, key, items)``
        batches this admission flushed, oldest first.  A ``None`` key first
        flushes the held batch (bucket change), then flushes the item alone
        — unbatchable requests never wait behind a deadline.  A different
        non-None key first consults ``unify``: a merged key re-keys the
        held batch and the item joins it (no flush)."""
        out = []
        if self._items and (key is None or key != self._key):
            merged = None
            if key is not None and self.unify is not None:
                # the hook is a scheduler contract, but a facade's probe
                # must never fail the dispatcher (the _bucket_of idiom)
                try:
                    merged = self.unify(self._key, key)
                # ktlint: allow[KT005] unification is an optimization —
                # a failing hook just keeps the two-flush path
                except Exception:
                    merged = None
            if merged is not None:
                self._key = merged
                if self.on_unify is not None:
                    self.on_unify()
            else:
                out.append(("bucket", self._key, self._take()))
        if key is None:
            out.append(("bucket", None, [item]))
            return out
        if not self._items:
            self._key = key
            self._first_at = self.clock.now()
        self._items.append(item)
        if len(self._items) >= self.max_slots:
            out.append(("full", self._key, self._take()))
        return out

    def poll(self):
        """Deadline check — call when the inbound queue goes idle; returns
        the expired batch as ``[(\"deadline\", key, items)]`` or ``[]``."""
        if self._items and self.clock.now() >= self._first_at + self.max_wait:
            return [("deadline", self._key, self._take())]
        return []

    def flush(self, reason: str = "deadline"):
        """Unconditional flush of whatever is held (queue-idle fast path
        when no max-wait is configured, and the shutdown drain)."""
        if not self._items:
            return []
        return [(reason, self._key, self._take())]


@dataclass
class _Bucket(Generic[T, U]):
    requests: List[T] = field(default_factory=list)
    results: List[U] = field(default_factory=list)


class _Batch:
    __slots__ = ("reqs", "event", "results")

    def __init__(self) -> None:
        self.reqs: List[object] = []
        self.event = threading.Event()
        self.results = None  # List[("ok", value) | ("err", exception)]


class CoalescerTimeout(RuntimeError):
    """A follower waited past ``follower_timeout`` for its batch leader to
    publish results — the leader thread likely died between registering the
    bucket and setting the event.  The request outcome is UNKNOWN: if the
    leader was merely stalled, the batched call may still execute."""


class ThreadCoalescer:
    """Coalescer for *concurrent* callers (batcher.go:130-151 semantics with
    goroutines mapped to threads): the first requester of a bucket becomes
    the leader, sleeps the idle window while peers join, then executes once
    and publishes per-request outcomes.  Used at the cloud boundary by
    ``cloud.batched.BatchedCloud``; the synchronous ``Coalescer`` above
    covers single-threaded accumulate-then-flush callers."""

    #: generous bound on how long a follower will wait for its leader; the
    #: backend call itself is bounded well under this, so expiry means the
    #: leader died (async exception / interpreter shutdown), not a slow call
    FOLLOWER_TIMEOUT = 120.0

    def __init__(
        self,
        execute: Callable[[List[object]], List[tuple]],
        idle_seconds: float = 0.002,
        follower_timeout: float = FOLLOWER_TIMEOUT,
    ) -> None:
        self.execute = execute
        self.idle = idle_seconds
        self.follower_timeout = follower_timeout
        self._lock = threading.Lock()
        self._buckets: Dict[Hashable, _Batch] = {}  # guarded-by: _lock
        self.batch_count = 0                        # guarded-by: _lock  backend round trips
        self.requests_served = 0                    # guarded-by: _lock  total requests across batches
        self.batch_sizes = deque(maxlen=128)        # guarded-by: _lock  recent batch sizes

    def call(self, key: Hashable, req: object):
        with self._lock:
            batch = self._buckets.get(key)
            leader = batch is None
            if leader:
                batch = _Batch()
                self._buckets[key] = batch
            idx = len(batch.reqs)
            batch.reqs.append(req)
        if leader:
            if self.idle > 0:
                time.sleep(self.idle)
            with self._lock:
                # late joiners after this point start a fresh bucket
                self._buckets.pop(key, None)
                reqs = list(batch.reqs)
            try:
                outcomes = self.execute(reqs)
            # ktlint: allow[KT005] leader publishes the failure to every
            # follower as its per-request outcome; each caller re-raises
            except Exception as err:  # backend-wide failure fans out to all
                outcomes = [("err", err)] * len(reqs)
            batch.results = outcomes
            with self._lock:  # concurrent leaders of other buckets also count
                self.batch_count += 1
                self.requests_served += len(reqs)
                self.batch_sizes.append(len(reqs))
            batch.event.set()
        else:
            # measured beyond the leader's idle-window sleep, so a live leader
            # still collecting joiners can never be mistaken for a dead one
            if not batch.event.wait(self.idle + self.follower_timeout):
                with self._lock:
                    # unregister the dead batch (if still current) so the next
                    # caller can become a fresh leader instead of every future
                    # call for this key stalling on the same corpse
                    if self._buckets.get(key) is batch:
                        del self._buckets[key]
                raise CoalescerTimeout(
                    f"batch leader for bucket {key!r} did not publish results "
                    f"within {self.idle + self.follower_timeout:.0f}s; request "
                    "outcome unknown (it may still execute if the leader was "
                    "only stalled)"
                )
        kind, val = batch.results[idx]
        if kind == "err":
            raise val
        return val


class Coalescer(Generic[T, U]):
    """Coalesce identical requests into one backend call.

    ``execute(reqs) -> results`` is invoked once per distinct hash bucket per
    flush; each caller gets its own result (fan-out), mirroring
    batcher.go:130-151's one-call-per-bucket with per-requester responses.
    """

    def __init__(
        self,
        hasher: Callable[[T], Hashable],
        execute: Callable[[List[T]], List[U]],
    ) -> None:
        self.hasher = hasher
        self.execute = execute
        self._buckets: Dict[Hashable, List[T]] = {}

    def add(self, request: T) -> Hashable:
        key = self.hasher(request)
        self._buckets.setdefault(key, []).append(request)
        return key

    def flush(self) -> Dict[Hashable, List[U]]:
        out: Dict[Hashable, List[U]] = {}
        for key, reqs in self._buckets.items():
            out[key] = self.execute(reqs)
        self._buckets.clear()
        return out
