"""Million-pod hierarchical solving: block decomposition + dual reconciliation.

One flat (pods x types x domains) program holds 50k pods at 24 ms
(docs/BENCH_RESULTS r05) but the next order of magnitude does not fit one
scan.  This module decomposes the batch the way CvxCluster decomposes its
clustering objective (PAPERS.md: "100-1000x faster via decomposition"):

1. **Partition** — union-find over the coupling guard's constraint
   reachability (the PR-6 warm-start index: a selector slot couples every
   group that CARRIES a hard constraint watching it with every group the
   selector MATCHES).  Namespace/selector-disjoint groups never share a
   component, so they can solve independently; a component is never split
   across blocks (fuzz-asserted).  Components are LPT-packed by pod count
   into at most ``MEGA_MAX_SLOTS`` blocks.

2. **Block solve** — every block is one slot of ONE vmapped megabatch
   dispatch (``solve_many_prepared``): the shared catalog tensors are built
   once (``_host_arrays`` base) and broadcast across slots by the
   dispatcher's ``_stack``; a block differs only by its masked counts
   vector, its suffix backfill projection, and its node budget.  One device
   round trip solves every block.

3. **Price loop** — blocks contend for shared capacity (provisioner
   limits).  A fixed-iteration dual ascent on the relax rung's
   mirror-descent schedule (``relax.mirror_eta``) prices over-subscribed
   provisioners up multiplicatively; contending blocks re-solve against the
   price-adjusted candidate costs — again ONE dispatch per wave — until
   either no limit is violated or the ``KT_HIER_PRICE_ITERS`` budget
   expires.  Fixed-iteration duals (not a global LP): every wave is the
   same compiled program at the same signature, the wall-clock budget is a
   hard constant, and an imperfect price equilibrium is repaired exactly in
   step 4 — an LP would give exact prices for a relaxation we round anyway.

4. **Repair** — the host enforces limits exactly (evicting the most
   expensive nodes of any still-over provisioner) and re-seats stragglers
   (evicted pods + block-infeasible pods) through the PR-6 warm-start path
   (``warmstart.delta_solve``): first-fit into the merged solution's
   residual capacity, flat re-solve against the kept nodes for the rest.
   A cross-block tail pass then evicts each block's most underfull node
   (every block rounds its own tail up to a whole node — the one cost flat
   pays nowhere) and re-seats those pods jointly through the same path;
   the cheaper of before/after ships, so repair is never-worse by select.

The per-wave hot path runs PACKED: feasibility as int8 and prices as bf16
(``models/tensorize.pack_feasibility``/``pack_scores`` — ~4x fewer HBM
bytes than the float32 layout the relax rung materializes), scored either
by a lax program or a hand-written Pallas kernel behind ``KT_PALLAS``
(interpreted on CPU for tier-1, real lowering on device) with byte-parity
between the two.

Import-light by design: no jax at module import — the partition, the LPT
packer and the scale model are pure numpy/stdlib so
``scripts/profile_solve.py --hier`` can time them without a backend.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..metrics import (
    HIER_BLOCKS,
    HIER_DURATION,
    HIER_PATHS,
    HIER_PRICE_ITERATIONS,
    HIER_REPAIR_PODS,
    HIER_SOLVES,
    Registry,
)
from ..gang import gang_enabled
from ..obs.trace import NULL_TRACE
from .types import SimNode, SolveResult

logger = logging.getLogger(__name__)

#: infeasible-cost sentinel, shared with the scan program's padding value
_BIG = float(np.float32(3.0e38))

DEFAULT_HIER_THRESHOLD = 100_000
DEFAULT_PRICE_ITERS = 4

#: the flat device reference point the dev-host scale model extrapolates
#: from when no device measurement is supplied: 50k pods in 24 ms
#: (docs/BENCH_RESULTS r05, config 2 steady-state)
DEVICE_REF_PODS = 50_000
DEVICE_REF_MS = 24.0


def hier_threshold() -> int:
    """Pod count at/above which the scheduler routes hierarchically
    (default 100k; 0 disables the hierarchical path entirely).  Read
    through the knob registry (ISSUE 19): a tuned override wins, else
    the registry falls back to ``KT_HIER_THRESHOLD``/the default at
    call time — env workflows are untouched until something moves the
    knob."""
    from ..tuning.knobs import global_knobs

    try:
        return int(global_knobs().get("hier_threshold"))
    except (TypeError, ValueError):
        return DEFAULT_HIER_THRESHOLD


def hier_price_iters() -> int:
    """Fixed price-ascent wave budget (``KT_HIER_PRICE_ITERS``)."""
    try:
        return max(0, int(os.environ.get("KT_HIER_PRICE_ITERS",
                                         DEFAULT_PRICE_ITERS)))
    except ValueError:
        return DEFAULT_PRICE_ITERS


def pallas_enabled() -> bool:
    """Whether the packed score kernel runs the Pallas program
    (``KT_PALLAS=1``; default = the lax program, byte-identical)."""
    return os.environ.get("KT_PALLAS", "0") == "1"


def zero_init_hier_metrics(registry: Registry) -> None:
    """Register the hierarchical series at 0 (KT003)."""
    for path in HIER_PATHS:
        if not registry.counter(HIER_SOLVES).has({"path": path}):
            registry.counter(HIER_SOLVES).inc({"path": path}, value=0.0)
    registry.histogram(HIER_BLOCKS)
    registry.histogram(HIER_PRICE_ITERATIONS)
    registry.histogram(HIER_REPAIR_PODS)
    registry.histogram(HIER_DURATION)


# ---------------------------------------------------------------------------
# partition: constraint-reachability components -> LPT blocks
# ---------------------------------------------------------------------------


class _UnionFind:
    def __init__(self, n: int) -> None:
        self.parent = list(range(n))

    def find(self, x: int) -> int:
        p = self.parent
        while p[x] != x:
            p[x] = p[p[x]]
            x = p[x]
        return x

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[rb] = ra


def coupling_components(st) -> List[List[int]]:
    """Connected components of the group-coupling graph, in first-group
    order.  Two groups couple iff some selector slot reaches both: a slot
    ``sid`` connects every group whose hard constraint CARRIES it (zone/
    host spread, anti-affinity, zone/host pod affinity — the same slot-id
    tensors the scan consumes) with every group the selector MATCHES
    (``g_sel_match`` — the coupling guard's reachability, exactly what the
    PR-6 warm-start displacement index walks).  Groups in different
    components share no constraint that could observe each other's
    placements, so their solves commute."""
    G = st.G
    uf = _UnionFind(G)
    S = st.S
    if S:
        sel_match = np.asarray(st.g_sel_match)  # [S, G]
        reach: List[List[int]] = [[] for _ in range(S)]
        for arr in (st.g_zone_spread, st.g_host_spread, st.g_zone_anti,
                    st.g_zone_paff, st.g_host_paff):
            a = np.asarray(arr)
            for gi in np.nonzero(a >= 0)[0]:
                reach[int(a[gi])].append(int(gi))
        for sid in range(S):
            members = set(reach[sid])
            members.update(int(g) for g in np.nonzero(sel_match[sid])[0])
            it = iter(sorted(members))
            first = next(it, None)
            if first is None:
                continue
            for g in it:
                uf.union(first, g)
    # gang never-split (ISSUE 20): groups carrying the same gang tag join
    # one component — the partition must hand an entire gang to one block,
    # or the per-block solves could each place a legal-looking fragment
    # the all-or-nothing epilogue would then have to retract whole
    g_gang = np.asarray(getattr(st, "g_gang", np.zeros(0, dtype=np.int32)))
    if g_gang.size and gang_enabled():
        first_of: Dict[int, int] = {}
        for gi in np.nonzero(g_gang >= 0)[0]:
            tag = int(g_gang[gi])
            anchor = first_of.setdefault(tag, int(gi))
            if anchor != int(gi):
                uf.union(anchor, int(gi))
    comps: Dict[int, List[int]] = {}
    for gi in range(G):
        comps.setdefault(uf.find(gi), []).append(gi)
    return sorted(comps.values(), key=lambda c: c[0])


def partition_blocks(
    st, components: Sequence[Sequence[int]], max_blocks: int,
) -> List[np.ndarray]:
    """LPT-pack components (weight = pod count) into at most ``max_blocks``
    bins; returns one boolean group mask ``[G]`` per non-empty block.  A
    component is NEVER split — the invariant the fuzz harness asserts."""
    counts = np.asarray(st.counts)
    B = max(1, min(int(max_blocks), len(components)))
    weights = [(int(sum(counts[g] for g in comp)), ci)
               for ci, comp in enumerate(components)]
    weights.sort(key=lambda t: (-t[0], t[1]))
    loads = [0] * B
    bins: List[List[int]] = [[] for _ in range(B)]
    for w, ci in weights:
        b = min(range(B), key=lambda i: (loads[i], i))
        loads[b] += w
        bins[b].append(ci)
    masks: List[np.ndarray] = []
    for b in range(B):
        if not bins[b]:
            continue
        mask = np.zeros(st.G, dtype=bool)
        for ci in bins[b]:
            for gi in components[ci]:
                mask[gi] = True
        masks.append(mask)
    return masks


def block_budgets(st, masks: Sequence[np.ndarray]) -> List[int]:
    """Per-block node budget: the block's pod count — the exact worst case
    (one node per pod), so a block solve can never hit slot exhaustion and
    the no-retry (``full_nr``) megabatch contract holds."""
    counts = np.asarray(st.counts)
    return [max(1, int(counts[m].sum())) for m in masks]


# ---------------------------------------------------------------------------
# block entries: one shared base build, per-block masked counts
# ---------------------------------------------------------------------------


def hier_dims(st, node_budget: int) -> dict:
    """Shared dims bucket for every block slot: the standard
    :func:`tpu.solve_dims` bucketing at the WORST block's node budget with
    the full-NR axis (no per-slot exhaustion retry)."""
    from .tpu import solve_dims

    return solve_dims(st, NE=0, node_budget=node_budget, track=True,
                      full_nr=True)


def hier_signature(st, dims: dict, slots: int, mesh=None) -> tuple:
    """Compile signature of the block wave's program.  The blocks ride the
    SAME megabatch program the consolidation sweep compiles (dims + slot
    rung + vocab tail), so the signature IS the dispatch's mega key —
    readiness earned by either caller serves both."""
    from .consolidation import sweep_signature

    return sweep_signature(st, dims, slots, mesh)


def build_block_entries(
    solver,
    st,
    masks: Sequence[np.ndarray],
    budgets: Sequence[int],
    dims: dict,
    *,
    base=None,
    cand_price: Optional[np.ndarray] = None,
    trace=None,
) -> Tuple[List[dict], tuple]:
    """One megabatch entry per block from ONE shared base build.  A block
    differs from the base only by (a) its counts vector masked to member
    groups, (b) the matching per-zone suffix backfill projection, (c) its
    node budget, and — on price waves — (d) the dual-adjusted candidate
    prices.  Everything else (catalog, feasibility inputs, init state) is
    the SAME array object across entries, which the dispatcher's ``_stack``
    broadcasts instead of copying."""
    from .tpu import suffix_projection, zone_share_matrix

    if base is None:
        base = solver._host_arrays(
            st, (), node_budget=max(budgets), track_assignments=True,
            full_nr=True, dims=dims,
        )
    np_consts0, feas0, np_init0, _ = base
    pad_g = dims["G"] - st.G
    Z = dims["Z"]
    np_requests = np_consts0["requests"]
    zone_share = zone_share_matrix(st, pad_g, Z)
    counts_full = np.asarray(st.counts)

    entries: List[dict] = []
    for mask, budget in zip(masks, budgets):
        counts = np.pad(counts_full * mask, (0, pad_g), constant_values=0)
        demand = (counts[:, None] * np_requests).astype(np.float32)
        demand_z = demand[:, None, :] * zone_share[:, :, None]
        count_z = counts[:, None].astype(np.float32) * zone_share
        suffix_res, suffix_cnt = suffix_projection(demand_z, count_z)
        consts = dict(np_consts0, counts=counts, suffix_res=suffix_res,
                      suffix_cnt=suffix_cnt,
                      node_budget=np.int32(budget))
        if cand_price is not None:
            consts["cand_price"] = cand_price
        entries.append(dict(
            r=dict(st=st, existing_nodes=(), max_nodes=int(budget),
                   track_assignments=True, raise_on_exhaust=False,
                   trace=trace or NULL_TRACE),
            np_consts=consts, feas=feas0, np_init=np_init0, dims=dims,
            est_dims=dims, full_dims=dims, full_nr=True, NE=0,
        ))
    return entries, base


def warm_hier(solver, entries: List[dict], slots: int, sig: tuple,
              mesh=None) -> None:
    """Background-compile the block wave's program (compile-behind: the
    serving path falls back to flat while XLA works).  Same thunk shape as
    the consolidation sweep's warm — it IS the same program."""
    from .consolidation import _warm_sweep

    _warm_sweep(solver, entries, slots, sig, mesh=mesh)


# ---------------------------------------------------------------------------
# packed feasibility+score hot path (int8 / bf16; lax or Pallas)
# ---------------------------------------------------------------------------

_PROGRAMS: Dict[object, object] = {}


def _lax_score():
    """The lax reference program: cheapest feasible candidate per group
    over int8 feasibility and bf16 prices (upcast to f32 for compare —
    exactly what the Pallas kernel does, so parity is bit-for-bit)."""
    prog = _PROGRAMS.get("lax")
    if prog is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def run(f_i8, price):  # ktlint: allow[KT008] memoized once in _PROGRAMS — wrapper and compile cache created on first call, reused after

            cost = jnp.where(f_i8 > 0,
                             price.astype(jnp.float32)[None, :], _BIG)
            return (jnp.min(cost, axis=1),
                    jnp.argmin(cost, axis=1).astype(jnp.int32))

        prog = _PROGRAMS["lax"] = run
    return prog


#: Pallas tile: int8 feasibility wants (32, 128) native tiles on TPU
#: (pallas guide); the wrapper pads G/C up to multiples
_TILE_G = 32
_TILE_C = 128


def _pallas_score(Gp: int, Cp: int):
    """Hand-written Pallas kernel for the packed score reduction.  Grid
    over row tiles; the price row is broadcast to every tile.  Argmin is
    expressed as min-over-matching-column-index (first-minimum tie-break,
    identical to ``jnp.argmin``).  Interpreted off-TPU (tier-1 runs it on
    CPU), real Mosaic lowering on device."""
    key = ("pallas", Gp, Cp)
    prog = _PROGRAMS.get(key)
    if prog is not None:
        return prog
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(f_ref, p_ref, cost_ref, idx_ref):
        f = f_ref[...]
        p = p_ref[...].astype(jnp.float32)          # [1, Cp]
        cost = jnp.where(f > 0, jnp.broadcast_to(p, f.shape), _BIG)
        best = jnp.min(cost, axis=1, keepdims=True)
        col = jax.lax.broadcasted_iota(jnp.int32, cost.shape, 1)
        hit = jnp.where(cost == best, col, Cp)
        cost_ref[...] = best
        idx_ref[...] = jnp.min(hit, axis=1, keepdims=True).astype(jnp.int32)

    call = pl.pallas_call(
        kernel,
        grid=(Gp // _TILE_G,),
        in_specs=[
            pl.BlockSpec((_TILE_G, Cp), lambda i: (i, 0)),
            pl.BlockSpec((1, Cp), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_TILE_G, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_G, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Gp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Gp, 1), jnp.int32),
        ],
        interpret=jax.default_backend() != "tpu",
    )
    # ktlint: allow[KT008] memoized per (Gp, Cp) in _PROGRAMS — one
    # wrapper per padded shape, created once and reused
    prog = _PROGRAMS[key] = jax.jit(call)
    return prog


def packed_scan_scores(
    f_packed: np.ndarray,
    price_packed: np.ndarray,
    use_pallas: Optional[bool] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """``(best_cost[G] f32, best_idx[G] i32)`` — cheapest feasible
    candidate per group from PACKED inputs (int8 feasibility, bf16
    prices).  ``use_pallas`` overrides ``KT_PALLAS`` (the parity harness
    runs both); all-infeasible rows return (``3.0e38``, 0) on either
    path."""
    G, C = f_packed.shape
    if use_pallas is None:
        use_pallas = pallas_enabled()
    if not use_pallas:
        cost, idx = _lax_score()(f_packed, price_packed)
        return np.asarray(cost), np.asarray(idx)
    Gp = -(-G // _TILE_G) * _TILE_G
    Cp = -(-C // _TILE_C) * _TILE_C
    f = np.zeros((Gp, Cp), dtype=np.int8)
    f[:G, :C] = f_packed
    p = np.zeros((1, Cp), dtype=price_packed.dtype)
    p[0, :C] = price_packed
    cost, idx = _pallas_score(Gp, Cp)(f, p)
    return np.asarray(cost)[:G, 0], np.asarray(idx)[:G, 0]


# ---------------------------------------------------------------------------
# price loop helpers (host-side dual bookkeeping)
# ---------------------------------------------------------------------------


def _prov_usage(st, nodes: Sequence[SimNode], P: int) -> np.ndarray:
    """[P, R] capacity bought per provisioner (the creation-time limit
    accounting rule: ``capacity_row``)."""
    R = st.R
    usage = np.zeros((P, R), dtype=np.float64)
    index = {name: i for i, name in enumerate(st.prov_names)}
    for n in nodes:
        pi = index.get(n.provisioner)
        if pi is not None:
            usage[pi] += st.capacity_row(n.instance_type, n.allocatable)
    return usage


def _limit_violation(usage: np.ndarray, limits: np.ndarray) -> np.ndarray:
    """[P] worst usage/limit ratio over FINITE limit resources (1.0 = at
    the limit; the 3.0e38 padding sentinel counts as unlimited)."""
    finite = limits < 1e37
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(finite, usage / np.maximum(limits, 1e-9), 0.0)
    return ratio.max(axis=1) if ratio.size else np.zeros(usage.shape[0])


def price_adjusted(cand_price: np.ndarray, cand_prov: np.ndarray,
                   lam: np.ndarray) -> np.ndarray:
    """Candidate prices under duals ``lam[P]``: multiply by ``exp(lam)`` of
    the owning provisioner, leaving the 3.0e38/inf no-offering sentinels
    alone (a float32 multiply past 1e38 overflows to inf and would change
    the padding the compiled program was built against).  ``cand_price``
    is the solver's ``[C, D]`` per-domain layout (or any array whose
    leading axis is candidates) — the multiplier broadcasts across the
    trailing axes."""
    base = np.asarray(cand_price, dtype=np.float32)
    m = np.exp(lam).astype(np.float32)[np.asarray(cand_prov)]
    m = m.reshape(m.shape + (1,) * (base.ndim - 1))
    with np.errstate(over="ignore"):  # sentinel rows overflow, then drop
        return np.where(base >= 1e37, base, base * m).astype(np.float32)


#: a block tail node below this peak-resource fill is a candidate for the
#: cross-block repack — fuller nodes have nothing left to merge
_TAIL_FILL = 0.9


def _node_fill(n: SimNode) -> float:
    """Peak fill fraction across resources (1.0 = some resource full)."""
    fill = 0.0
    alloc = n.allocatable
    for k, v in n.used().items():
        cap = alloc.get(k, 0.0)
        if cap > 0.0:
            fill = max(fill, v / cap)
    return fill


# ---------------------------------------------------------------------------
# the hierarchical solve
# ---------------------------------------------------------------------------


def _record(registry, path: str) -> None:
    registry.counter(HIER_SOLVES).inc({"path": path})


def solve_hierarchical(
    scheduler,
    pods,
    provisioners,
    instance_types,
    daemonsets=(),
    unavailable=None,
    trace=None,
    registry: Optional[Registry] = None,
    stats: Optional[dict] = None,
) -> Optional[SolveResult]:
    """Partition -> one-dispatch block waves -> price ascent -> repair.
    Returns ``None`` when flat is the right (or only warm) program — the
    scheduler falls through to ``_solve_tpu``; the metrics label says why.
    ``stats``, when given, receives per-stage timings and dispatch counts
    (the bench gate asserts exactly ONE dispatch per block wave).

    Re-entrancy: repair re-seats stragglers through ``scheduler._solve_once``
    — if that inner solve routed hierarchically again (a straggler batch at/
    above ``KT_HIER_THRESHOLD``), repair would recurse without bound.  The
    depth counter pins every nested solve to the flat path
    (``_route_hier`` checks it)."""
    scheduler._hier_depth = getattr(scheduler, "_hier_depth", 0) + 1
    try:
        return _solve_hierarchical(
            scheduler, pods, provisioners, instance_types,
            daemonsets=daemonsets, unavailable=unavailable, trace=trace,
            registry=registry, stats=stats,
        )
    finally:
        scheduler._hier_depth -= 1


def _solve_hierarchical(
    scheduler,
    pods,
    provisioners,
    instance_types,
    daemonsets=(),
    unavailable=None,
    trace=None,
    registry: Optional[Registry] = None,
    stats: Optional[dict] = None,
) -> Optional[SolveResult]:
    t0 = time.perf_counter()
    registry = registry or scheduler.registry
    zero_init_hier_metrics(registry)
    trace = trace or NULL_TRACE
    st_out = stats if stats is not None else {}

    st, tensorize_s = scheduler._tensorize(
        pods, provisioners, instance_types, daemonsets, unavailable,
        trace=trace,
    )
    t_part0 = time.perf_counter()
    comps = coupling_components(st)
    from .tpu import MEGA_MAX_SLOTS, max_mega_slots

    max_blocks = (MEGA_MAX_SLOTS if scheduler.mesh is None
                  else max_mega_slots(scheduler.mesh))
    if len(comps) < 2 or max_blocks < 2:
        _record(registry, "fallback_structure")
        return None
    masks = partition_blocks(st, comps, max_blocks)
    if len(masks) < 2:
        _record(registry, "fallback_structure")
        return None
    budgets = block_budgets(st, masks)
    partition_ms = (time.perf_counter() - t_part0) * 1000.0

    # ---- entries + compile gating --------------------------------------
    t_ent0 = time.perf_counter()
    solver = scheduler._tpu
    mesh = scheduler.mesh
    dims = hier_dims(st, max(budgets))
    slots0 = len(masks)
    sig = hier_signature(st, dims, slots0, mesh)
    entries, base = build_block_entries(
        solver, st, masks, budgets, dims, trace=trace)
    entries_ms = (time.perf_counter() - t_ent0) * 1000.0
    if scheduler.compile_behind and not solver.ready(sig):
        if not solver.warm_pending(sig):
            warm_hier(solver, entries, slots0, sig, mesh=mesh)
        _record(registry, "fallback_cold")
        return None

    # ---- block waves ----------------------------------------------------
    guard = scheduler._guard
    price_budget = hier_price_iters()
    wave_frac = 1.0 / (1.0 + price_budget)
    dispatches = 0
    wave_ms: List[float] = []

    def wave(wave_entries):
        nonlocal dispatches
        tw = time.perf_counter()

        def call():
            pending = solver.solve_many_prepared(
                wave_entries, min_slots=slots0, mesh=mesh,
                registry=registry)
            return pending.results()

        outs = (guard.run_budgeted(call, budget_frac=wave_frac)
                if guard.enabled else call())
        dispatches += 1
        wave_ms.append((time.perf_counter() - tw) * 1000.0)
        for o in outs:
            if isinstance(o, Exception):
                raise o
        return outs

    from .guard import DeviceHang

    P = len(st.prov_names)
    limits = np.asarray(st.prov_limits, dtype=np.float64)
    iters_run = 0
    try:
        outs = wave(entries)

        # ---- price ascent (fixed budget, mirror-descent schedule) ------
        from ..models.tensorize import pack_feasibility, pack_scores
        from .relax import _host_feasibility, mirror_eta

        lam = np.zeros(P, dtype=np.float64)
        f_packed: Optional[np.ndarray] = None
        # ktlint: allow[KT020] price waves are sequentially dependent —
        # each dual update needs the PREVIOUS wave's usage; every wave is
        # still ONE vmapped dispatch over all contending blocks
        for t in range(price_budget):
            usage = np.zeros((len(masks), P, st.R), dtype=np.float64)
            for bi, out in enumerate(outs):
                usage[bi] = _prov_usage(st, out.result.nodes, P)
            v = _limit_violation(usage.sum(axis=0), limits)
            hot = v > 1.0 + 1e-6
            if not hot.any():
                break
            iters_run += 1
            eta = float(mirror_eta(np.float32(t)))
            lam = np.minimum(np.where(hot, lam + eta * (v - 1.0),
                                      lam * 0.5), 8.0)
            # adjust the PADDED sentinel tensor (3.0e38 rows stay put —
            # the compiled program's padding contract) and slice the real
            # candidates back out for the kernel
            adj_padded = price_adjusted(base[0]["cand_price"],
                                        base[0]["cand_prov"], lam)
            # packed hot path: which provisioner each group would buy
            # NOW, under the adjusted prices — int8 feasibility, bf16
            # prices (cheapest offering per candidate: min over the
            # domain axis; all-sentinel rows stay >= 1e37), lax or
            # Pallas per KT_PALLAS
            adj = adj_padded[:st.C].min(axis=1)
            if f_packed is None:
                f_packed = pack_feasibility(_host_feasibility(st))
            _cost, best = packed_scan_scores(f_packed, pack_scores(adj))
            want_hot = np.zeros(st.G, dtype=bool)
            if st.C:
                prov_of_best = np.asarray(st.cand_prov)[best]
                want_hot = hot[prov_of_best] & (np.asarray(_cost) < 1e37)
            contending = [
                bi for bi in range(len(masks))
                if usage[bi][hot].any() or want_hot[masks[bi]].any()
            ]
            if not contending:
                break
            sub_entries, _ = build_block_entries(
                solver, st, [masks[bi] for bi in contending],
                [budgets[bi] for bi in contending], dims, base=base,
                cand_price=adj_padded, trace=trace,
            )
            sub_outs = wave(sub_entries)
            for bi, out in zip(contending, sub_outs):
                outs[bi] = out
    except DeviceHang:
        logger.warning("hierarchical block wave hit the hang guard; "
                       "flat degradation ladder serves this batch")
        _record(registry, "fallback_degraded")
        return None
    except Exception:
        logger.warning("hierarchical wave failed; falling back to flat",
                       exc_info=True)
        _record(registry, "fallback_degraded")
        return None

    # ---- merge ----------------------------------------------------------
    t_rep0 = time.perf_counter()
    member_names: List[set] = []
    for mask in masks:
        names = set()
        for gi in np.nonzero(mask)[0]:
            names.update(p.name for p in st.groups[gi].pods)
        member_names.append(names)

    nodes: List[SimNode] = []
    assignments: Dict[str, str] = {}
    straggler_names: set = set()
    block_of: Dict[str, int] = {}  # node name -> owning block
    for bi, out in enumerate(outs):
        res = out.result
        members = member_names[bi]
        nodes.extend(res.nodes)
        for n in res.nodes:
            block_of[n.name] = bi
        for pn, nn in res.assignments.items():
            if pn in members:
                assignments[pn] = nn
        # a block's extract marks every pod of every MASKED-OUT group
        # infeasible (zero counts -> zero takes); only member infeasibility
        # is real
        straggler_names.update(pn for pn in res.infeasible if pn in members)

    # ---- exact limit enforcement + warm-start repair --------------------
    usage_all = _prov_usage(st, nodes, P)
    v = _limit_violation(usage_all, limits)
    evicted: List[SimNode] = []
    for pi in np.nonzero(v > 1.0 + 1e-6)[0]:
        prov = st.prov_names[pi]
        mine = sorted((n for n in nodes if n.provisioner == prov),
                      key=lambda n: (-n.price, n.name))
        for n in mine:
            if _limit_violation(usage_all[pi:pi + 1],
                                limits[pi:pi + 1])[0] <= 1.0 + 1e-6:
                break
            usage_all[pi] -= st.capacity_row(n.instance_type, n.allocatable)
            evicted.append(n)
    if evicted:
        gone = {id(n) for n in evicted}
        nodes = [n for n in nodes if id(n) not in gone]
        for n in evicted:
            straggler_names.update(p.name for p in n.pods)
        assignments = {pn: nn for pn, nn in assignments.items()
                       if pn not in straggler_names}

    pods_by_name = {p.name: p for p in pods}
    stragglers = [pods_by_name[pn] for pn in sorted(straggler_names)
                  if pn in pods_by_name]
    n_repair = len(stragglers)
    infeasible: Dict[str, str] = {}

    def _repair_solve(rp, existing, unav):
        return scheduler._solve_once(
            list(rp), provisioners, instance_types, list(existing),
            daemonsets, unav, True, None, trace=trace,
        )

    if stragglers:
        from .warmstart import delta_solve

        merged = SolveResult(nodes=nodes, assignments=assignments,
                             infeasible={}, existing_nodes=[])

        outcome = delta_solve(
            merged, added=stragglers,
            solve_displaced=_repair_solve, solve_full=_repair_solve,
            registry=registry, unavailable=unavailable,
        )
        repaired = outcome.result
        nodes = list(repaired.existing_nodes) + list(repaired.nodes)
        assignments = dict(repaired.assignments)
        infeasible = dict(repaired.infeasible)

    # ---- cross-block tail consolidation ---------------------------------
    # every block rounds its own tail up to a whole node — with B blocks
    # the merged solution can carry up to B underfull tails that the flat
    # program would have shared.  Evict each block's least-filled node
    # (under _TAIL_FILL peak fill), re-seat those pods jointly through the
    # same warm-start path, and ship the cheaper of before/after — the
    # select makes this pass never-worse.  delta_solve mutates its inputs,
    # so the candidate runs against copies of the kept nodes.
    n_tail = 0
    if len(masks) > 1 and nodes:
        tails: List[SimNode] = []
        by_block: Dict[int, List[SimNode]] = {}
        for n in nodes:
            bi = block_of.get(n.name)
            if bi is not None and n.pods:
                by_block.setdefault(bi, []).append(n)
        for mine in by_block.values():
            cand = min(mine, key=_node_fill)
            if _node_fill(cand) < _TAIL_FILL:
                tails.append(cand)
        # only tails that could actually co-reside merge: a tail whose
        # zone no OTHER block's tail shares has nothing to merge with —
        # evicting it would let the repair repack a single block's answer
        # and break byte-parity on fully block-disjoint batches (the
        # ISSUE gate: disjoint blocks must ship flat's exact placement)
        zone_counts: Dict[str, int] = {}
        for n in tails:
            zone_counts[n.zone] = zone_counts.get(n.zone, 0) + 1
        tails = [n for n in tails if zone_counts[n.zone] > 1]
        tail_pods = [pods_by_name[p.name] for n in tails for p in n.pods
                     if p.name in pods_by_name]
        if len(tails) > 1 and tail_pods:
            from dataclasses import replace

            from .warmstart import delta_solve

            gone = {n.name for n in tails}
            kept = [replace(n, pods=list(n.pods),
                            allocatable=dict(n.allocatable))
                    for n in nodes if n.name not in gone]
            alt = SolveResult(
                nodes=kept,
                assignments={pn: nn for pn, nn in assignments.items()
                             if nn not in gone},
                infeasible={}, existing_nodes=[])
            outcome = delta_solve(
                alt, added=tail_pods,
                solve_displaced=_repair_solve, solve_full=_repair_solve,
                registry=registry, unavailable=unavailable,
            )
            r2 = outcome.result
            nodes2 = list(r2.existing_nodes) + list(r2.nodes)
            if (not r2.infeasible
                    and sum(n.price for n in nodes2)
                    < sum(n.price for n in nodes) - 1e-9):
                n_tail = len(tail_pods)
                nodes = nodes2
                assignments = dict(r2.assignments)
    repair_ms = (time.perf_counter() - t_rep0) * 1000.0

    elapsed_ms = (time.perf_counter() - t0) * 1000.0
    registry.histogram(HIER_BLOCKS).observe(float(len(masks)))
    registry.histogram(HIER_PRICE_ITERATIONS).observe(float(iters_run))
    registry.histogram(HIER_REPAIR_PODS).observe(float(n_repair))
    registry.histogram(HIER_DURATION).observe(elapsed_ms / 1000.0)
    _record(registry, "hierarchical")
    trace.annotate(hier_blocks=len(masks), hier_price_iters=iters_run,
                   hier_repair_pods=n_repair)
    st_out.update(
        blocks=len(masks), components=len(comps), waves=1 + iters_run,
        price_iters=iters_run, dispatches=dispatches,
        repair_pods=n_repair, tail_repack_pods=n_tail,
        partition_ms=round(partition_ms, 3),
        entries_ms=round(entries_ms, 3),
        wave_ms=[round(w, 2) for w in wave_ms],
        repair_ms=round(repair_ms, 2), total_ms=round(elapsed_ms, 2),
        n_pods=len(pods),
    )
    logger.info(
        "hierarchical solve: %d pods, %d components -> %d blocks, "
        "%d price wave(s), %d repaired, %.1f ms",
        len(pods), len(comps), len(masks), iters_run, n_repair, elapsed_ms,
    )
    return SolveResult(
        nodes=nodes, assignments=assignments, infeasible=infeasible,
        existing_nodes=[], solve_ms=elapsed_ms,
        tensorize_ms=tensorize_s * 1000.0,
    )


# ---------------------------------------------------------------------------
# dev-host scale model
# ---------------------------------------------------------------------------


def scale_model(measured: dict, n_pods: int) -> dict:
    """Project the hierarchical wall at ``n_pods`` from one measured run —
    pure host math (no jax), shared by ``bench.measure_hierarchical`` and
    ``scripts/profile_solve.py --hier``.

    Stage scaling: partition/entry build and repair are host-linear in the
    pod count; a block wave is ONE vmapped dispatch whose per-slot scan
    state is the block's share ``n_pods / blocks`` (slots run data-parallel
    on device), so device wave time scales with the BLOCK size, not the
    batch — that is the whole decomposition dividend.  The device
    per-pod rate comes from ``measured['device_per_pod_us']`` when the run
    had a real device, else the BENCH r05 flat reference (50k in 24 ms)."""
    n0 = max(1, int(measured.get("n_pods", 1)))
    blocks = max(1, int(measured.get("blocks", 1)))
    waves = max(1, int(measured.get("waves", 1)))
    s = n_pods / n0
    host_ms = (float(measured.get("partition_ms", 0.0))
               + float(measured.get("entries_ms", 0.0))) * s
    per_pod_us = float(
        measured.get("device_per_pod_us")
        or DEVICE_REF_MS * 1000.0 / DEVICE_REF_PODS)
    dispatch_ms = float(measured.get("dispatch_overhead_ms", 2.0))
    wave_ms = per_pod_us * (n_pods / blocks) / 1000.0 + dispatch_ms
    repair_ms = float(measured.get("repair_ms", 0.0)) * s
    total = host_ms + waves * wave_ms + repair_ms
    return {
        "n_pods": int(n_pods), "blocks": blocks, "waves": waves,
        "host_ms": round(host_ms, 2), "wave_ms": round(wave_ms, 2),
        "repair_ms": round(repair_ms, 2), "total_ms": round(total, 2),
    }
