"""TPU batch solver — vectorized FFD bin-packing as a jitted JAX program.

This is the component BASELINE.json's north star names: karpenter-core's
``scheduling.Solve`` first-fit-decreasing loop (SURVEY.md §3.2 step 3)
re-expressed as dense tensor math so 50k pods x the full catalog solve in
milliseconds on a TPU.

Design (tpu-first, not a port of the Go loop):

- **Feasibility is tensor algebra.**  ``F[g, c] = label_ok & fit_ok & prov_ok``
  computed by packed-bitmask gathers (models/vocab.py) and broadcast resource
  compares; zone/capacity-type feasibility joins per-domain:
  ``Fd[c, d] = F[g, c] & avail[c, d] & zone_ok[g, d] & ct_ok[g, d]``.
- **The pack is a scan over pod *groups*, not pods.**  Identical pods (same
  constraints+requests) collapse into one scan step; within a step every
  placement decision is closed-form vector math over node slots:
  first-fit = prefix-sum allocation in slot-creation order
  (ops/masks.prefix_allocate), topology spread = integer water-fill over
  zones (ops/masks.water_fill), new-node selection = lexicographic argmin
  over (candidate x domain) score tensors.  No data-dependent Python control
  flow — one traced step, ``lax.scan`` over G.
- **Node state is slot-per-node.**  Preallocated arrays of NR node slots
  (existing nodes first, then creation order), so "first fit in creation
  order" is literally array order.

Known v1 semantic gaps vs the CPU oracle (solver/reference.py), accepted
within the 1.02x cost-parity budget and flagged for later rounds:
- positive pod-affinity IS solved on-device (per-group modes: co-locate with
  existing matches / seed one zone-or-node / infeasible), but only one
  positive term per topology key and only zone/hostname keys; other shapes
  are marked by tensorize and routed to the oracle by the scheduler,
- maxSkew > 1 spread is allocated by the skew-band fill (free-row-preferring
  banded leveling) instead of strict first-fit-within-band,
- in-step provisioner-limit fallback depth is 2 (bulk, tail) creation rounds
  per zone pass = 4 candidate picks; residue a deeper cascade would strand
  is re-solved by the scheduler's host-side residue-convergence waves
  (solver/scheduler.py MAX_RESIDUE_WAVES) against the accumulated state,
  matching the oracle's unbounded invalidate-and-retry.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import faults as faults_mod
from ..models import labels as L
from ..models.tensorize import NO_SELECTOR, SolveTensors
from ..obs.trace import NULL_TRACE
from ..utils.clock import Clock
from ..ops.masks import (
    BIG,
    gather_pm_bits,
    lex_argmin,
    prefix_allocate,
    skew_band_fill,
    water_fill,
)
from .types import SimNode, SolveResult

# host-side on purpose (see ops/masks.py BIG): no device init at import time
BIGN = np.float32(1e9)  # "unbounded" node/pod counts

#: applied once per process (TpuSolver.__init__ calls it; idempotent)
_JIT_CACHE_WIRED = False


def _init_jit_cache() -> None:
    """Wire JAX's persistent (on-disk) compilation cache to ``KT_JIT_CACHE``
    at solver init: every process that builds a solver — serve replicas,
    the operator's fallback, bench subprocesses — shares compiled XLA
    programs through one directory, so a restarted or scaled-out replica
    loads the ~8 s solver compiles from disk instead of re-paying them
    (ROADMAP item 2's shared-cache story; deploy/solver.yaml mounts the
    default emptyDir and exports KT_JIT_CACHE at the mount path).

    An explicit ``--jit-cache-dir`` (cli.py ``_maybe_jit_cache``) wins: if
    the config already names a directory this is a no-op, so command-line
    and env wiring compose instead of fighting."""
    global _JIT_CACHE_WIRED
    if _JIT_CACHE_WIRED:
        return
    _JIT_CACHE_WIRED = True
    import os

    cache_dir = os.environ.get("KT_JIT_CACHE", "")
    if not cache_dir or cache_dir == "0":
        return
    if jax.config.jax_compilation_cache_dir:
        return  # cli --jit-cache-dir already configured it
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def _rung(n: int, quantum: int, linear_max: int, ratio: float = 1.5,
          axis_div: int = 1) -> int:
    """Bucket ``n`` up to a small, stable rung ladder: linear multiples of
    ``quantum`` up to ``linear_max``, then a geometric x``ratio`` ladder
    (each rung rounded to the quantum).  Linear quanta keep padding waste
    near zero for the common small shapes; the geometric tail bounds the
    TOTAL number of distinct rungs (≈ log-many), so a growing cluster stops
    triggering a fresh XLA compile every ``quantum`` of growth — the compile
    ladder becomes warmable.  ``axis_div`` keeps the rung divisible for mesh
    sharding."""
    q = max(quantum, axis_div)
    q = ((q + axis_div - 1) // axis_div) * axis_div

    def up(m: int) -> int:
        out = ((m + q - 1) // q) * q
        return max(out, axis_div)

    if n <= linear_max:
        return up(n)
    rung = up(linear_max)
    while rung < n:
        rung = up(int(rung * ratio))
    return rung


def _mesh_divs(mesh) -> Tuple[int, int]:
    if mesh is None:
        return 1, 1
    from ..parallel.mesh import POD_AXIS, TYPE_AXIS

    return mesh.shape[POD_AXIS], mesh.shape[TYPE_AXIS]


def _nr_estimate(st: SolveTensors, NE: int, node_budget: int) -> int:
    """Optimistic-but-padded node-slot count for the scan's NR axis.

    The worst-case budget (one node per pod) makes the per-step state
    enormous — a 50k-pod solve would carry res[55k, R] + selcnt[55k, S]
    through every scan step when it ends up creating ~558 nodes; the
    [NR]-axis traffic, not arithmetic, then dominates device time
    (docs/PROFILE.md).  Estimate instead: per group, the node count if
    packing hit the best resource-only pods-per-node any candidate offers,
    summed, doubled (zone splits/interleave slack), plus slack.  Hostname
    caps are deliberately ignored (capped groups share rows with other
    groups); when the estimate is genuinely short the solve detects slot
    exhaustion and retries once at the full budget (TpuSolver.solve)."""
    if node_budget <= 2048:  # min rung: estimate can't help
        return node_budget
    # memoized on the tensors: solve()/signature()/prepare each consult the
    # dims several times per solve, and the [G, C, R] broadcast below is the
    # only non-trivial part
    cache = getattr(st, "_nr_est_cache", None)
    key = (NE, node_budget)
    if cache is not None and cache[0] == key:
        return cache[1]
    req = np.asarray(st.requests, dtype=np.float32)      # [G, R]
    alloc = np.asarray(st.cand_alloc, dtype=np.float32)  # [C, R]
    if alloc.shape[0] == 0 or req.shape[0] == 0:
        return node_budget
    with np.errstate(divide="ignore", invalid="ignore"):
        ratios = np.floor(alloc[None, :, :] / np.maximum(req[:, None, :], 1e-9))
    ratios = np.where(req[:, None, :] > 1e-12, ratios, np.inf)  # [G, C, R]
    ppn = ratios.min(axis=2)                                    # [G, C]
    best = np.maximum(ppn.max(axis=1), 1.0)                     # [G]
    best = np.where(np.isfinite(best), best, 1.0)
    nodes = np.ceil(np.asarray(st.counts, dtype=np.float64) / best)
    est = NE + int(2.0 * nodes.sum()) + 128
    out = int(min(max(est, 1), node_budget))
    st._nr_est_cache = (key, out)
    return out


def solve_dims(st: SolveTensors, *, NE: int, node_budget: int,
               a: int = 1, b: int = 1, track: bool = True,
               full_nr: bool = False) -> dict:
    """The padded tensor dimensions (and thus the XLA compile signature) for
    a solve of ``st`` against ``NE`` existing nodes with ``node_budget`` max
    node slots.  The SINGLE source of the bucketing math: ``prepare`` pads to
    these dims and ``TpuSolver.signature`` keys compile-readiness on them, so
    the two can never drift.  ``full_nr`` forces the worst-case NR axis (the
    slot-exhaustion retry path)."""
    G_pad = _rung(st.G, 16, 128, axis_div=a)
    C_pad = _rung(max(1, st.C), 64, 512, axis_div=b)
    nr_slots = node_budget if full_nr else _nr_estimate(st, NE, node_budget)
    NR = _rung(max(1, nr_slots), 512, 2048, axis_div=a)
    NE_pad = _rung(max(1, NE), 16, 64)
    S_pad = _rung(st.S, 8, 32) if st.S else 0
    P_pad = _rung(max(1, len(st.prov_names)), 4, 8)
    K, W = st.pm.shape[1], st.pm.shape[2]
    return dict(
        G=G_pad, C=C_pad, NR=NR, NE_pad=NE_pad, S=S_pad, P=P_pad,
        D=st.D, R=st.R, Z=max(1, st.n_zones), K=K, W=W,
        track=bool(track), a=a, b=b,
    )


def _dims_key(dims: dict) -> tuple:
    return tuple(sorted(dims.items()))


# ---------------------------------------------------------------------------
# feasibility precompute
# ---------------------------------------------------------------------------


def compute_feasibility(
    pm: jnp.ndarray,          # [G, K, W] uint32
    requests: jnp.ndarray,    # [G, R]
    gp_ok: jnp.ndarray,       # [G, P]
    cand_vw: jnp.ndarray,     # [C, K]
    cand_vb: jnp.ndarray,     # [C, K]
    cand_alloc: jnp.ndarray,  # [C, R]
    cand_prov: jnp.ndarray,   # [C]
    key_check: jnp.ndarray,   # [K]
    dom_vw: jnp.ndarray,      # [D, 2]
    dom_vb: jnp.ndarray,      # [D, 2]
    zone_key: int,
    ct_key: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (F[G, C] candidate feasibility, dom_ok[G, D] zone&ct allowed)."""
    from ..ops.feasibility import MATMUL_MIN_G, candidate_selector, label_feasibility_matmul

    G = pm.shape[0]

    def fit_group(req_g):
        return jnp.all(
            (req_g[None, :] <= cand_alloc + 1e-6) | (req_g[None, :] <= 0), axis=1
        )

    if G >= MATMUL_MIN_G:
        # heterogeneous-pod shapes: one bf16 MXU contraction over the value
        # vocabulary replaces G x C x K gathers (ops/feasibility.py)
        sel = candidate_selector(cand_vw, cand_vb, key_check, pm.shape[2])
        lab = label_feasibility_matmul(pm, sel, key_check)
        fit = jax.vmap(fit_group)(requests)
        F = lab & fit
    else:
        def one_group(args):
            pm_g, req_g = args
            bits = gather_pm_bits(pm_g, cand_vw, cand_vb)      # [C, K]
            lab = jnp.all(bits | ~key_check[None, :], axis=1)  # [C]
            return lab & fit_group(req_g)

        # chunked vmap bounds the materialized [chunk, C, K] gather intermediate
        outs = []
        for i in range(0, G, 512):
            outs.append(jax.vmap(one_group)((pm[i : i + 512], requests[i : i + 512])))
        F = jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
    F = F & gp_ok[jnp.arange(G)[:, None], cand_prov[None, :]]

    # domain allowance from the zone / capacity-type keys of each group's mask
    def dom_one(pm_g):
        zw = pm_g[zone_key][dom_vw[:, 0]]
        zok = ((zw >> dom_vb[:, 0].astype(jnp.uint32)) & jnp.uint32(1)).astype(bool)
        cw = pm_g[ct_key][dom_vw[:, 1]]
        cok = ((cw >> dom_vb[:, 1].astype(jnp.uint32)) & jnp.uint32(1)).astype(bool)
        return zok & cok

    dom_ok = jax.vmap(dom_one)(pm)
    return F, dom_ok


# Module-level jitted feasibility.  The wrapper is created ONCE: a per-call
# ``jax.jit(compute_feasibility)`` owns a fresh compile cache and silently
# recompiles on every solve (the KT008 class); here the cache persists and
# the bucketed input shapes keep the compile count log-bounded.  The zone/ct
# key ids are static so the traced program indexes with constants, exactly
# like the eager path.
feasibility_jit = partial(jax.jit, static_argnames=("zone_key", "ct_key"))(
    compute_feasibility
)


# ---------------------------------------------------------------------------
# the scan step
# ---------------------------------------------------------------------------


def _make_step(
    consts: dict,
    NR: int,
    Z: int,
    track: bool,
):
    """Build the per-group scan step closure over constant tensors."""
    counts = consts["counts"]          # [G]
    suffix_res = consts["suffix_res"]  # [G, Z, R] later-group demand per zone
    suffix_cnt = consts["suffix_cnt"]  # [G, Z] later-group pod count per zone
    requests = consts["requests"]      # [G, R]
    F = consts["F"]                    # [G, C]
    dom_ok = consts["dom_ok"]          # [G, D]
    g_zone_spread = consts["g_zone_spread"]
    g_zone_skew = consts["g_zone_skew"]
    g_host_spread = consts["g_host_spread"]
    g_host_cap = consts["g_host_cap"]
    g_zone_anti = consts["g_zone_anti"]
    g_zone_paff = consts["g_zone_paff"]
    g_host_paff = consts["g_host_paff"]
    g_sel_match = consts["g_sel_match"]  # [S, G]
    cand_alloc = consts["cand_alloc"]  # [C, R]
    cand_cap = consts["cand_cap"]      # [C, R]
    cand_prov = consts["cand_prov"]    # [C]
    cand_price = consts["cand_price"]  # [C, D]
    cand_avail = consts["cand_avail"]  # [C, D]
    prov_limits = consts["prov_limits"]  # [P, R]
    dom_zone = consts["dom_zone"]      # [D]
    ex_ok = consts["ex_ok"]            # [G, NE_pad] existing-node label/taint compat
    node_budget = consts["node_budget"]  # [] int32 — semantic max_nodes cap
    # NR is bucketed up for jit-shape stability; node_budget carries the
    # caller's real max_nodes so the budget survives the padding.

    C, D = cand_price.shape
    NE_pad = ex_ok.shape[1]
    slot_idx = jnp.arange(NR, dtype=jnp.int32)

    def step(carry, g):
        (res, row_zone, row_dom, row_cand, row_price, selcnt, active,
         n_used, zc, tot, prov_used, infeasible) = carry

        req_g = requests[g]                      # [R]
        cnt = counts[g].astype(jnp.float32)
        Fg = F[g]                                # [C]
        dok = dom_ok[g]                          # [D]
        Fd_g = (Fg[:, None] & cand_avail & dok[None, :])  # [C, D]

        # ---- per-slot feasibility & capacity --------------------------
        safe_cand = jnp.maximum(row_cand, 0)
        safe_dom = jnp.maximum(row_dom, 0)
        rf_cand = Fd_g[safe_cand, safe_dom]
        # slots >= NE_pad always have row_cand >= 0 (solver-created), so the
        # clamped gather below never feeds a wrong ex_ok value into rf
        exv = ex_ok[g][jnp.minimum(slot_idx, NE_pad - 1)]
        rf = active & jnp.where(row_cand >= 0, rf_cand, exv)

        # ---- positive pod-affinity modes (reference.py _zone_allowed /
        # _host_cap / _new_node_host_cap semantics, per group):
        #   A: matching pods exist -> co-locate (their zones / their nodes,
        #      no fresh hostname domain),
        #   B: none exist, group self-matches -> seed ONE zone / ONE node,
        #   C: none exist, no self-match -> infeasible.
        zpa = g_zone_paff[g]
        zpa_on = zpa >= 0
        zpa_i = jnp.maximum(zpa, 0)
        ztot = tot[zpa_i] > 0
        zself = g_sel_match[zpa_i, g]
        zone_seed = zpa_on & ~ztot & zself
        zdead = zpa_on & ~ztot & ~zself

        hpa = g_host_paff[g]
        hpa_on = hpa >= 0
        hpa_i = jnp.maximum(hpa, 0)
        htot = tot[hpa_i] > 0
        hhave = selcnt[:, hpa_i] > 0
        hself = g_sel_match[hpa_i, g]
        host_seed = hpa_on & ~htot & hself
        host_gated = hpa_on & htot
        hdead = hpa_on & ~htot & ~hself

        rf = rf & (~host_gated | hhave) & ~hdead & ~zdead
        # an empty node never satisfies mode-A/C hostname affinity
        new_allowed = ~host_gated & ~hdead & ~zdead

        # step-entry PER-ZONE net-backfill state for pick(): how much of the
        # later-group demand committed to each zone the FREE capacity on that
        # zone's open rows absorbs, in units of the zone's average later-pod
        # request vector.  Per-zone on both sides (fuzz seed 14): a huge free
        # row in zone c must not cancel the backfill credit of zones a/b,
        # whose committed spread-group share can only land on nodes bought
        # THERE.  Hoisted here — it depends only on the step-entry carry
        # (pick() closes over this `res`, not the threaded creation state),
        # and the [NR, R] reduction is the most memory-heavy scoring term.
        # zero-guard only (not a floor): the even zone split makes per-zone
        # counts FRACTIONAL, and flooring a 1/3-pod count at 1 would shrink
        # the average request (and the net fraction below) threefold
        cnt_z_safe = jnp.where(suffix_cnt[g] > 0, suffix_cnt[g], 1.0)    # [Z]
        avg_req_z = suffix_res[g] / cnt_z_safe[:, None]                  # [Z, R]
        row_avg = avg_req_z[jnp.maximum(row_zone, 0)]                   # [NR, R]
        per_row_absorb = jnp.min(jnp.where(
            row_avg > 0,
            jnp.maximum(res, 0.0) / jnp.maximum(row_avg, 1e-9),
            BIGN,
        ), axis=1)                                                      # [NR]
        rows_absorb_z = jnp.zeros(Z, dtype=jnp.float32).at[
            jnp.maximum(row_zone, 0)
        ].add(jnp.where(active, per_row_absorb, 0.0))                   # [Z]
        net_backfill_frac_z = jnp.clip(
            (suffix_cnt[g] - rows_absorb_z) / cnt_z_safe,
            0.0, 1.0,
        )                                                               # [Z]
        # later-group demand convertible into THIS group's pod-equivalents,
        # per zone (hoisted from pick(): depends only on g)
        backfill_eq_z = jnp.min(jnp.where(
            req_g[None, :] > 0,
            suffix_res[g] / jnp.maximum(req_g[None, :], 1e-9),
            BIGN,
        ), axis=1)                                                      # [Z]

        ratios = jnp.where(req_g[None, :] > 0, jnp.floor((res + 1e-6) / jnp.maximum(req_g[None, :], 1e-9)), BIGN)
        cap = jnp.min(ratios, axis=1)            # [NR]

        sh = g_host_spread[g]
        hk = g_host_cap[g].astype(jnp.float32)
        selrow = selcnt[:, jnp.maximum(sh, 0)].astype(jnp.float32)
        hcap = jnp.where(hk > 0, hk - selrow, jnp.where(selrow > 0, 0.0, BIGN))
        cap = jnp.where(sh >= 0, jnp.minimum(cap, hcap), cap)
        cap = jnp.maximum(cap, 0.0) * rf

        # ---- zone-level caps ------------------------------------------
        zsp = g_zone_spread[g]
        za = g_zone_anti[g]
        zoned = (zsp >= 0) | (za >= 0) | zpa_on

        # eligible zones: any allowed domain in the zone
        el = jnp.zeros(Z, dtype=bool).at[dom_zone].max(dok)
        # zone positive affinity, modes A and C
        zcpa = zc[zpa_i] > 0                                        # [Z]
        el = el & (~(zpa_on & ztot) | zcpa) & ~zdead
        # zone anti-affinity cap
        zc_an = zc[jnp.maximum(za, 0)].astype(jnp.float32)          # [Z]
        self_match = g_sel_match[jnp.maximum(za, 0), g]
        anti_cap = jnp.where(
            self_match, jnp.maximum(1.0 - zc_an, 0.0),
            jnp.where(zc_an > 0, 0.0, BIGN),
        )
        anti_cap = jnp.where(za >= 0, anti_cap, BIGN)               # [Z]

        rowcap_z = jnp.zeros(Z, dtype=jnp.float32).at[jnp.maximum(row_zone, 0)].add(
            jnp.where(active, cap, 0.0)
        )

        # per-zone budget from zone anti-affinity + zone-spread headroom
        # (oracle _zone_allowed: counts[z] + 1 - min_eligible <= maxSkew);
        # the seed flows must honor it — the normal flow gets it via cap_z
        zc_sp = jnp.where(zsp >= 0, zc[jnp.maximum(zsp, 0)], jnp.zeros(Z, jnp.int32)).astype(jnp.float32)
        min_sp = jnp.min(jnp.where(el, zc_sp, BIGN))
        spread_cap = jnp.where(
            zsp >= 0, g_zone_skew[g].astype(jnp.float32) + min_sp - zc_sp, BIGN
        )
        zone_budget = jnp.minimum(anti_cap, jnp.maximum(spread_cap, 0.0))   # [Z]

        # ---- new-node candidate scoring --------------------------------
        nr_ratios = jnp.where(
            req_g[None, :] > 0,
            jnp.floor((cand_alloc + 1e-6) / jnp.maximum(req_g[None, :], 1e-9)),
            BIGN,
        )
        # ppn is the resource-only pods-per-node; take_pn is what THIS group
        # actually places per node (hostname caps applied).  Scoring uses a
        # backfill-aware blend of the two — see pick().
        ppn = jnp.min(nr_ratios, axis=1)                            # [C]
        hcap_new = jnp.where((sh >= 0) & (hk > 0), hk, BIGN)
        take_pn = jnp.minimum(ppn, hcap_new)
        lim_ok = jnp.all(
            prov_used[cand_prov] + cand_cap <= prov_limits[cand_prov] + 1e-6, axis=1
        )                                                            # [C]
        new_ok = (Fd_g & (take_pn[:, None] >= 1.0) & lim_ok[:, None]
                  & new_allowed)                                     # [C, D]
        zone_of_dom = dom_zone                                       # [D]

        # ---- candidate pick (used by creation AND the zone-seed choice) --
        # Mirrors the oracle: argmin price / min(ppn, remaining); nodes of the
        # chosen type are created in bulk while remaining >= ppn, then the
        # tail re-scores once with the smaller remainder.
        ci_key = jnp.broadcast_to(jnp.arange(C, dtype=jnp.float32)[:, None], (C, D))
        di_key = jnp.broadcast_to(jnp.arange(D, dtype=jnp.float32)[None, :], (C, D))
        new_ok_nolim = Fd_g & (take_pn[:, None] >= 1.0) & new_allowed

        def _lim_ok_cur(prov_used_cur):
            return jnp.all(
                prov_used_cur[cand_prov] + cand_cap <= prov_limits[cand_prov] + 1e-6,
                axis=1,
            )

        def pick(rem, dom_mask, prov_used_cur, tail_rem=None,
                 size_tiebreak=True, pool_rem=None):
            """argmin over (C, D & dom_mask) of price / min(fill, rem),
            where fill = min(ppn, take_pn + later-group demand committed to
            the candidate domain's ZONE) — the backfill-aware effective
            pods-per-node (see comment below).

            Limit feasibility is recomputed from the *current* provisioner
            usage so once a limit binds mid-group the next pick falls back to
            the next-best candidate (mirroring the oracle's invalidate-and-
            retry at reference.py _create_node)."""
            ok_cd = new_ok_nolim & _lim_ok_cur(prov_used_cur)[:, None] & dom_mask[None, :]
            # Effective fill for scoring: this group fills take_pn per node
            # (hostname caps included); slack beyond that is only worth
            # paying for when LATER groups exist to backfill it IN THIS
            # ZONE.  The oracle scores resource-only ppn because its
            # sequential interleave always has backfill in flight; here the
            # later-group RESOURCE demand committed to the candidate's zone
            # (converted to this-group pod equivalents, backfill_eq_z) makes
            # that optimism explicit and zone-local — a hostname-capped
            # group solved last buys right-sized nodes instead of betting on
            # backfill that never comes (fuzz seeds 14/20), while capped
            # groups with real later demand still buy big co-location nodes
            # (bench c3).
            # The zone's backfill pool is shared across every node this
            # group will create there: per-node slack is only worth what the
            # pool can deliver to ONE node.  The node-count estimate is
            # pool_rem/take_pn (the creation remainder this pick serves —
            # the zone's share under zoned creation) CLAMPED by how many
            # nodes the provisioner limit can still fund — when the limit
            # tail binds (one node left), the whole pool concentrates on it,
            # and a roomier type is worth its price premium (the sequential
            # oracle gets this for free: its tail placement sees every
            # group's residual at once; fuzz seed 27).
            head_nodes = jnp.min(
                jnp.floor(
                    (prov_limits[cand_prov] - prov_used_cur[cand_prov] + 1e-6)
                    / jnp.maximum(cand_cap, 1e-9)
                ),
                axis=1,
            )                                                        # [C]
            est_rem = rem if pool_rem is None else pool_rem
            n_nodes_est = jnp.clip(
                jnp.minimum(est_rem / jnp.maximum(take_pn, 1.0),
                            jnp.clip(head_nodes, 0.0, BIGN)),
                1.0, BIGN,
            )                                                        # [C]
            per_node_backfill = (
                backfill_eq_z[dom_zone][None, :] / n_nodes_est[:, None]
            )                                                        # [C, D]
            fill = jnp.minimum(ppn[:, None], take_pn[:, None] + per_node_backfill)
            denom = jnp.maximum(jnp.minimum(fill, jnp.maximum(rem, 1.0)), 1.0)
            pnb_net = per_node_backfill * net_backfill_frac_z[dom_zone][None, :]
            if tail_rem is not None:
                # TAIL purchases are the oracle's last-pods-standing buys:
                # cap the utilization estimate additionally by the zone's
                # own tail count plus only the NET backfill — the zone-
                # committed later-group demand minus what the free capacity
                # on THAT ZONE's open rows absorbs first (later groups
                # first-fit free rows, so gross suffix demand over-credits a
                # tail node — fuzz seed 14's 8x node for a 2-pod tail; but
                # when the zone's rows are full or a limit squeezes later
                # demand onto this very node, the credit is real — fuzz
                # seed 27's 2-cpu tail).  Rows absorb in units of their
                # zone's average later-pod request vector (resource-coupled:
                # free memory with no free cpu absorbs nothing).
                denom = jnp.maximum(
                    jnp.minimum(
                        denom, jnp.maximum(tail_rem, 1.0) + pnb_net
                    ),
                    1.0,
                )
            score = jnp.where(ok_cd, cand_price / denom, BIG)
            # tie-break at exactly equal $/pod: prefer the LARGER candidate,
            # but only when this group's own remainder fills it completely
            # (take_pn <= rem) — then the $ outcome is identical by
            # construction and the cluster gets fewer, larger nodes (less
            # kubelet/API/image-pull/ENI load at the same price).
            # Partially-fillable candidates never win the tie: their equal
            # score rests on backfill estimates, not on guaranteed $ — even
            # the per-zone projected credit must not upsize a tie, because
            # when a provisioner limit binds, a node bought "for backfill"
            # spends limit headroom later zones of THIS group still need
            # (fuzz seed 27: a 16x tail node starves zone c below its skew
            # band).  Cross-group tail fragmentation is handled after
            # extraction by cost-neutral coalescing (solver/coalesce.py),
            # not by upsizing picks here.  For TAIL picks the guard compares
            # against the zone's own tail count (tail_rem), not the
            # group-wide scoring remainder.  The host-seed flow opts out
            # entirely (size_tiebreak=False): it buys exactly ONE node
            # either way, so a larger type is strictly more $.
            guard_rem = (
                jnp.broadcast_to(jnp.maximum(rem, 1.0), (C, D))
                if tail_rem is None
                else jnp.broadcast_to(jnp.maximum(tail_rem, 1.0), (C, D))
            )
            full_take = jnp.where(
                take_pn[:, None] <= guard_rem, take_pn[:, None], 0.0,
            )
            if not size_tiebreak:
                full_take = jnp.zeros_like(full_take)
            size_key = jnp.where(ok_cd, -full_take, BIG)
            pk = jnp.where(ok_cd, cand_price, BIG)
            flat = lex_argmin(score, size_key, pk, ci_key * D + di_key)
            bc = (flat // D).astype(jnp.int32)
            bd = (flat % D).astype(jnp.int32)
            ok = score.reshape(-1)[flat] < BIG
            return bc, bd, ok


        # ---- zone-seed (mode B): the whole group lands in ONE zone — the
        # cheapest-absorption zone when open slots exist, else the best
        # new-node zone (after the first placement every later pod must join
        # a zone with a matching pod, so the seed choice is the whole game)
        def _z_seed(_):
            # only zones with anti-affinity/spread headroom are seedable
            elb = el & (zone_budget >= 1.0)
            ok_slots0 = rf & (cap >= 1.0) & elb[jnp.maximum(row_zone, 0)]
            has0 = jnp.any(ok_slots0)
            # Seed the zone that ABSORBS the group most cheaply, not the
            # earliest open slot's zone: eligible free-row capacity takes
            # pods at zero marginal cost, the remainder pays the zone's best
            # new-node $/pod (kubelet fuzz seed 20: the earliest open slot
            # sat in zone-1a while a hostname-spread fleet's free rows —
            # enough for the whole group — sat in zone-1b; chasing the slot
            # bought 4 dedicated nodes the sequential oracle never buys).
            # Ties (several zones absorb everything free) break on
            # first-open-slot order then zone index — the old deterministic
            # behavior, which also serves as the all-BIG fallback when no
            # zone can host the whole group.
            free_z = jnp.zeros(Z, dtype=jnp.float32).at[
                jnp.maximum(row_zone, 0)
            ].add(jnp.where(ok_slots0, cap, 0.0))
            # the zone's LEGAL headroom for this group (anti-affinity +
            # spread band) caps both free-row absorption and what new nodes
            # can add — a zone whose rows could hold the group but whose
            # budget admits one pod must not win on phantom capacity
            budget_z = jnp.where(elb, zone_budget, 0.0)
            place_z = jnp.minimum(jnp.minimum(free_z, budget_z), cnt)
            paid_z = jnp.maximum(jnp.minimum(cnt, budget_z) - place_z, 0.0)
            ok_cd0 = (new_ok_nolim & _lim_ok_cur(prov_used)[:, None]
                      & elb[dom_zone][None, :])
            # $/pod amortized over the ZONE's paid remainder, not the whole
            # group: a 2-pod remainder on a 40-pod node pays the full node
            ppp_cd = jnp.where(
                ok_cd0,
                cand_price / jnp.maximum(
                    jnp.minimum(take_pn[:, None], paid_z[dom_zone][None, :]),
                    1.0,
                ),
                BIG,
            )
            ppp_z = jnp.full(Z, BIG).at[dom_zone].min(jnp.min(ppp_cd, axis=0))
            # budget headroom only counts as placeable when there is SUPPLY
            # behind it — free rows, or a purchasable candidate in the zone
            # (limits can exhaust a zone's candidates mid-solve; an empty
            # zone with a big spread budget but nothing to buy must not win
            # the seed and strand the whole group)
            purch_z = jnp.where(ppp_z < BIG, paid_z, 0.0)
            unplaced_z = jnp.maximum(cnt - place_z - purch_z, 0.0)
            cost_z = jnp.where(
                elb, jnp.minimum(purch_z * ppp_z, BIG), BIG,
            )
            first_slot = jnp.min(
                jnp.where(
                    ok_slots0[:, None]
                    & (row_zone[:, None] == jnp.arange(Z)[None, :]),
                    slot_idx[:, None].astype(jnp.float32), BIGN,
                ), axis=0,
            )                                                       # [Z]
            z_best = lex_argmin(
                jnp.where(elb, unplaced_z, BIGN), cost_z, first_slot,
                jnp.arange(Z, dtype=jnp.float32),
            ).astype(jnp.int32)
            _bc0, bd0, okp0 = pick(cnt, elb[dom_zone], prov_used)
            return jnp.where(has0, z_best, jnp.where(okp0, dom_zone[bd0], -1))

        z_star = jax.lax.cond(zone_seed, _z_seed,
                              lambda _: jnp.int32(-1), operand=None)
        el = jnp.where(zone_seed, el & (jnp.arange(Z) == z_star), el)

        new_ok_z = jnp.zeros(Z, dtype=bool).at[zone_of_dom].max(jnp.any(new_ok, axis=0))
        cap_z = jnp.minimum(rowcap_z + jnp.where(new_ok_z, BIGN, 0.0), anti_cap)
        cap_z = jnp.where(el, cap_z, 0.0)

        # ---- allocation: rows then new nodes ---------------------------
        def zoned_alloc(_):
            # Limit-aware, zone-fair allocation.  Per-zone creation below
            # runs zones SEQUENTIALLY, so a provisioner limit that binds
            # mid-group would be spent entirely on the first zones,
            # stranding later zones at 0 — a maxSkew violation the
            # sequential oracle never produces because it interleaves
            # zones.  Three closed-form passes:
            #   1. tentative fill with unlimited new capacity -> how many
            #      NEW pods each zone would need beyond its open rows;
            #   2. water-fill the limit-fundable new-pod budget (sum over
            #      provisioner pools of each pool's best whole-node count;
            #      partial nodes consume full capacity against the limit)
            #      across those needs;
            #   3. final fill with rows+funded caps, then the maxSkew recap
            #      (lvl_min over ALL eligible zones, capacity-stuck ones
            #      included) — overflow stays unplaced, it does NOT pile
            #      into unstuck zones.
            head_c = jnp.min(
                jnp.floor(
                    (prov_limits[cand_prov] - prov_used[cand_prov] + 1e-6)
                    / jnp.maximum(cand_cap, 1e-9)
                ),
                axis=1,
            )                                                           # [C]
            c_ok = jnp.any(new_ok_nolim, axis=1)
            per_c = jnp.where(c_ok, jnp.clip(head_c, 0.0, BIGN) * take_pn, 0.0)
            # provisioner limits are independent pools: the fundable total is
            # the SUM over provisioners of each pool's best candidate, not a
            # single global best
            per_p = jnp.zeros(prov_limits.shape[0], dtype=per_c.dtype).at[
                cand_prov
            ].max(per_c)
            fundable_new = jnp.minimum(jnp.sum(per_p), BIGN)
            # all three allocation passes prefer FREE existing-row capacity
            # within the skew band (skew_band_fill): plain leveling buys a
            # new node in one zone while free capacity idles in another —
            # the sequential oracle's first-fit never does (fuzz seed 14)
            rows_z = jnp.where(el, rowcap_z, 0.0)
            skew_eff = jnp.where(
                zsp >= 0, g_zone_skew[g].astype(jnp.float32), BIGN
            )
            alloc0 = skew_band_fill(
                zc_sp, rows_z, cap_z, cnt, skew_eff, el
            ).astype(jnp.float32)
            need_new = jnp.maximum(alloc0 - jnp.minimum(rows_z, alloc0), 0.0)
            funded_new = water_fill(
                jnp.zeros(Z, dtype=jnp.float32), need_new, fundable_new,
                el & (need_new > 0),
            ).astype(jnp.float32)
            cap_f = jnp.where(el, jnp.minimum(rows_z + funded_new, cap_z), 0.0)
            alloc1 = skew_band_fill(
                zc_sp, jnp.minimum(rows_z, cap_f), cap_f, cnt, skew_eff, el
            ).astype(jnp.float32)
            lvl_min = jnp.min(jnp.where(el, zc_sp + alloc1, BIGN))
            skew_cap = jnp.where(
                zsp >= 0,
                lvl_min + g_zone_skew[g].astype(jnp.float32) - zc_sp,
                BIGN,
            )
            cap_z2 = jnp.minimum(cap_f, jnp.maximum(skew_cap, 0.0))
            alloc_z = skew_band_fill(
                zc_sp, jnp.minimum(rows_z, cap_z2), cap_z2, cnt, skew_eff, el
            ).astype(jnp.float32)  # [Z]
            # per-zone prefix allocation over slots in creation order
            zone1h = (row_zone[:, None] == jnp.arange(Z)[None, :])           # [NR, Z]
            capz_slots = jnp.where(zone1h, cap[:, None], 0.0)
            before = jnp.cumsum(capz_slots, axis=0) - capz_slots
            take_slots = jnp.clip(alloc_z[None, :] - before, 0.0, capz_slots)
            take = jnp.sum(jnp.where(zone1h, take_slots, 0.0), axis=1)
            taken_z = jnp.sum(jnp.where(zone1h, take_slots, 0.0), axis=0)
            rem_z = jnp.maximum(alloc_z - taken_z, 0.0)
            return take, rem_z

        def simple_alloc(_):
            take = prefix_allocate(cap, cnt)
            rem = cnt - jnp.sum(take)
            return take, jnp.where(jnp.arange(Z) == 0, rem, 0.0)  # placeholder; zone chosen below

        state = (res, row_zone, row_dom, row_cand, row_price, active, prov_used,
                 jnp.zeros(NR, dtype=jnp.float32), n_used)

        def write_block(state, n_nodes, per_node, last_extra, bc, bd):
            """Append n_nodes slots of candidate bc/domain bd; each takes
            per_node pods except the last which takes last_extra.  Returns
            (state, pods actually placed)."""
            (res, row_zone, row_dom, row_cand, row_price, active, prov_used,
             new_take, cursor) = state
            # budget clamp; floor at 0 — cursor starts at NE which may already
            # exceed a small node_budget, and a negative count must not walk
            # the cursor backward or deduct phantom prov_used capacity
            n_req = n_nodes
            n_nodes = jnp.maximum(
                jnp.minimum(n_nodes, jnp.minimum(NR, node_budget) - cursor), 0
            )
            in_block = (slot_idx >= cursor) & (slot_idx < cursor + n_nodes)
            is_last = slot_idx == (cursor + n_nodes - 1)
            # last_extra is the partial fill of the block's true final node;
            # when the budget truncated the block, every written node is an
            # interior one and must take the full per_node
            last_take = jnp.where(n_nodes >= n_req, last_extra, per_node)
            blk = jnp.where(in_block, jnp.where(is_last, last_take, per_node), 0.0)
            new_take = new_take + blk
            res = jnp.where(in_block[:, None], cand_alloc[bc][None, :], res)
            row_zone = jnp.where(in_block, dom_zone[bd], row_zone)
            row_dom = jnp.where(in_block, bd, row_dom)
            row_cand = jnp.where(in_block, bc, row_cand)
            row_price = jnp.where(in_block, cand_price[bc, bd], row_price)
            active = active | in_block
            prov_used = prov_used.at[cand_prov[bc]].add(
                cand_cap[bc] * n_nodes.astype(jnp.float32)
            )
            state = (res, row_zone, row_dom, row_cand, row_price, active,
                     prov_used, new_take, cursor + n_nodes)
            return state, jnp.sum(blk)

        def limit_headroom(prov_used_cur, bc):
            """Max nodes of candidate bc before its provisioner limit binds."""
            p = cand_prov[bc]
            head = prov_limits[p] - prov_used_cur[p]          # [R]
            cap_row = cand_cap[bc]
            per = jnp.where(cap_row > 0, jnp.floor((head + 1e-6) / jnp.maximum(cap_row, 1e-9)), BIGN)
            return jnp.clip(jnp.min(per), 0.0, BIGN)

        def stage_pair(state, rem, dom_mask, score_rem):
            """One (bulk, tail) creation round; returns leftover pods.

            ``score_rem`` is the remaining count used in the $/pod scoring
            denominator — the GROUP's remainder, not this zone's share.  The
            sequential oracle scores every placement against the whole
            group's remaining pods (reference.py _best_in_zone), so a
            3-zone-spread group still buys node types sized for the full
            group; scoring per-zone thirds buys smaller types and ~2x the
            node count at similar cost."""
            bc, bd, ok = pick(score_rem, dom_mask, state[6], pool_rem=rem)
            ppn_b = jnp.maximum(take_pn[bc], 1.0)
            n_bulk_f = jnp.where(ok, jnp.floor(rem / ppn_b), 0.0)
            n_bulk = jnp.minimum(n_bulk_f, limit_headroom(state[6], bc)).astype(jnp.int32)
            state, took_b = write_block(state, n_bulk, ppn_b, ppn_b, bc, bd)
            rem_t = jnp.maximum(rem - took_b, 0.0)
            score_t = jnp.maximum(score_rem - took_b, rem_t)
            ct_, dt_, ok_t = pick(score_t, dom_mask, state[6], tail_rem=rem_t,
                                  pool_rem=rem_t)
            ppn_t = jnp.maximum(take_pn[ct_], 1.0)
            n_tail_f = jnp.where(ok_t & (rem_t > 0), jnp.ceil(rem_t / ppn_t), 0.0)
            n_tail = jnp.minimum(n_tail_f, limit_headroom(state[6], ct_)).astype(jnp.int32)
            last = rem_t - (n_tail.astype(jnp.float32) - 1.0) * ppn_t
            state, took_t = write_block(
                state, n_tail, ppn_t, jnp.clip(last, 0.0, ppn_t), ct_, dt_
            )
            return state, jnp.maximum(rem_t - took_t, 0.0)

        def two_stage(state, rem, dom_mask, score_rem=None):
            # round 2 only fires when a provisioner limit (or slot budget)
            # clamped round 1; pick() re-derives limit feasibility, so the
            # remainder falls back to the next-best candidate type.
            if score_rem is None:
                score_rem = rem
            state, left = stage_pair(state, rem, dom_mask, score_rem)
            state, _ = stage_pair(state, left, dom_mask, jnp.maximum(score_rem - (rem - left), left))
            return state

        def normal_flow(state):
            take, rem_z = jax.lax.cond(zoned, zoned_alloc, simple_alloc, operand=None)

            def create_simple(state):
                return two_stage(state, jnp.sum(rem_z), jnp.ones(D, dtype=bool))

            def create_zoned(state):
                # scan (not a Python loop) over zones: the two_stage creation
                # body is traced ONCE instead of Z times, cutting the XLA
                # program size — and thus compile time — roughly by the zone
                # count for the creation section (the dominant traced code).
                # Every zone's BULK type choice scores against the group's
                # FULL new-node demand (not a zone-decremented remainder):
                # the sequential oracle interleaves zones, so each zone's
                # first node is created while `remaining` is still the whole
                # group — a later-ordered zone must not buy a smaller type
                # (worse $/pod after the reserved-overhead staircase) just
                # because the scan visited it second (fuzz seed 14).  Tail
                # picks stay honest via tail_rem; an oversized bulk choice
                # self-corrects (n_bulk floors to 0 and the tail re-scores).
                total = jnp.sum(rem_z)

                def zbody(st_z, z):
                    st_z = two_stage(st_z, rem_z[z], zone_of_dom == z,
                                     score_rem=total)
                    return st_z, jnp.int32(0)

                state, _ = jax.lax.scan(
                    zbody, state, jnp.arange(Z, dtype=jnp.int32),
                )
                return state

            state = jax.lax.cond(zoned, create_zoned, create_simple, state)
            return state, take

        def host_seed_flow(state):
            # mode-B hostname affinity: every pod of the group must land on
            # the SAME node — first-fit the earliest compatible open slot,
            # else create one node; the un-fitting remainder is infeasible
            # (exactly where the sequential oracle ends up: after pod 1 seeds
            # a node, pods 2..k must join it, and a fresh node is never
            # admissible again because matching pods now exist).
            elb = el & (zone_budget >= 1.0)
            ok_slots = rf & (cap >= 1.0) & elb[jnp.maximum(row_zone, 0)]
            has = jnp.any(ok_slots)
            first = jnp.argmax(ok_slots)
            z_first = jnp.maximum(row_zone[first], 0)
            take = jnp.zeros(NR, dtype=jnp.float32).at[first].set(
                jnp.where(has,
                          jnp.minimum(jnp.minimum(cnt, cap[first]),
                                      zone_budget[z_first]),
                          0.0)
            )
            bc, bd, okp = pick(cnt, elb[dom_zone], state[6], size_tiebreak=False)
            n_new = jnp.where(~has & okp, 1, 0).astype(jnp.int32)
            per = jnp.minimum(jnp.minimum(cnt, jnp.maximum(take_pn[bc], 1.0)),
                              jnp.maximum(zone_budget[dom_zone[bd]], 0.0))
            state, _ = write_block(state, n_new, per, per, bc, bd)
            return state, take

        state, take = jax.lax.cond(host_seed, host_seed_flow, normal_flow, state)
        (res, row_zone, row_dom, row_cand, row_price, active, prov_used,
         new_take, n_used) = state

        total_take = take + new_take
        res = res - total_take[:, None] * req_g[None, :]

        # ---- counters -----------------------------------------------------
        match_g = g_sel_match[:, g].astype(jnp.float32)                        # [S]
        selcnt = selcnt + (total_take[:, None] * match_g[None, :]).astype(selcnt.dtype)
        placed_z = jnp.zeros(Z, dtype=jnp.float32).at[jnp.maximum(row_zone, 0)].add(
            jnp.where(active, total_take, 0.0)
        )
        zc = zc + (match_g[:, None] * placed_z[None, :]).astype(zc.dtype)
        placed = jnp.sum(total_take)
        tot = tot + (match_g * placed).astype(tot.dtype)
        infeasible = infeasible.at[g].set(jnp.round(cnt - placed).astype(jnp.int32))

        carry = (res, row_zone, row_dom, row_cand, row_price, selcnt, active,
                 n_used, zc, tot, prov_used, infeasible)
        ys = total_take.astype(jnp.int32) if track else jnp.int32(0)
        return carry, ys

    return step


@partial(jax.jit, static_argnames=("NR", "Z", "track"))
def _run_scan(consts, init, NR: int, Z: int, track: bool):
    """Module-level jitted scan: the jit cache persists across solves, so
    bucketed shapes recompile once per signature, not once per call."""
    step = _make_step(consts, NR, Z, track)
    G = consts["counts"].shape[0]
    return jax.lax.scan(step, init, jnp.arange(G, dtype=jnp.int32))


#: megabatch request-slot cap: one vmapped dispatch solves at most this many
#: independent solve requests (service/server.py --max-slots clamps here)
MEGA_MAX_SLOTS = 32


def _mega_rung(n: int, n_dev: int = 1) -> int:
    """Pad the request-slot axis to a power-of-two rung (1,2,4,...,32): the
    megabatch kernel compiles per (dims, B) signature, so bucketing B keeps
    the compile ladder log-bounded and AOT-precompilable, exactly like the
    tensor-axis rungs of :func:`_rung`.

    ``n_dev`` > 1 is the SHARDED megabatch (slot axis data-parallel over the
    flattened mesh — parallel/mesh.py slot_mesh): the rung ladder floors at
    the device count and doubles from there (8 devices -> 8, 16, 32), so the
    slot axis always divides evenly over the chips and every rung keeps the
    whole mesh lit — a 3-slot flush on an 8-chip mesh pads to 8 (padding
    slots replicate request 0 and are discarded; idle chips would cost the
    same wall time and serve nothing).  The result never exceeds
    MEGA_MAX_SLOTS: a non-power-of-two device count whose next double would
    cross the cap stops at its largest in-ladder rung (24 devices -> {24},
    6 -> {6, 12, 24}) — callers cap their flush size at that rung
    (:func:`max_mega_slots`), so no off-ladder program is ever compiled."""
    r = max(1, n_dev)
    while r < min(max(1, n), MEGA_MAX_SLOTS) and r * 2 <= MEGA_MAX_SLOTS:
        r *= 2
    return r


def max_mega_slots(mesh) -> int:
    """Largest megabatch flush this mesh can serve on the sharded rung
    ladder (= MEGA_MAX_SLOTS when unmeshed or the devices divide it evenly;
    smaller for awkward device counts — 24 chips cap flushes at 24), or 0
    for an unshardable mesh (device count past the ladder): no sharded
    megabatch program exists to size a flush for, and returning the raw
    device count would let a trusting caller build a flush that
    solve_many_async can only reject."""
    if not mesh_shardable(mesh):
        return 0
    return _mega_rung(MEGA_MAX_SLOTS, _mesh_size(mesh))


def _mesh_size(mesh) -> int:
    return 1 if mesh is None else int(mesh.devices.size)


def _mega_key_tail(slots: int, zone_key: int, ct_key: int, mesh) -> tuple:
    """The megabatch compile-key suffix: slot rung + zone/ct vocab
    positions (+ the mesh fingerprint when sharded).  The SINGLE source of
    this format — ``mega_signature``, ``_dispatch_prepared`` and the
    consolidation sweep's ``sweep_signature`` all append exactly this, so
    readiness/warm bookkeeping can never drift from what dispatch keys."""
    tail = (
        ("mega_slots", _mega_rung(slots, _mesh_size(mesh))),
        ("zk", zone_key),
        ("ck", ct_key),
    )
    if mesh is not None:
        from ..parallel.mesh import mesh_signature

        tail += (("mesh", mesh_signature(mesh)),)
    return tail


def mesh_shardable(mesh) -> bool:
    """True when the megabatch slot axis can shard over ``mesh``: the
    device count must fit inside the slot-rung ladder (a 64-chip mesh
    cannot pad a <=32-slot batch to one slot per chip — such schedulers
    keep the sharded single-solve path and count mesh_serial flushes)."""
    return _mesh_size(mesh) <= MEGA_MAX_SLOTS


#: the megabatch bucket-key components that are PADDED axis rungs — two
#: buckets differing only here can share one dispatch when one dominates
#: (building the smaller request at the larger rungs is the normal padding
#: path `_host_arrays` already runs for every solve)
UNIFIABLE_DIMS = ("G", "C", "NR", "NE_pad", "S", "P")
#: the non-dims tail components `_mega_key_tail` appends — derived FROM
#: the tail builder (plus the mesh fingerprint it conditionally adds), so
#: key-splitting can never drift from key construction (KT014's
#: single-source contract)
_MEGA_TAIL_NAMES = tuple(
    k for k, _ in _mega_key_tail(1, 0, 0, None)) + ("mesh",)


def unify_mega_keys(a: tuple, b: tuple) -> Optional[tuple]:
    """The DOMINANT of two megabatch bucket keys when one subsumes the
    other, else None — the host-aware coalescer's mixed-bucket unification
    (ISSUE 14): a flush holding bucket A can admit a bucket-B request iff
    every axis rung of one key >= the other's and everything else (vocab
    key positions, track, mesh fingerprint, slot rung) matches exactly;
    the dominated requests then build their tensors at the dominant dims
    (``solve_many_async(target_dims=...)``) and the whole flush runs ONE
    mesh dispatch instead of two serial ones.

    Domination-only on purpose: the unified program IS the dominant
    bucket's own program, which real traffic already warms — a
    component-wise-max of divergent keys would mint programs nothing
    precompiles (the KT014 compile-surface discipline)."""
    if a == b:
        return a
    da, db = dict(a), dict(b)
    if set(da) != set(db):
        return None
    a_dom = b_dom = True
    for k, va in da.items():
        vb = db[k]
        if va == vb:
            continue
        if k not in UNIFIABLE_DIMS:
            return None
        if va < vb:
            a_dom = False
        else:
            b_dom = False
    if a_dom:
        return a
    if b_dom:
        return b
    return None


def mega_key_dims(key: tuple) -> dict:
    """The solve_dims dict embedded in a megabatch bucket key (everything
    but the `_mega_key_tail` components) — what a unified dispatch passes
    to ``_host_arrays(dims=...)`` so dominated requests pad to the
    dominant bucket's rungs."""
    return {k: v for k, v in dict(key).items() if k not in _MEGA_TAIL_NAMES}


def mega_key_at_slots(key: tuple, slots: int, mesh) -> tuple:
    """Re-key a slots=1 megabatch bucket key at a real flush size: the
    dims part stays, the tail is re-derived for ``slots`` — the signature
    a unified flush's readiness/warm bookkeeping probes (single-sourced
    through `_mega_key_tail` like every other mega key)."""
    d = dict(key)
    dims_part = tuple(sorted(
        (k, v) for k, v in d.items() if k not in _MEGA_TAIL_NAMES))
    return dims_part + _mega_key_tail(slots, d["zk"], d["ck"], mesh)


def multihost_fence_enabled() -> bool:
    """Per-host megabatch fences (read only the process-addressable slot
    shards) — default on; ``KT_MULTIHOST=0`` forces the legacy whole-batch
    readback (the bench A/B and an emergency kill switch)."""
    import os

    return os.environ.get("KT_MULTIHOST", "1") != "0"


def read_slot_rows(arrays, *, local_only: bool = False):
    """Fence + read the leading (request-slot) axis of stacked megabatch
    arrays — THE addressable-shard accessor (ktlint KT018's sanctioned
    home): serving-path extraction must route mesh-sharded carry reads
    through here, never a raw ``np.asarray``/``device_get`` on the whole
    array, which on a multi-host mesh pays DCN latency (and memory) for
    every slot other hosts own.

    ``local_only`` reads ONLY ``jax.process_index()``-addressable shards
    (single-process: that is every shard, byte-identical to the whole
    read); otherwise one whole-array D2H per array (the single-device /
    kill-switch path).  Returns ``(rows, bytes_read, bytes_total)`` where
    ``rows[k][s]`` is slot ``s`` of array ``k`` — only locally-owned slots
    are present under ``local_only`` on a multi-process mesh."""
    rows: List[dict] = []
    bytes_read = 0
    bytes_total = 0
    for arr in arrays:
        bytes_total += int(getattr(arr, "nbytes", 0) or 0)
        per: Dict[int, np.ndarray] = {}
        if local_only:
            for shard in arr.addressable_shards:
                # D2H of the LOCAL shard only: this np.asarray is the
                # per-host fence — it blocks until the shard's slots
                # finish and transfers just their bytes
                data = np.asarray(shard.data)  # ktlint: allow[KT018] the accessor itself
                start = shard.index[0].start or 0
                for j in range(data.shape[0]):
                    per[start + j] = data[j]
                bytes_read += int(data.nbytes)
        else:
            a = np.asarray(arr)  # ktlint: allow[KT018] the accessor itself
            for s in range(a.shape[0]):
                per[s] = a[s]
            bytes_read += int(a.nbytes)
        rows.append(per)
    return rows, bytes_read, bytes_total


@partial(jax.jit, static_argnames=("NR", "Z", "track", "zone_key", "ct_key"))
def _run_scan_many(consts_b, feas_b, init_b, NR: int, Z: int, track: bool,
                   zone_key: int, ct_key: int):
    """Megabatch kernel: B independent solve requests in ONE device dispatch.

    ``jax.vmap`` over the per-request (consts, feasibility-input, init)
    pytrees — every slot runs the same feasibility + scan program the single
    path runs, over its own tensors.  Slots cannot interact by construction:
    vmap introduces no cross-batch reductions, so a slot's result is a pure
    function of that slot's inputs (tests/test_megabatch.py pins per-request
    byte parity with serial solves and adversarial cross-tenant isolation).
    Feasibility runs inside the program (not eagerly per request) so the
    whole megabatch costs one dispatch + one fence.

    SHARDED megabatches need no kernel change: when the caller commits the
    stacked inputs with the slot-axis sharding (``_dispatch_prepared``
    with a mesh — dim 0 one-slot-per-chip, parallel/mesh.py slot_mesh),
    GSPMD partitions this very program on the batch dimension; the
    independence argument above is also why the partitioning introduces
    zero collectives (tests/test_megabatch_sharded.py pins parity and the
    every-chip placement)."""

    def one(consts, feas, init):
        F, dom_ok = compute_feasibility(
            feas["pm"], consts["requests"], feas["gp_ok"], feas["cand_vw"],
            feas["cand_vb"], consts["cand_alloc"], consts["cand_prov"],
            feas["key_check"], feas["dom_vw"], feas["dom_vb"],
            zone_key, ct_key,
        )
        consts = dict(consts, F=F, dom_ok=dom_ok)
        step = _make_step(consts, NR, Z, track)
        G = consts["counts"].shape[0]
        return jax.lax.scan(step, init, jnp.arange(G, dtype=jnp.int32))

    return jax.vmap(one)(consts_b, feas_b, init_b)


# ---------------------------------------------------------------------------
# host-facing API
# ---------------------------------------------------------------------------


@dataclass
class TpuSolveOutput:
    result: SolveResult
    takes: Optional[np.ndarray]  # [G, NR] pods placed per slot per group step
    n_used: int
    solve_ms: float
    compile_ms: float


class SlotsExhausted(Exception):
    """The optimistic NR axis ran out of node slots and the full-budget
    program is not compiled yet (see TpuSolver.solve raise_on_exhaust)."""

    def __init__(self, full_sig: tuple) -> None:
        super().__init__("node-slot estimate exhausted; full program cold")
        self.full_sig = full_sig


class MegaBucketMismatch(ValueError):
    """A megabatch flush's requests do not share one compile bucket (the
    caller's grouping raced a bucket-state change, or a direct caller
    over/mis-filled the slots).  The collector degrades the flush to serial
    per-request dispatches — clients must never see this."""


def _node_budget(st: SolveTensors, NE: int, max_nodes: Optional[int]) -> int:
    if max_nodes is None:
        max_nodes = NE + int(st.counts.sum())  # worst case: one pod per node
    return max(1, max_nodes)


def zone_share_matrix(st: SolveTensors, pad_g: int, Z: int) -> np.ndarray:
    """``[G+pad, Z]`` even split over each group's eligible zones — the
    counts-INdependent factor of :func:`host_count_arrays`, memoized on the
    tensors (like ``_nr_est_cache``): the hierarchical block builder
    (solver/hierarchy.py) rebuilds the suffix projections once per block
    per price wave and must not re-walk every group's zone requirements
    each time."""
    cache = getattr(st, "_zone_share_cache", None)
    key = (pad_g, Z)
    if cache is not None and cache[0] == key:
        return cache[1]
    G = st.G
    zone_share = np.zeros((G + pad_g, Z), dtype=np.float32)
    for gi, grp in enumerate(st.groups):
        vs = grp.requirements.get(L.ZONE)
        ok = np.zeros(Z, dtype=bool)
        for zi, zname in enumerate(st.zone_names):
            ok[zi] = vs.contains(zname)
        if not ok.any():
            ok[:] = True
        zone_share[gi] = ok.astype(np.float32) / float(ok.sum())
    st._zone_share_cache = (key, zone_share)
    return zone_share


def suffix_projection(demand_z: np.ndarray, count_z: np.ndarray):
    """``(suffix_res[G, Z, R], suffix_cnt[G, Z])`` — the later-group
    backfill suffix sums of per-zone demand.  Shared by
    :func:`host_count_arrays` and the hierarchical block builder's masked
    per-block recompute (one source for the cumsum orientation)."""
    suffix_res = np.concatenate(
        [np.cumsum(demand_z[::-1], axis=0)[::-1][1:],
         np.zeros((1,) + demand_z.shape[1:])]
    ).astype(np.float32)
    suffix_cnt = np.concatenate(
        [np.cumsum(count_z[::-1], axis=0)[::-1][1:],
         np.zeros((1, count_z.shape[1]))]
    ).astype(np.float32)
    return suffix_res, suffix_cnt


def host_count_arrays(st: SolveTensors, pad_g: int, Z: int):
    """The counts-dependent host tensors of one solve: padded counts +
    requests and the PER-ZONE suffix projection of later-group demand
    (suffix sums of count*request, distributed over each group's eligible
    zones) — the backfill available to fill slack on nodes bought for the
    current group, in resource units: 50 tiny pods cannot justify a big
    node the way 50 same-sized pods can, and a later group zone-pinned (or
    hard-spread) elsewhere cannot justify THIS zone's node at all.  The
    sequential oracle gets this for free by replaying demand zone by zone
    (designs/bin-packing.md:28-43); here the zone share is an even split
    over the group's eligible zones (node_selector folds into group
    requirements), which is exactly what a hard DoNotSchedule spread
    commits and a conservative, pool-conserving estimate for flexible
    groups.

    Factored out of ``_host_arrays`` because these are the ONLY group-side
    tensors that depend on the counts vector: the consolidation sweep
    (solver/consolidation.py) derives every candidate what-if from one
    shared base build and recomputes just this per candidate."""
    np_counts = np.pad(st.counts, (0, pad_g), constant_values=0)
    np_requests = np.pad(st.requests, ((0, pad_g), (0, 0)),
                         constant_values=0)
    demand = (np_counts[:, None] * np_requests).astype(np.float32)   # [G, R]
    zone_share = zone_share_matrix(st, pad_g, Z)
    demand_z = demand[:, None, :] * zone_share[:, :, None]           # [G, Z, R]
    count_z = np_counts[:, None].astype(np.float32) * zone_share     # [G, Z]
    np_suffix_res, np_suffix_cnt = suffix_projection(demand_z, count_z)
    return np_counts, np_requests, np_suffix_res, np_suffix_cnt


class TpuSolver:
    """Builds and caches the jitted solve for a tensor shape signature.

    Compile-readiness is tracked per signature (the padded-dims key from
    ``solve_dims``): ``ready()`` tells the scheduler whether a solve of this
    shape will hit the jit cache or stall ~tens of seconds in XLA, and
    ``warm_async()`` compiles a signature on a background thread — the
    scheduler's compile-behind fallback and the operator's startup warmup
    both ride it.  The reference bar is the Go FFD's zero-warmup ms-scale
    first solve (designs/bin-packing.md:28-43): callers must never eat a
    cold compile."""

    #: at most this many concurrent background compiles; extras queue (FIFO,
    #: bounded) and start as slots free up
    MAX_CONCURRENT_WARMS = 2
    MAX_QUEUED_WARMS = 8
    #: a shape whose background compile failed is not retried for this long
    #: (prevents a deterministically-failing compile from burning a full
    #: compile of CPU on every solve of that shape)
    WARM_FAILURE_BACKOFF = 300.0

    def __init__(self, clock: Optional[Clock] = None) -> None:
        import threading

        # persistent AOT compile cache (KT_JIT_CACHE): every process that
        # constructs a solver shares previously compiled XLA programs —
        # a restarted replica skips the ~8s compile (ROADMAP item 2's
        # shared-cache story; bench.py measure_cold_restart gates it)
        _init_jit_cache()
        # injectable clock for the warm-failure backoff (tests advance a
        # FakeClock past WARM_FAILURE_BACKOFF instead of sleeping it out)
        self._clock = clock or Clock()
        # fault-injection plane (docs/RESILIENCE.md): null + falsy unless
        # KT_FAULTS configures a chaos schedule — the dispatch/fence choke
        # points below guard with one truthiness check
        self._faults = faults_mod.plane()
        self._lock = threading.Lock()
        self._ready: set = set()                     # guarded-by: _lock
        self._compiling: set = set()                 # guarded-by: _lock
        self._queued: list = []                      # guarded-by: _lock  [(sig, kwargs)]
        self._failed_until: Dict[tuple, float] = {}  # guarded-by: _lock
        self._stopped = False                        # guarded-by: _lock  stop_warms(): no new spawns
        # shape families whose optimistic NR estimate exhausted at least
        # once: their signature permanently resolves to the full-budget
        # dims, so readiness checks / warmups / solves all target the
        # program that will actually serve them (no per-solve double run)
        self._nr_exhausted: set = set()              # guarded-by: _lock

    # ---- compile-readiness ----------------------------------------------
    def signature(
        self,
        st: SolveTensors,
        *,
        existing_nodes: Sequence[SimNode] = (),
        max_nodes: Optional[int] = None,
        track_assignments: bool = True,
        mesh=None,
    ) -> tuple:
        NE = len(existing_nodes)
        a, b = _mesh_divs(mesh)
        node_budget = _node_budget(st, NE, max_nodes)
        dims = solve_dims(
            st, NE=NE, node_budget=node_budget,
            a=a, b=b, track=track_assignments,
        )
        key = _dims_key(dims)
        with self._lock:
            exhausted = key in self._nr_exhausted
        if exhausted:
            key = _dims_key(solve_dims(
                st, NE=NE, node_budget=node_budget,
                a=a, b=b, track=track_assignments, full_nr=True,
            ))
        return key

    def mega_signature(
        self,
        st: SolveTensors,
        *,
        existing_nodes: Sequence[SimNode] = (),
        max_nodes: Optional[int] = None,
        track_assignments: bool = True,
        slots: int = 2,
        mesh=None,
    ) -> tuple:
        """Compile signature of the megabatch program that would serve a
        ``slots``-request batch of this shape: the single-solve dims key plus
        the padded request-slot rung and the vocab positions of the zone/ct
        keys (static args of the vmapped kernel — two catalogs interning the
        keys differently are different programs AND different buckets).

        ``mesh`` is the SHARDED megabatch: per-slot dims stay the
        single-device ones (each slot runs whole on one chip — the slot
        axis, not the tensor axes, is what shards), the slot rung floors at
        the device count, and the mesh's (axis, size) fingerprint joins the
        key — the partitioned program is a different XLA binary AND a
        different coalescer bucket than the single-device one."""
        base = self.signature(
            st, existing_nodes=existing_nodes, max_nodes=max_nodes,
            track_assignments=track_assignments,
        )
        return base + _mega_key_tail(
            slots, st.vocab.key_id[L.ZONE], st.vocab.key_id[L.CAPACITY_TYPE],
            mesh,
        )

    def ready(self, sig: tuple) -> bool:
        with self._lock:
            return sig in self._ready

    def compiling(self, sig: tuple) -> bool:
        with self._lock:
            return sig in self._compiling

    def warm_pending(self, sig: tuple) -> bool:
        """A warm for ``sig`` is already compiling, queued, or in its
        failure backoff — admitting another would be refused, so callers
        can skip preparing its (potentially expensive) inputs."""
        with self._lock:
            return (sig in self._compiling
                    or any(s == sig for s, _ in self._queued)
                    or self._clock.now() < self._failed_until.get(sig, 0.0))

    def compiles_in_flight(self) -> int:
        with self._lock:
            return len(self._compiling)

    def warm_idle(self) -> bool:
        """No background compile running or queued."""
        with self._lock:
            return not self._compiling and not self._queued

    def stop_warms(self) -> None:
        """Drop all queued warms and stop the drain (operator shutdown):
        exit then waits only for the compiles already in flight, never the
        queue."""
        with self._lock:
            self._stopped = True
            self._queued.clear()

    def _mark_ready(self, sig: tuple) -> None:
        # NOTE: deliberately does NOT discard the sig from _compiling — a
        # warm thread for this sig may still be mid-flight, and the
        # "compiles_in_flight() == 0 implies every on_done ran" invariant
        # (watchers poll it, then read the compile metrics) requires the
        # warm thread itself to clear its entry AFTER its on_done callback
        with self._lock:
            self._ready.add(sig)

    def warm_async(
        self,
        st: SolveTensors,
        *,
        existing_nodes: Sequence[SimNode] = (),
        max_nodes: Optional[int] = None,
        track_assignments: bool = True,
        mesh=None,
        on_done=None,
        slots: Optional[int] = None,
    ) -> bool:
        """Compile this solve's signature on a background thread (running
        the full solve and discarding the result — compile dominates).
        Returns True when the warm was accepted (started or queued), False
        when the signature is already ready/compiling/queued, is in its
        failure backoff, or the queue is full.  ``on_done(sig, seconds,
        error)`` fires from the worker thread when the warm ends.
        ``slots`` > 1 warms the MEGABATCH program at that request-slot rung
        instead of the single-solve program; with ``mesh`` that is the
        SHARDED megabatch program (slot axis over the flattened mesh)."""
        if slots and slots > 1:
            sig = self.mega_signature(
                st, existing_nodes=existing_nodes, max_nodes=max_nodes,
                track_assignments=track_assignments, slots=slots, mesh=mesh,
            )
        else:
            slots = None
            sig = self.signature(
                st, existing_nodes=existing_nodes, max_nodes=max_nodes,
                track_assignments=track_assignments, mesh=mesh,
            )
        kwargs = dict(
            st=st, existing_nodes=existing_nodes, max_nodes=max_nodes,
            track_assignments=track_assignments, mesh=mesh, on_done=on_done,
            slots=slots,
        )
        return self._admit_warm(sig, kwargs)

    def warm_custom(self, sig, thunk, on_done=None) -> bool:
        """Background-compile an arbitrary prepared device program on the
        warm machinery (concurrency cap, bounded queue, failure backoff):
        ``thunk()`` must run — and thereby compile + ``_mark_ready`` — the
        program ``sig`` names.  The consolidation sweep uses this to warm
        its shared-base vmapped what-if program while serving the first
        sweeps serially (the compile-behind contract)."""
        return self._admit_warm(sig, dict(on_done=on_done, thunk=thunk))

    def _admit_warm(self, sig: tuple, kwargs: dict) -> bool:
        with self._lock:
            if self._stopped:
                return False
            if sig in self._ready or sig in self._compiling:
                return False
            if any(s == sig for s, _ in self._queued):
                return False
            if self._clock.now() < self._failed_until.get(sig, 0.0):
                return False  # recent compile failure: back off
            if len(self._compiling) >= self.MAX_CONCURRENT_WARMS:
                if len(self._queued) >= self.MAX_QUEUED_WARMS:
                    return False
                self._queued.append((sig, kwargs))
                return True
            self._compiling.add(sig)
        self._spawn_warm(sig, kwargs)
        return True

    def _spawn_warm(self, sig: tuple, kwargs: dict) -> None:
        import threading

        on_done = kwargs.pop("on_done")
        slots = kwargs.pop("slots", None)
        thunk = kwargs.pop("thunk", None)

        def work():
            t0 = time.perf_counter()
            err = None
            try:
                if thunk is not None:
                    # custom prepared program (warm_custom): the thunk owns
                    # compilation AND the _mark_ready of its signature
                    thunk()
                elif slots:
                    # megabatch warm: one request padded up to the slot rung
                    # compiles exactly the program a full batch will run
                    # (with a mesh, the SHARDED rung program)
                    warm_mesh = kwargs.pop("mesh", None)
                    outs = self.solve_many([dict(kwargs)], min_slots=slots,
                                           mesh=warm_mesh)
                    if isinstance(outs[0], Exception):
                        raise outs[0]
                else:
                    self.solve(**kwargs)
            # ktlint: allow[KT005] compile failure is surfaced via on_done
            # (the scheduler's callback logs it) and arms the retry backoff
            except Exception as e:  # pragma: no cover - surfaced via on_done
                err = e
                with self._lock:
                    self._failed_until[sig] = self._clock.now() + self.WARM_FAILURE_BACKOFF
            try:
                if on_done is not None:
                    on_done(sig, time.perf_counter() - t0, err)
            except Exception:  # a throwing callback must not wedge the tier
                import logging as _logging

                _logging.getLogger(__name__).warning(
                    "warm on_done callback raised", exc_info=True
                )
            finally:
                # clear the in-flight entry only AFTER on_done: watchers
                # poll compiles_in_flight() down to 0 and then read the
                # metrics the callback records — dropping the count first
                # is a race.  In a finally (with the callback exception
                # swallowed above) so neither the entry leaks nor the queue
                # drain below is skipped — either would permanently consume
                # a MAX_CONCURRENT_WARMS slot
                with self._lock:
                    self._compiling.discard(sig)
            # drain: start the next queued warm that is still cold — unless
            # the process is exiting (threading._shutdown is joining us: the
            # main thread is gone) or stop_warms() ran; exit must wait only
            # for compiles already in flight, never the whole queue
            import threading as _threading

            while True:
                with self._lock:
                    if (self._stopped
                            or not _threading.main_thread().is_alive()
                            or not self._queued
                            or len(self._compiling) >= self.MAX_CONCURRENT_WARMS):
                        return
                    next_sig, next_kwargs = self._queued.pop(0)
                    if next_sig in self._ready:
                        continue  # compiled by a direct solve meanwhile
                    self._compiling.add(next_sig)
                self._spawn_warm(next_sig, next_kwargs)
                return

        # NON-daemon: a daemon thread hard-killed at interpreter exit while
        # inside an XLA compile aborts the whole process (std::terminate);
        # a non-daemon thread instead delays exit until the compile lands,
        # which is the safe behavior for operator shutdown and CLI runs
        threading.Thread(target=work, name="tpu-solver-warm").start()

    def _host_arrays(
        self,
        st: SolveTensors,
        existing_nodes: Sequence[SimNode],
        *,
        node_budget: int,
        track_assignments: bool,
        full_nr: bool,
        a: int = 1,
        b: int = 1,
        dims: Optional[dict] = None,
    ):
        """Pure-host (numpy) build of one solve's padded tensors: returns
        ``(np_consts, feas, np_init, dims)`` with every value a numpy array.
        The SINGLE source of the padding/bucketing both device paths share:
        :meth:`prepare` (single solve — device placement + feasibility
        precompute) and :meth:`solve_many` (megabatch — slot-stacked arrays,
        feasibility inside the vmapped program) each consume this, so the
        two programs can never pad a batch differently.  No device ops run
        here (``feas`` carries the feasibility INPUTS, not F).

        ``dims`` overrides the :func:`solve_dims` bucketing with caller-
        chosen padded dimensions (the consolidation sweep's fine-grained
        small-solve rungs) — callers own the compile-ladder consequences."""
        G, C, D, R = st.G, max(1, st.C), st.D, st.R
        S, Z = st.S, max(1, st.n_zones)
        K, W = st.pm.shape[1], st.pm.shape[2]
        NE = len(existing_nodes)

        # ---- shape bucketing + mesh padding ------------------------------
        # The scan compiles per (G, C, NR, ...) signature; rung-bucketing the
        # axes (linear quanta for small shapes, geometric beyond — see _rung)
        # makes repeated controller solves hit the persistent jit cache
        # instead of paying a fresh XLA compile per batch shape, and keeps
        # the total rung ladder small enough to precompile (warm_async).
        if dims is None:
            dims = solve_dims(st, NE=NE, node_budget=node_budget, a=a, b=b,
                              track=track_assignments, full_nr=full_nr)
        pad_g = dims["G"] - G
        pad_c = dims["C"] - C
        pad_s = dims["S"] - S
        NR = dims["NR"]

        def _pad(arr, n, axis, value):
            if n == 0:
                return arr
            widths = [(0, 0)] * arr.ndim
            widths[axis] = (0, n)
            return np.pad(arr, widths, constant_values=value)

        np_counts, np_requests, np_suffix_res, np_suffix_cnt = (
            host_count_arrays(st, pad_g, Z))
        np_pm = _pad(st.pm, pad_g, 0, 0)
        np_gzs = _pad(st.g_zone_spread, pad_g, 0, -1)
        np_gzk = _pad(st.g_zone_skew, pad_g, 0, 1)
        np_ghs = _pad(st.g_host_spread, pad_g, 0, -1)
        np_ghc = _pad(st.g_host_cap, pad_g, 0, 0)
        np_gza = _pad(st.g_zone_anti, pad_g, 0, -1)
        np_gzp = _pad(st.g_zone_paff, pad_g, 0, -1)
        np_ghp = _pad(st.g_host_paff, pad_g, 0, -1)
        np_gsm = _pad(_pad(st.g_sel_match, pad_g, 1, False), pad_s, 0, False)
        np_gp_ok = _pad(st.gp_ok, pad_g, 0, False)
        np_cvw = _pad(st.cand_vw, pad_c, 0, 0)
        np_cvb = _pad(st.cand_vb, pad_c, 0, 0)
        np_calloc = _pad(st.cand_alloc, pad_c, 0, 0)
        np_ccap = _pad(st.cand_cap, pad_c, 0, 0)
        np_cprov = _pad(st.cand_prov, pad_c, 0, 0)
        np_cprice = _pad(st.cand_price, pad_c, 0, np.float32(3.0e38))
        np_cavail = _pad(st.cand_avail, pad_c, 0, False)
        G = G + pad_g
        S = S + pad_s

        # ---- existing-node tensors (host-side compat precompute) -------
        NE_pad = dims["NE_pad"]  # rung-bucketed: stable jit shapes
        P_pad = dims["P"]
        ex_res = np.zeros((NR, R), dtype=np.float32)
        ex_zone = np.zeros(NR, dtype=np.int32)
        ex_sel = np.zeros((NR, S), dtype=np.int32)
        ex_ok = np.zeros((G, NE_pad), dtype=bool)
        ex_price = np.zeros(NR, dtype=np.float32)
        zone_index = {z: i for i, z in enumerate(st.zone_names)}
        zc0 = np.zeros((S, Z), dtype=np.int32)
        tot0 = np.zeros(S, dtype=np.int32)
        prov_used0 = np.zeros((P_pad, R), dtype=np.float32)
        prov_index = {n: i for i, n in enumerate(st.prov_names)}

        # limits bind on raw machine CAPACITY (st.capacity_row; the
        # independent validator agrees) — fuzz seed 23
        for ni, node in enumerate(existing_nodes):
            ex_res[ni] = st.vocab.resources_to_row(node.remaining()).astype(np.float32)
            ex_zone[ni] = zone_index.get(node.zone, 0)
            ex_price[ni] = node.price
            pi = prov_index.get(node.provisioner)
            if pi is not None:
                prov_used0[pi] += st.capacity_row(node.instance_type,
                                                  node.allocatable)
            for gi, g in enumerate(st.groups):
                rep = g.pods[0]
                ex_ok[gi, ni] = (
                    not any(t.blocks(rep.tolerations) for t in node.taints)
                    and g.requirements.compatible(node.labels) is None
                )
        # selector counts on existing nodes + zone counters
        for si, (sel, topo, kind) in enumerate(st.selector_defs):
            for ni, node in enumerate(existing_nodes):
                n_match = sum(1 for p in node.pods if sel.matches(p.labels))
                ex_sel[ni, si] = n_match
                zc0[si, zone_index.get(node.zone, 0)] += n_match
                tot0[si] += n_match

        np_consts = dict(
            counts=np_counts,
            suffix_res=np_suffix_res,
            suffix_cnt=np_suffix_cnt,
            requests=np_requests,
            g_zone_spread=np_gzs,
            g_zone_skew=np_gzk,
            g_host_spread=np_ghs,
            g_host_cap=np_ghc,
            g_zone_anti=np_gza,
            g_zone_paff=np_gzp,
            g_host_paff=np_ghp,
            g_sel_match=np_gsm,
            cand_alloc=np_calloc,
            cand_cap=np_ccap,
            cand_prov=np_cprov,
            cand_price=np.where(np.isinf(np_cprice), np.float32(3.0e38),
                                np_cprice).astype(np.float32),
            cand_avail=np_cavail,
            prov_limits=_pad(
                np.where(np.isinf(st.prov_limits), np.float32(3.0e38),
                         st.prov_limits).astype(np.float32),
                P_pad - st.prov_limits.shape[0], 0, np.float32(3.0e38),
            ),
            dom_zone=st.dom_zone,
            ex_ok=ex_ok,
            node_budget=np.int32(node_budget),
        )
        feas = dict(
            pm=np_pm,
            gp_ok=np_gp_ok,
            cand_vw=np_cvw,
            cand_vb=np_cvb,
            key_check=st.key_check,
            dom_vw=st.dom_vw,
            dom_vb=st.dom_vb,
        )
        np_init = (
            ex_res,                                  # res
            ex_zone,                                 # row_zone
            np.full(NR, -1, dtype=np.int32),         # row_dom
            np.full(NR, -1, dtype=np.int32),         # row_cand
            ex_price,                                # row_price
            ex_sel,                                  # selcnt
            np.arange(NR) < NE,                      # active
            np.int32(NE),                            # n_used
            zc0,                                     # zc
            tot0,                                    # tot
            prov_used0,                              # prov_used
            np.zeros(G, dtype=np.int32),             # infeasible
        )
        return np_consts, feas, np_init, dims

    def prepare(
        self,
        st: SolveTensors,
        *,
        existing_nodes: Sequence[SimNode] = (),
        max_nodes: Optional[int] = None,
        track_assignments: bool = True,
        mesh=None,
        full_nr: bool = False,
    ):
        """Build (run_fn, init_carry).  ``mesh`` shards the group/candidate/
        node-slot axes over a jax.sharding.Mesh (parallel/mesh.py layout)."""
        NE = len(existing_nodes)
        node_budget = _node_budget(st, NE, max_nodes)
        a, b = _mesh_divs(mesh)
        np_consts, feas, np_init, dims = self._host_arrays(
            st, existing_nodes, node_budget=node_budget,
            track_assignments=track_assignments, full_nr=full_nr, a=a, b=b,
        )
        NR, Z = dims["NR"], dims["Z"]

        consts = {k: jnp.asarray(v) for k, v in np_consts.items()}

        zone_key = st.vocab.key_id[L.ZONE]
        ct_key = st.vocab.key_id[L.CAPACITY_TYPE]

        if mesh is not None:
            from ..parallel.distributed import put_sharded
            from ..parallel.mesh import POD_AXIS, TYPE_AXIS, axis_sharding

            # cached construction (parallel/mesh.py): sharding objects are
            # built once per (mesh, spec), not once per solve (KT011)
            sg = axis_sharding(mesh, POD_AXIS)     # group axis
            sc = axis_sharding(mesh, TYPE_AXIS)    # candidate axis
            sr = axis_sharding(mesh)               # replicated
            place = {
                "counts": sg, "requests": sg, "suffix_res": sg,
                "suffix_cnt": sg,
                "g_zone_spread": sg, "g_zone_skew": sg,
                "g_host_spread": sg, "g_host_cap": sg, "g_zone_anti": sg,
                "g_zone_paff": sg, "g_host_paff": sg,
                "g_sel_match": sr, "cand_alloc": sc, "cand_cap": sc,
                "cand_prov": sc, "cand_price": sc, "cand_avail": sc,
                "prov_limits": sr, "dom_zone": sr, "ex_ok": sg,
            }
            consts = {k: put_sharded(v, place.get(k, sr)) for k, v in consts.items()}

        if mesh is not None and jax.process_count() > 1:
            # multi-process: eager per-op execution on non-addressable global
            # arrays is not allowed — run the feasibility precompute as one
            # jitted SPMD program over explicitly placed inputs
            from ..parallel.distributed import put_sharded

            F, dom_ok = feasibility_jit(
                put_sharded(feas["pm"], sg), consts["requests"],
                put_sharded(feas["gp_ok"], sg),
                put_sharded(feas["cand_vw"], sc),
                put_sharded(feas["cand_vb"], sc), consts["cand_alloc"],
                consts["cand_prov"], put_sharded(feas["key_check"], sr),
                put_sharded(feas["dom_vw"], sr),
                put_sharded(feas["dom_vb"], sr),
                zone_key=zone_key, ct_key=ct_key,
            )
        elif mesh is not None:
            # single-process mesh: eager compute respects the consts'
            # explicit shardings (GSPMD layout is driven by input placement)
            F, dom_ok = compute_feasibility(
                jnp.asarray(feas["pm"]), consts["requests"],
                jnp.asarray(feas["gp_ok"]), jnp.asarray(feas["cand_vw"]),
                jnp.asarray(feas["cand_vb"]), consts["cand_alloc"],
                consts["cand_prov"], jnp.asarray(feas["key_check"]),
                jnp.asarray(feas["dom_vw"]), jnp.asarray(feas["dom_vb"]),
                zone_key, ct_key,
            )
        else:
            # single-device: the module-level jitted program replaces ~a
            # dozen eager op dispatches per solve (each ~host-ms on the
            # serving path); compare ops and exact bf16 bit-counts make the
            # jitted result byte-identical to the eager one
            F, dom_ok = feasibility_jit(
                jnp.asarray(feas["pm"]), consts["requests"],
                jnp.asarray(feas["gp_ok"]), jnp.asarray(feas["cand_vw"]),
                jnp.asarray(feas["cand_vb"]), consts["cand_alloc"],
                consts["cand_prov"], jnp.asarray(feas["key_check"]),
                jnp.asarray(feas["dom_vw"]), jnp.asarray(feas["dom_vb"]),
                zone_key=zone_key, ct_key=ct_key,
            )
        consts["F"], consts["dom_ok"] = F, dom_ok

        init = tuple(jnp.asarray(v) for v in np_init)
        if mesh is not None:
            from ..parallel.distributed import put_sharded
            from ..parallel.mesh import POD_AXIS, axis_sharding

            sn = axis_sharding(mesh, POD_AXIS)   # node-slot axis
            sr = axis_sharding(mesh)
            shardings = (sn, sn, sn, sn, sn, sn, sn, sr, sr, sr, sr, sr)
            init = tuple(put_sharded(a, s) for a, s in zip(init, shardings))

        def run(init):
            return _run_scan(consts, init, NR, Z, track_assignments)

        return run, init, NE

    def _prepare_dispatch(
        self, st: SolveTensors, existing_nodes, max_nodes,
        track_assignments: bool, mesh, full_nr: bool,
    ):
        """Shared dispatch preamble for ``solve`` and ``solve_async`` —
        the SINGLE source of the dims/bucketing/exhausted-promotion steps,
        so the synchronous and pipelined paths can never run different
        programs for the same batch.  Returns
        ``(run, init, NE, est_dims, full_dims, full_nr)``; ``run(init)``
        has NOT been called."""
        a, b = _mesh_divs(mesh)
        NE0 = len(existing_nodes)
        node_budget = _node_budget(st, NE0, max_nodes)
        est_dims = solve_dims(st, NE=NE0, node_budget=node_budget, a=a, b=b,
                              track=track_assignments)
        full_dims = solve_dims(st, NE=NE0, node_budget=node_budget, a=a, b=b,
                               track=track_assignments, full_nr=True)
        if not full_nr:
            # shape families that exhausted the optimistic NR before go
            # straight to the full program (see _nr_exhausted)
            with self._lock:
                full_nr = _dims_key(est_dims) in self._nr_exhausted
        run, init, NE = self.prepare(
            st, existing_nodes=existing_nodes, max_nodes=max_nodes,
            track_assignments=track_assignments, mesh=mesh, full_nr=full_nr,
        )
        return run, init, NE, est_dims, full_dims, full_nr

    # ktlint: fence reads two scalars off the finished carry to decide the
    # slot-exhaustion retry — the solve is already fenced by its caller
    def _maybe_retry_exhausted(
        self, carry, est_dims: dict, full_dims: dict, full_nr: bool,
        raise_on_exhaust: bool, retry,
    ) -> Optional["TpuSolveOutput"]:
        """Slot-exhaustion epilogue, the SINGLE source of the retry protocol
        shared by ``solve`` and ``PendingTpuSolve.result``: when the
        optimistic NR axis genuinely ran out of node slots AND left pods
        unplaced, remember the shape family (``_nr_exhausted``), honor
        ``raise_on_exhaust`` (the compile-behind contract), register the
        inline full-budget compile so a concurrent ``warm_async`` of the
        same shape doesn't spawn a duplicate XLA compile, and run
        ``retry()`` (a full-budget re-solve).  Returns None when the solve
        stands.  Rare by construction — the estimate is doubled — so steady
        state keeps the small fast program."""
        if full_nr or est_dims["NR"] >= full_dims["NR"]:
            return None
        n_used_v = int(np.asarray(carry[7]))
        infeasible_v = int(np.asarray(carry[11]).sum())
        if n_used_v < est_dims["NR"] or infeasible_v <= 0:
            return None
        full_key = _dims_key(full_dims)
        with self._lock:
            self._nr_exhausted.add(_dims_key(est_dims))
            full_ready = full_key in self._ready
        if raise_on_exhaust and not full_ready:
            raise SlotsExhausted(full_key)
        with self._lock:
            inline_compile = full_key not in self._compiling
            if inline_compile:
                self._compiling.add(full_key)
        try:
            return retry()
        finally:
            if inline_compile:
                with self._lock:
                    self._compiling.discard(full_key)

    # ktlint: fence the synchronous solve IS the sync point — dispatch, the
    # one-RTT D2H fence, and the measured re-run all live here by contract
    def solve(
        self,
        st: SolveTensors,
        *,
        existing_nodes: Sequence[SimNode] = (),
        max_nodes: Optional[int] = None,
        track_assignments: bool = True,
        mesh=None,
        measure: bool = False,
        full_nr: bool = False,
        raise_on_exhaust: bool = False,
        trace=None,
    ) -> TpuSolveOutput:
        """One device solve.  ``measure=True`` adds a second, results-discarded
        execution with fenced timing (benchmarks only — production controller
        solves must pay exactly one device execution; VERDICT r1 weak #4).

        ``raise_on_exhaust=True`` raises :class:`SlotsExhausted` instead of
        inline-compiling the full-budget program when the optimistic NR axis
        ran out of slots and the full program is not compiled yet — the
        scheduler catches it and serves the solve from the warm tier while
        the full program compiles behind (the 'callers must never eat a cold
        compile' contract)."""
        t0 = time.perf_counter()
        trace = trace or NULL_TRACE
        with trace.span("device_prepare"):
            run, init, NE, est_dims, full_dims, full_nr = self._prepare_dispatch(
                st, existing_nodes, max_nodes, track_assignments, mesh, full_nr,
            )
        with trace.span("device_execute", full_nr=full_nr):
            if self._faults:
                self._faults.fire("dispatch")     # dispatch_exc raises here
            carry, ys = run(init)
            if self._faults:
                effect = self._faults.fire("fence")  # device_hang raises
                if effect is not None and effect.kind == "slow_fence":
                    self._faults.sleep(effect)
            np.asarray(carry[7])  # D2H fence; see timing note below
        compile_ms = (time.perf_counter() - t0) * 1000.0
        solve_ms = compile_ms
        # mark ready the key of the program that ACTUALLY compiled (a fresh
        # signature() could race a concurrent _nr_exhausted insert and mark
        # the full program ready when only the estimated one compiled)
        self._mark_ready(_dims_key(full_dims if full_nr else est_dims))

        # slot-exhaustion retry: NR is sized by an optimistic estimate
        # (_nr_estimate); see _maybe_retry_exhausted for the protocol
        retried = self._maybe_retry_exhausted(
            carry, est_dims, full_dims, full_nr, raise_on_exhaust,
            lambda: self.solve(
                st, existing_nodes=existing_nodes, max_nodes=max_nodes,
                track_assignments=track_assignments, mesh=mesh,
                measure=measure, full_nr=True,
            ),
        )
        if retried is not None:
            return retried

        if measure:
            # Timing run, results discarded.  Two quirks of the tunneled
            # device runtime make the naive re-run dishonest: block_until_ready
            # can acknowledge before execution completes (so we fence with a
            # tiny D2H read, ~one RTT), and executions with bit-identical
            # inputs can be deduped to ~0ms (so the re-run gets an
            # epsilon-shifted input).
            init2 = (init[0] + jnp.float32(1e-9),) + tuple(init[1:])
            t1 = time.perf_counter()
            carry2, _ys2 = run(init2)
            np.asarray(carry2[7])
            solve_ms = (time.perf_counter() - t1) * 1000.0

        with trace.span("extract"):
            return self._extract(
                st, carry, ys if track_assignments else None, existing_nodes,
                NE, solve_ms, compile_ms,
            )

    def solve_async(
        self,
        st: SolveTensors,
        *,
        existing_nodes: Sequence[SimNode] = (),
        max_nodes: Optional[int] = None,
        track_assignments: bool = True,
        mesh=None,
        raise_on_exhaust: bool = False,
        trace=None,
    ) -> "PendingTpuSolve":
        """Dispatch one device solve WITHOUT fencing.

        JAX dispatch is asynchronous: ``run(init)`` enqueues the H2D
        transfers (double-buffered ``device_put`` of this batch's tensors)
        and the scan, then returns while the device may still be executing
        the PREVIOUS batch.  The caller keeps the host free — typically to
        tensorize batch N+1 while batch N computes — and later calls
        :meth:`PendingTpuSolve.result` to fence and extract.  Callers are
        expected to dispatch only shapes that are already compiled
        (``ready()``); a cold shape compiles inline at dispatch, stalling
        the pipeline exactly like a cold ``solve`` would."""
        t0 = time.perf_counter()
        trace = trace or NULL_TRACE
        with trace.span("device_dispatch"):
            run, init, NE, est_dims, full_dims, full_nr = self._prepare_dispatch(
                st, existing_nodes, max_nodes, track_assignments, mesh,
                full_nr=False,
            )
            if self._faults:
                self._faults.fire("dispatch")  # dispatch_exc raises here
            carry, ys = run(init)  # async: enqueued, not fenced
        return PendingTpuSolve(
            solver=self, st=st, existing_nodes=existing_nodes, NE=NE,
            carry=carry, ys=ys, t0=t0, track=track_assignments,
            est_dims=est_dims, full_dims=full_dims, full_nr=full_nr,
            raise_on_exhaust=raise_on_exhaust,
            solve_kwargs=dict(
                existing_nodes=existing_nodes, max_nodes=max_nodes,
                track_assignments=track_assignments, mesh=mesh,
            ),
            trace=trace,
        )

    def solve_many_async(
        self,
        requests: Sequence[dict],
        *,
        min_slots: Optional[int] = None,
        mesh=None,
        target_dims: Optional[dict] = None,
        registry=None,
    ) -> "PendingMegaSolve":
        """Dispatch B independent, signature-compatible solve requests as
        ONE vmapped device program over padded request slots, WITHOUT
        fencing — the continuous-batching analog of :meth:`solve_async`:
        the caller (SolvePipeline via the scheduler's collector) coalesces
        and tensorizes megabatch N+1 while megabatch N executes, then calls
        :meth:`PendingMegaSolve.results` for the single batch-wide fence.

        Each request is a dict with ``st`` (required) and optionally
        ``existing_nodes``, ``max_nodes``, ``track_assignments``,
        ``raise_on_exhaust``, ``trace``.  Every request must resolve to the
        SAME :meth:`mega_signature` bucket (the scheduler's coalescer groups
        by it; asserted here).  The batch axis pads up to the power-of-two
        slot rung (``_mega_rung``; ``min_slots`` forces a larger rung — the
        warm path compiles the full-batch program from one request); padding
        slots replicate request 0 and their outputs are discarded — vmap
        slots are independent by construction, so padding can never leak
        into a real request's result.

        ``mesh`` serves the batch SHARDED: the slot axis becomes a
        data-parallel dimension over the flattened mesh (one slot per chip,
        parallel/mesh.py slot_mesh), so a mesh-configured scheduler's
        coalesced flush lights every device — still ONE dispatch and ONE
        batch-wide fence (per-HOST fences on a multi-process mesh: each
        serving process reads only its addressable slot shards).  Per-slot
        programs are the single-device ones (results byte-identical to
        unmeshed serial solves).

        ``target_dims`` builds every request at caller-chosen padded dims
        (a UNIFIED mixed-bucket flush: dominated requests pad up to the
        dominant bucket's rungs — see :func:`unify_mega_keys`); the usual
        per-request `solve_dims` bucketing is bypassed, so callers own the
        compile-ladder consequences (the `_host_arrays(dims=...)`
        contract).  ``registry`` observes the per-host fence metrics."""
        assert requests, "empty megabatch"
        if len(requests) > MEGA_MAX_SLOTS:
            # a silent truncation would compile at shape B while marking the
            # rung-32 signature ready — callers (the pipeline's coalescer)
            # clamp to MEGA_MAX_SLOTS; a direct caller must too
            raise MegaBucketMismatch(
                f"{len(requests)} requests exceed MEGA_MAX_SLOTS="
                f"{MEGA_MAX_SLOTS}")
        t0 = time.perf_counter()
        defaults = dict(
            existing_nodes=(), max_nodes=None, track_assignments=True,
            raise_on_exhaust=False, trace=NULL_TRACE,
        )
        reqs = [{**defaults, **r} for r in requests]
        n_slots = max(len(reqs), min_slots or 1)
        # ONE snapshot of the exhausted families for the whole call: a
        # background warm thread flipping _nr_exhausted mid-flush must not
        # make the per-request dims diverge (the single path guards the
        # same race in solve(); see _mark_ready's comment there)
        with self._lock:
            exhausted = set(self._nr_exhausted)
        track = reqs[0]["track_assignments"]
        zone_key = reqs[0]["st"].vocab.key_id[L.ZONE]
        ct_key = reqs[0]["st"].vocab.key_id[L.CAPACITY_TYPE]

        entries = []
        for r in reqs:
            st = r["st"]
            NE = len(r["existing_nodes"])
            nb = _node_budget(st, NE, r["max_nodes"])
            est_dims = solve_dims(st, NE=NE, node_budget=nb, track=track)
            full_dims = solve_dims(st, NE=NE, node_budget=nb, track=track,
                                   full_nr=True)
            full_nr = _dims_key(est_dims) in exhausted
            np_consts, feas, np_init, dims = self._host_arrays(
                st, r["existing_nodes"], node_budget=nb,
                track_assignments=track, full_nr=full_nr,
                # unified flush: every request pads to the dominant
                # bucket's rungs, so one program serves the mixed batch
                dims=dict(target_dims) if target_dims is not None else None,
            )
            entries.append(dict(
                r=r, np_consts=np_consts, feas=feas, np_init=np_init,
                dims=dims, est_dims=est_dims, full_dims=full_dims,
                full_nr=full_nr, NE=NE,
            ))
        return self._dispatch_prepared(entries, n_slots=n_slots, track=track,
                                       zone_key=zone_key, ct_key=ct_key,
                                       t0=t0, mesh=mesh, registry=registry)

    def solve_many_prepared(
        self,
        entries: Sequence[dict],
        *,
        min_slots: Optional[int] = None,
        mesh=None,
        registry=None,
    ) -> "PendingMegaSolve":
        """Dispatch PRE-BUILT megabatch entries as one vmapped device
        program, without fencing — the consolidation sweep's entry point:
        it derives every candidate's entry from ONE shared base build
        (solver/consolidation.py build_sweep_entries) instead of paying a
        per-request ``_host_arrays``.  Each entry carries the same fields
        :meth:`solve_many_async` builds internally (``r``, ``np_consts``,
        ``feas``, ``np_init``, ``dims``, ``est_dims``, ``full_dims``,
        ``full_nr``, ``NE``); all entries must share one dims bucket."""
        if not entries:
            # typed like every other megabatch-construction failure (the
            # collector degrades these to serial dispatches) — a bare
            # assert vanishes under python -O and decays to an IndexError
            raise MegaBucketMismatch("empty megabatch")
        if len(entries) > MEGA_MAX_SLOTS:
            raise MegaBucketMismatch(
                f"{len(entries)} entries exceed MEGA_MAX_SLOTS="
                f"{MEGA_MAX_SLOTS}")
        t0 = time.perf_counter()
        r0 = entries[0]["r"]
        st0 = r0["st"]
        return self._dispatch_prepared(
            entries, n_slots=max(len(entries), min_slots or 1),
            track=r0["track_assignments"],
            zone_key=st0.vocab.key_id[L.ZONE],
            ct_key=st0.vocab.key_id[L.CAPACITY_TYPE], t0=t0, mesh=mesh,
            registry=registry,
        )

    def _dispatch_prepared(
        self, entries, *, n_slots: int, track: bool, zone_key: int,
        ct_key: int, t0: float, mesh=None, registry=None,
    ) -> "PendingMegaSolve":
        """Stack + dispatch prepared entries (shared by the request path and
        :meth:`solve_many_prepared`); validates the one-bucket invariant."""
        reqs = [e["r"] for e in entries]
        dims0 = entries[0]["dims"]
        if not all(e["dims"] == dims0 for e in entries) or any(
            r["st"].vocab.key_id[L.ZONE] != zone_key
            or r["st"].vocab.key_id[L.CAPACITY_TYPE] != ct_key
            or r["track_assignments"] != track
            for r in reqs
        ):
            # mis-bucketed flush (caller raced a bucket-state change): a
            # typed error the collector degrades to serial dispatches on —
            # never an opaque crash fanned to every RPC in the batch
            raise MegaBucketMismatch("requests span megabatch buckets")
        NR, Z = dims0["NR"], dims0["Z"]
        n_dev = _mesh_size(mesh)
        if not mesh_shardable(mesh):
            # padding one-slot-per-chip would compile a program past the
            # rung ladder; the scheduler gates these meshes onto the serial
            # path (mesh_serial), so only a direct caller can land here
            raise MegaBucketMismatch(
                f"{n_dev}-device mesh exceeds MEGA_MAX_SLOTS="
                f"{MEGA_MAX_SLOTS}; sharded megabatch unavailable")
        mega_key = _dims_key(dims0) + _mega_key_tail(
            n_slots, zone_key, ct_key, mesh)

        B = len(entries)
        B_pad = _mega_rung(n_slots, n_dev)
        if B > B_pad:
            # an awkward device count's largest in-ladder rung can sit
            # below the caller's flush size (24 chips cap at 24 slots) —
            # a mis-sized flush must degrade to serial, not under-pad
            raise MegaBucketMismatch(
                f"{B} entries exceed the {B_pad}-slot sharded rung of a "
                f"{n_dev}-device mesh")
        padded = entries + [entries[0]] * (B_pad - B)

        if mesh is not None:
            # sharded megabatch: the slot axis (dim 0 of every stacked
            # array) shards one-slot-per-chip over the flattened mesh
            # (parallel/mesh.py slot_mesh); trailing axes replicate, so a
            # slot's feasibility+scan run entirely on its own device — the
            # jitted kernel partitions from this input placement alone, no
            # cross-slot collectives by construction.  put_sharded keeps
            # the multi-process case honest (each host contributes only
            # its addressable — contiguous, host-major — slot shards).
            from ..parallel.distributed import put_sharded
            from ..parallel.mesh import slot_sharding

            slot_sh = slot_sharding(mesh)

        def _stack(vals):
            # slots built from one shared base (the consolidation sweep)
            # carry the SAME array object in most positions — broadcast the
            # batch axis instead of materializing B host copies (device_put
            # makes it contiguous once, at transfer)
            first = vals[0]
            if all(v is first for v in vals[1:]):
                arr = np.asarray(first)
                out = np.broadcast_to(arr, (len(vals),) + arr.shape)
            else:
                out = np.stack(vals)
            if mesh is not None:
                return put_sharded(out, slot_sh)
            return jnp.asarray(out)

        consts_b = {
            k: _stack([e["np_consts"][k] for e in padded])
            for k in entries[0]["np_consts"]
        }
        feas_b = {
            k: _stack([e["feas"][k] for e in padded])
            for k in entries[0]["feas"]
        }
        init_b = tuple(
            _stack([e["np_init"][i] for e in padded])
            for i in range(len(entries[0]["np_init"]))
        )

        # per-request trace stamps: the shared device phase is recorded on
        # EVERY request's trace as a pre-closed "megabatch" span carrying its
        # slot index and the batch occupancy (obs: per-slot attribution of a
        # shared dispatch)
        t_starts = [e["r"]["trace"].now() for e in entries]
        carry_b, ys_b = _run_scan_many(  # async: enqueued, not fenced
            consts_b, feas_b, init_b, NR, Z, track, zone_key, ct_key,
        )
        return PendingMegaSolve(
            solver=self, entries=entries, carry_b=carry_b, ys_b=ys_b,
            t0=t0, t_starts=t_starts, track=track, B=B, B_pad=B_pad,
            mega_key=mega_key, mesh=mesh, registry=registry,
        )

    def solve_many(
        self,
        requests: Sequence[dict],
        *,
        min_slots: Optional[int] = None,
        mesh=None,
    ) -> List[object]:
        """Synchronous megabatch: :meth:`solve_many_async` + the one
        batch-wide fence.  Returns one entry per request IN ORDER: a
        :class:`TpuSolveOutput`, or the Exception that request alone hit
        (``SlotsExhausted`` under the compile-behind contract) — a bad slot
        must not poison its batchmates.  Per-request ``solve_ms`` is the
        megabatch wall time (dispatch→fence); callers wanting
        enqueue→respond latency stamp it themselves (service/server.py
        SolvePipeline does)."""
        if not requests:
            return []
        return self.solve_many_async(
            requests, min_slots=min_slots, mesh=mesh).results()

    def solve_delta(
        self,
        prev: "SolveResult",
        added: Sequence = (),
        removed: Sequence[str] = (),
        iced: Sequence[object] = (),
        *,
        provisioners,
        instance_types,
        daemonsets: Sequence = (),
        unavailable=None,
        max_delta_frac: Optional[float] = None,
        force_full: bool = False,
        tensorize_cache=None,
        registry=None,
        trace=None,
    ):
        """Warm-start delta solve: reuse ``prev``'s assignment and solve only
        the displaced subproblem (see solver/warmstart.py for the tiering
        and guards).  ``added`` are new pods, ``removed`` pod names leaving,
        ``iced`` newly unavailable offerings or reclaimed node names.

        The displaced-subproblem scan is SEEDED from the previous
        assignment: the surviving nodes (pods seated) become the existing-
        node tensors, so residual capacity, selector counts, zone counters
        and provisioner usage all start from the previous solution.  Passing
        a :class:`~karpenter_tpu.models.tensorize.TensorizeCache` reuses its
        catalog-side :class:`TensorizeContext` across the chain — the
        sub-millisecond tensorize the delta path rides.

        Consumes ``prev`` (node objects and assignment dict are carried
        forward, not copied).  Returns a ``DeltaOutcome``.  Device-
        expressible batches only — scheduler-level callers use
        :meth:`BatchScheduler.solve_delta`, which brings the full fallback
        ladder."""
        from ..models.tensorize import tensorize as _tensorize
        from . import warmstart

        def _tz(pods, unavail):
            if tensorize_cache is not None:
                st, _tier = tensorize_cache.tensorize(
                    pods, provisioners, instance_types,
                    daemonsets=daemonsets, unavailable=unavail,
                )
                return st
            return _tensorize(pods, provisioners, instance_types,
                              daemonsets=daemonsets, unavailable=unavail)

        def _solve(pods, existing, unavail):
            st = _tz(pods, unavail)
            out = self.solve(
                st, existing_nodes=existing,
                max_nodes=len(existing) + len(pods), trace=trace,
            )
            return out.result

        return warmstart.delta_solve(
            prev, added, removed, iced,
            solve_displaced=_solve, solve_full=_solve,
            max_delta_frac=max_delta_frac, registry=registry,
            unavailable=unavailable, force_full=force_full,
        )

    # ---- result extraction ---------------------------------------------
    # ktlint: fence extraction reads the whole carry back to host — it runs
    # strictly after the fence, on already-transferred results
    def _extract(
        self, st, carry, ys, existing_nodes, NE, solve_ms, compile_ms
    ) -> TpuSolveOutput:
        (res, row_zone, row_dom, row_cand, row_price, selcnt, active,
         n_used, zc, tot, prov_used, infeasible) = [np.asarray(x) for x in carry]
        n_used = int(n_used)

        new_nodes: List[SimNode] = []
        slot_to_node: Dict[int, SimNode] = {}
        NE_pad = max(1, NE)
        for si in range(NE, n_used):
            ci = int(row_cand[si])
            if ci < 0 or not active[si]:
                continue
            prov_name, type_name = st.cand_names[ci]
            zone = st.zone_names[int(row_zone[si])] if st.zone_names else ""
            node = SimNode(
                instance_type=type_name,
                provisioner=prov_name,
                zone=zone,
                capacity_type=self._ct_of_dom(st, int(row_dom[si])),
                price=float(row_price[si]),
                allocatable={
                    st.vocab.resources[r]: float(st.cand_alloc[ci, r])
                    for r in range(st.cand_alloc.shape[1])
                },
                existing=False,
            )
            node.stamp_labels()
            new_nodes.append(node)
            slot_to_node[si] = node

        # snapshots: placements must not leak into the caller's node objects;
        # the placed snapshots are returned (existing_nodes) so retry waves
        # can chain on them without double-booking capacity
        snap_existing = [n.snapshot() for n in existing_nodes]
        for ni, node in enumerate(snap_existing):
            slot_to_node[ni] = node

        assignments: Dict[str, str] = {}
        infeasible_map: Dict[str, str] = {}
        node_groups: Optional[Dict[int, set]] = None
        if ys is not None:
            takes = np.asarray(ys)  # [G, NR]
            node_groups = {}
            for gi, g in enumerate(st.groups):
                placed_slots = np.nonzero(takes[gi])[0]
                pod_iter = iter(g.pods)
                for si in placed_slots:
                    node = slot_to_node.get(int(si))
                    if node is not None:
                        node_groups.setdefault(id(node), set()).add(gi)
                    for _ in range(int(takes[gi, si])):
                        try:
                            pod = next(pod_iter)
                        except StopIteration:
                            break
                        assignments[pod.name] = node.name if node else f"slot-{si}"
                        if node is not None:
                            node.pods.append(pod)
                for pod in pod_iter:
                    infeasible_map[pod.name] = "solver: no feasible placement"
        else:
            takes = None
            for gi, g in enumerate(st.groups):
                k = int(infeasible[gi])
                for pod in g.pods[len(g.pods) - k:]:
                    infeasible_map[pod.name] = "solver: no feasible placement"

        # cost-neutral coalescing: merge small new nodes into larger types at
        # <= the same price (solver/coalesce.py — the scan buys each group's
        # tail at that group's step, so fragments accumulate across groups;
        # node count is operational load even when the $ match)
        from .coalesce import apply_coalesce

        used_rows = {}
        for si, node in slot_to_node.items():
            if si >= NE:  # slots >= NE are exactly the new_nodes entries
                ci = int(row_cand[si])
                used_rows[id(node)] = (
                    np.asarray(st.cand_alloc[ci], dtype=np.float64)
                    - np.asarray(res[si], dtype=np.float64)
                )
        new_nodes = apply_coalesce(st, new_nodes, used_rows, node_groups,
                                   assignments)

        result = SolveResult(
            nodes=new_nodes,
            assignments=assignments,
            infeasible=infeasible_map,
            existing_nodes=snap_existing,
            solve_ms=solve_ms,
        )
        return TpuSolveOutput(
            result=result, takes=takes, n_used=n_used,
            solve_ms=solve_ms, compile_ms=compile_ms,
        )

    @staticmethod
    def _ct_of_dom(st, di: int) -> str:
        # tensorize builds domains zone-major: d = z * |ct| + ct_index
        n_ct = max(1, len(st.ct_names))
        if di < 0:
            return ""
        return st.ct_names[di % n_ct]


class PendingTpuSolve:
    """Handle for an async-dispatched device solve (``TpuSolver.solve_async``).

    ``result()`` performs the honest one-RTT D2H fence, then extraction.
    The published ``solve_ms`` spans dispatch start → fence completion, so
    it keeps exactly one tunnel RTT by construction and honestly includes
    any device queue wait behind an earlier in-flight batch (the
    caller-visible latency of the pipelined solve).  ``result()`` is
    idempotent; the slot-exhaustion retry semantics match ``solve``
    (including ``raise_on_exhaust`` for the compile-behind contract).
    """

    def __init__(self, solver, st, existing_nodes, NE, carry, ys, t0, track,
                 est_dims, full_dims, full_nr, raise_on_exhaust,
                 solve_kwargs, trace=NULL_TRACE) -> None:
        self.solver = solver
        self.trace = trace
        self.st = st
        self.existing_nodes = existing_nodes
        self.NE = NE
        self.carry = carry
        self.ys = ys
        self.t0 = t0
        self.track = track
        self.est_dims = est_dims
        self.full_dims = full_dims
        self.full_nr = full_nr
        self.raise_on_exhaust = raise_on_exhaust
        self.solve_kwargs = solve_kwargs
        self._out: Optional[TpuSolveOutput] = None

    # ktlint: fence result() IS the async handle's one-RTT D2H fence
    def result(self) -> TpuSolveOutput:
        if self._out is not None:
            return self._out
        s = self.solver
        with self.trace.span("device_fence"):
            if s._faults:
                effect = s._faults.fire("fence")  # device_hang raises here
                if effect is not None and effect.kind == "slow_fence":
                    s._faults.sleep(effect)
            np.asarray(self.carry[7])  # the one-RTT D2H fence
        elapsed_ms = (time.perf_counter() - self.t0) * 1000.0
        s._mark_ready(_dims_key(self.full_dims if self.full_nr
                                else self.est_dims))
        # slot-exhaustion retry: the async handle resolves to a synchronous
        # full-budget re-solve via the same shared protocol as solve()
        retried = s._maybe_retry_exhausted(
            self.carry, self.est_dims, self.full_dims, self.full_nr,
            self.raise_on_exhaust,
            lambda: s.solve(self.st, full_nr=True, **self.solve_kwargs),
        )
        if retried is not None:
            self._out = retried
            return retried
        with self.trace.span("extract"):
            self._out = s._extract(
                self.st, self.carry, self.ys if self.track else None,
                self.existing_nodes, self.NE, elapsed_ms, elapsed_ms,
            )
        return self._out


class PendingMegaSolve:
    """Handle for an async-dispatched megabatch (``solve_many_async``):
    ``results()`` performs the ONE batch-wide D2H fence — a PER-HOST fence
    on a meshed dispatch: only the ``jax.process_index()``-addressable
    slot shards are read back (:func:`read_slot_rows`), so on a
    multi-process mesh each serving process pays D2H for exactly the slots
    it owns instead of DCN latency for the whole batch — then per-slot
    extraction of the owned slots.  Slots another host owns resolve to a
    typed :class:`~karpenter_tpu.parallel.forward.SlotNotOwned` in their
    position (the per-slot boxed-outcome contract); the serving layer's
    forwarding shim routes those to the owning host.  Idempotent; per-slot
    slot-exhaustion semantics match ``solve_many``."""

    def __init__(self, solver, entries, carry_b, ys_b, t0, t_starts, track,
                 B, B_pad, mega_key, mesh=None, registry=None) -> None:
        self.solver = solver
        self.entries = entries
        self.carry_b = carry_b
        self.ys_b = ys_b
        self.t0 = t0
        self.t_starts = t_starts
        self.track = track
        self.B = B
        self.B_pad = B_pad
        self.mega_key = mega_key
        #: the dispatch's mesh: the per-slot exhausted retry must re-solve
        #: on the MESHED full-budget program (the only one the meshed warm
        #: ladder covers), like the sibling retry sites in solve() and
        #: PendingTpuSolve
        self.mesh = mesh
        self.registry = registry
        #: per-host fence accounting, populated by results(): bytes this
        #: process actually read vs what a whole-batch readback would
        #: have, and the [start, stop) slot range it owns
        self.fence_bytes_read = 0
        self.fence_bytes_total = 0
        self.owned_slots: Tuple[int, int] = (0, B_pad)
        self._outputs: Optional[List[object]] = None

    # ktlint: fence the megabatch handle's one D2H read completes ALL
    # locally-owned request slots (the whole point: B solves, one device
    # round trip per host — addressable shards only on a meshed dispatch)
    def results(self) -> List[object]:
        if self._outputs is not None:
            return self._outputs
        s = self.solver
        # per-host fence (ISSUE 14): meshed dispatches read ONLY the
        # process-addressable slot shards of the carry — single-process
        # meshes own every shard (byte-identical to the whole read), and
        # KT_MULTIHOST=0 forces the legacy whole-batch readback
        per_host = self.mesh is not None and multihost_fence_enabled()
        owners: Optional[tuple] = None
        if per_host:
            from ..parallel.mesh import local_slot_range, multihost

            if multihost(self.mesh):
                from ..parallel.mesh import slot_hosts

                owners = slot_hosts(self.mesh, self.B_pad)
                self.owned_slots = local_slot_range(self.mesh, self.B_pad)
        # fence element 7 (n_used) first so elapsed_ms spans dispatch ->
        # fence completion exactly like the single-solve handle; the
        # remaining carry reads are post-fence extraction traffic
        rows7, br, bt = read_slot_rows([self.carry_b[7]],
                                       local_only=per_host)
        elapsed_ms = (time.perf_counter() - self.t0) * 1000.0
        s._mark_ready(self.mega_key)
        rest = [x for k, x in enumerate(self.carry_b) if k != 7]
        if self.track:
            rest.append(self.ys_b)
        rows_rest, br2, bt2 = read_slot_rows(rest, local_only=per_host)
        self.fence_bytes_read = br + br2
        self.fence_bytes_total = bt + bt2
        if per_host and self.registry is not None:
            from ..metrics import MULTIHOST_FENCE_BYTES

            c = self.registry.counter(MULTIHOST_FENCE_BYTES)
            c.inc({"scope": "read"}, value=float(self.fence_bytes_read))
            c.inc({"scope": "whole"}, value=float(self.fence_bytes_total))
        carry_rows = list(rows_rest[:len(self.carry_b) - 1])
        carry_rows.insert(7, rows7[0])
        ys_rows = rows_rest[-1] if self.track else None
        lo, hi = self.owned_slots
        outputs: List[object] = []
        for i, e in enumerate(self.entries):
            r = e["r"]
            trace = r["trace"] or NULL_TRACE
            trace.record(
                "megabatch", self.t_starts[i], trace.now(),
                slot=i, slots=self.B_pad, occupied=self.B,
            )
            if not (lo <= i < hi):
                # another host's slot: this process holds no shard of it.
                # A typed, boxed per-slot outcome — the serving layer's
                # forwarding shim (parallel/forward.py) re-routes it to
                # the owning host over the fleet transport
                from ..parallel.forward import SlotNotOwned

                outputs.append(SlotNotOwned(
                    i, owners[i] if owners else -1))
                continue
            carry_i = tuple(x[i] for x in carry_rows)
            ys_i = ys_rows[i] if ys_rows is not None else None
            try:
                retried = s._maybe_retry_exhausted(
                    carry_i, e["est_dims"], e["full_dims"], e["full_nr"],
                    r["raise_on_exhaust"],
                    lambda r=r: s.solve(
                        r["st"], existing_nodes=r["existing_nodes"],
                        max_nodes=r["max_nodes"],
                        track_assignments=r["track_assignments"],
                        mesh=self.mesh, full_nr=True,
                    ),
                )
            # ktlint: allow[KT005] per-slot boxed outcome: the exhausted
            # slot's exception is returned in its slot so batchmates still
            # get their results; the caller re-raises per request
            except Exception as err:
                outputs.append(err)
                continue
            if retried is not None:
                outputs.append(retried)
                continue
            with trace.span("extract", slot=i):
                outputs.append(s._extract(
                    r["st"], carry_i, ys_i, r["existing_nodes"], e["NE"],
                    elapsed_ms, elapsed_ms,
                ))
        if owners is not None and self.registry is not None:
            from ..metrics import MULTIHOST_SLOTS
            from ..parallel.forward import SlotNotOwned

            n_foreign = sum(1 for o in outputs
                            if isinstance(o, SlotNotOwned))
            slots_c = self.registry.counter(MULTIHOST_SLOTS)
            slots_c.inc({"ownership": "foreign"}, value=float(n_foreign))
            slots_c.inc({"ownership": "owned"},
                        value=float(len(outputs) - n_foreign))
        self._outputs = outputs
        return outputs


_default_solver = TpuSolver()


def solve_tensors(st: SolveTensors, **kw) -> TpuSolveOutput:
    return _default_solver.solve(st, **kw)
