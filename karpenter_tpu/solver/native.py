"""ctypes binding for the native FFD core (native/ffd.cpp).

The low-latency tier: small unconstrained batches solve in microseconds here;
the scheduler's "auto" policy routes big or topology-constrained batches to
the TPU solver instead.  Feasibility is computed with numpy using the exact
packed-bitmask semantics of the device path (models/vocab.py).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..models import labels as L
from ..models.tensorize import SolveTensors
from .types import SimNode, SolveResult

_SO = Path(__file__).with_name("_native.so")
_SRC = Path(__file__).resolve().parents[2] / "native" / "ffd.cpp"

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    stale = (
        _SRC.exists()
        and (not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime)
    )
    if stale:
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-Wall", "-std=c++17",
             "-o", str(_SO), str(_SRC)],
            check=True,
        )
    lib = ctypes.CDLL(str(_SO))
    lib.kt_ffd_solve.restype = ctypes.c_int
    lib.kt_version.restype = ctypes.c_char_p
    _lib = lib
    return lib


def available() -> bool:
    try:
        return _load() is not None
    except Exception:
        return False


def version() -> str:
    return _load().kt_version().decode()


# ---------------------------------------------------------------------------
# numpy feasibility (mirrors solver.tpu.compute_feasibility bit-for-bit)
# ---------------------------------------------------------------------------


def feasibility_numpy(st: SolveTensors):
    G, C = st.G, max(1, st.C)
    K = st.pm.shape[1]
    zone_key = st.vocab.key_id[L.ZONE]
    ct_key = st.vocab.key_id[L.CAPACITY_TYPE]

    lab = np.ones((G, C), dtype=bool)
    for k in range(K):
        if not st.key_check[k]:
            continue
        words = st.pm[:, k, :][:, st.cand_vw[:, k]]          # [G, C]
        bits = (words >> st.cand_vb[None, :, k].astype(np.uint32)) & 1
        lab &= bits.astype(bool)
    fit = np.all(
        (st.requests[:, None, :] <= st.cand_alloc[None, :, :] + 1e-6)
        | (st.requests[:, None, :] <= 0),
        axis=2,
    )
    gp = st.gp_ok[np.arange(st.G)[:, None], st.cand_prov[None, :]]
    F = lab & fit & gp

    zw = st.pm[:, zone_key, :][:, st.dom_vw[:, 0]]
    zok = ((zw >> st.dom_vb[None, :, 0].astype(np.uint32)) & 1).astype(bool)
    cw = st.pm[:, ct_key, :][:, st.dom_vw[:, 1]]
    cok = ((cw >> st.dom_vb[None, :, 1].astype(np.uint32)) & 1).astype(bool)
    return F, (zok & cok)


def has_topology(st: SolveTensors) -> bool:
    """Groups the native tier can't express: positive pod-affinity (modes
    A/B/C live on the device / oracle).  Zone/hostname spread and
    anti-affinity ARE handled natively (ffd.cpp place_constrained)."""
    import numpy as _np

    return bool(
        _np.any(st.g_zone_paff >= 0)
        or _np.any(st.g_host_paff >= 0)
    )


# ---------------------------------------------------------------------------
# solve
# ---------------------------------------------------------------------------


def solve_tensors_native(
    st: SolveTensors,
    existing_nodes: Sequence[SimNode] = (),
    max_nodes: Optional[int] = None,
) -> SolveResult:
    import time

    lib = _load()
    t0 = time.perf_counter()
    G, C, D, R = st.G, max(1, st.C), st.D, st.R
    NE = len(existing_nodes)
    NR = max(1, (max_nodes if max_nodes is not None else NE + int(st.counts.sum())))

    F, dom_ok = feasibility_numpy(st)
    F = np.ascontiguousarray(F, dtype=np.uint8)
    dom_ok = np.ascontiguousarray(dom_ok, dtype=np.uint8)

    ex_res = np.zeros((max(1, NE), R), dtype=np.float32)
    ex_ok = np.zeros((G, max(1, NE)), dtype=np.uint8)
    for ni, node in enumerate(existing_nodes):
        ex_res[ni] = st.vocab.resources_to_row(node.remaining()).astype(np.float32)
        for gi, g in enumerate(st.groups):
            rep = g.pods[0]
            ex_ok[gi, ni] = (
                not any(t.blocks(rep.tolerations) for t in node.taints)
                and g.requirements.compatible(node.labels) is None
            )

    price = np.where(np.isinf(st.cand_price), np.float32(3.0e38), st.cand_price)
    price = np.ascontiguousarray(price, dtype=np.float32)
    avail = np.ascontiguousarray(st.cand_avail, dtype=np.uint8)
    req = np.ascontiguousarray(st.requests, dtype=np.float32)
    counts = np.ascontiguousarray(st.counts, dtype=np.int32)
    alloc = np.ascontiguousarray(st.cand_alloc, dtype=np.float32)

    slot_res = np.zeros((NR, R), dtype=np.float32)
    slot_cand = np.zeros(NR, dtype=np.int32)
    slot_dom = np.zeros(NR, dtype=np.int32)
    slot_price = np.zeros(NR, dtype=np.float32)
    takes = np.zeros((G, NR), dtype=np.int32)
    n_used = np.zeros(1, dtype=np.int32)
    infeasible = np.zeros(G, dtype=np.int32)

    c = lambda a: a.ctypes.data_as(ctypes.c_void_p)
    lib.kt_ffd_solve(
        G, C, D, R, NE, NR,
        c(req), c(counts), c(F), c(dom_ok), c(alloc), c(price), c(avail),
        c(ex_res), c(ex_ok),
        c(slot_res), c(slot_cand), c(slot_dom), c(slot_price), c(takes),
        n_used.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        c(infeasible),
    )

    # ---- extraction (same shape as TpuSolver._extract) -----------------
    nused = int(n_used[0])
    nodes: List[SimNode] = []
    slot_to_node: Dict[int, SimNode] = {}
    for ni, node in enumerate(existing_nodes):
        slot_to_node[ni] = node
    n_ct = max(1, len(st.ct_names))
    for s in range(NE, nused):
        ci = int(slot_cand[s])
        if ci < 0:
            continue
        prov_name, type_name = st.cand_names[ci]
        di = int(slot_dom[s])
        node = SimNode(
            instance_type=type_name,
            provisioner=prov_name,
            zone=st.zone_names[di // n_ct] if st.zone_names else "",
            capacity_type=st.ct_names[di % n_ct] if st.ct_names else "",
            price=float(slot_price[s]),
            allocatable={
                st.vocab.resources[r]: float(st.cand_alloc[ci, r]) for r in range(R)
            },
        )
        nodes.append(node)
        slot_to_node[s] = node

    assignments: Dict[str, str] = {}
    infeasible_map: Dict[str, str] = {}
    for gi, g in enumerate(st.groups):
        pod_iter = iter(g.pods)
        for s in np.nonzero(takes[gi])[0]:
            node = slot_to_node.get(int(s))
            for _ in range(int(takes[gi, s])):
                pod = next(pod_iter, None)
                if pod is None:
                    break
                assignments[pod.name] = node.name if node else f"slot-{s}"
                if node is not None:
                    node.pods.append(pod)
        for pod in pod_iter:
            infeasible_map[pod.name] = "native solver: no feasible placement"

    return SolveResult(
        nodes=nodes,
        assignments=assignments,
        infeasible=infeasible_map,
        existing_nodes=list(existing_nodes),
        solve_ms=(time.perf_counter() - t0) * 1000.0,
    )
