"""ctypes binding for the native FFD core (native/ffd.cpp).

The low-latency tier: small unconstrained batches solve in microseconds here;
the scheduler's "auto" policy routes big or topology-constrained batches to
the TPU solver instead.  Feasibility is computed with numpy using the exact
packed-bitmask semantics of the device path (models/vocab.py).
"""

from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..models import labels as L
from ..models.tensorize import SolveTensors
from .types import SimNode, SolveResult, node_classes

_SRC = Path(__file__).resolve().parents[2] / "native" / "ffd.cpp"

_lib = None

#: kt_ffd_solve arity: 9 dims + 23 input arrays + 7 output arrays.  Declared
#: so a source/binding mismatch fails loudly (ctypes arity check) instead of
#: corrupting the stack.
_N_DIMS = 9
_N_ARRAYS = 30


_CXX_FLAGS = ("-O3", "-fPIC", "-shared", "-Wall", "-std=c++17")


def _so_path() -> Path:
    """Build artifact keyed on the source content hash (and compile flags):
    a fresh checkout (or an edited ffd.cpp, or a flags change) always
    compiles its own binary; stale binaries from other source revisions are
    never loaded (mtimes are unreliable on fresh clones — every file gets
    checkout time)."""
    import hashlib

    h = hashlib.sha256(
        _SRC.read_bytes() + " ".join(_CXX_FLAGS).encode()
    ).hexdigest()[:12]
    return Path(__file__).with_name(f"_native_{h}.so")


def _load():
    global _lib
    if _lib is not None:
        return _lib
    so = _so_path()
    if not so.exists():
        # compile to a private temp path, then atomically publish: concurrent
        # processes (operator + bench, parallel pytest) must never CDLL a
        # half-written ELF
        import os
        import tempfile

        fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(so.parent))
        os.close(fd)
        try:
            subprocess.run(
                ["g++", *_CXX_FLAGS, "-o", tmp, str(_SRC)],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, so)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    lib = ctypes.CDLL(str(so))
    lib.kt_ffd_solve.restype = ctypes.c_int
    lib.kt_ffd_solve.argtypes = (
        [ctypes.c_int] * _N_DIMS + [ctypes.c_void_p] * _N_ARRAYS
    )
    lib.kt_version.restype = ctypes.c_char_p
    _lib = lib
    return lib


def available() -> bool:
    # the three real failure shapes: g++ missing / CDLL of a bad ELF
    # (OSError, incl. FileNotFoundError), a failed compile
    # (CalledProcessError), and a compiled .so whose exported symbols don't
    # match this binding (AttributeError from ctypes symbol lookup)
    try:
        return _load() is not None
    except (OSError, subprocess.CalledProcessError, AttributeError):
        return False


def version() -> str:
    return _load().kt_version().decode()


# ---------------------------------------------------------------------------
# numpy feasibility (mirrors solver.tpu.compute_feasibility bit-for-bit)
# ---------------------------------------------------------------------------


def feasibility_numpy(st: SolveTensors):
    G, C = st.G, max(1, st.C)
    K = st.pm.shape[1]
    zone_key = st.vocab.key_id[L.ZONE]
    ct_key = st.vocab.key_id[L.CAPACITY_TYPE]

    lab = np.ones((G, C), dtype=bool)
    for k in range(K):
        if not st.key_check[k]:
            continue
        words = st.pm[:, k, :][:, st.cand_vw[:, k]]          # [G, C]
        bits = (words >> st.cand_vb[None, :, k].astype(np.uint32)) & 1
        lab &= bits.astype(bool)
    fit = np.all(
        (st.requests[:, None, :] <= st.cand_alloc[None, :, :] + 1e-6)
        | (st.requests[:, None, :] <= 0),
        axis=2,
    )
    gp = st.gp_ok[np.arange(st.G)[:, None], st.cand_prov[None, :]]
    F = lab & fit & gp

    zw = st.pm[:, zone_key, :][:, st.dom_vw[:, 0]]
    zok = ((zw >> st.dom_vb[None, :, 0].astype(np.uint32)) & 1).astype(bool)
    cw = st.pm[:, ct_key, :][:, st.dom_vw[:, 1]]
    cok = ((cw >> st.dom_vb[None, :, 1].astype(np.uint32)) & 1).astype(bool)
    return F, (zok & cok)


def has_topology(st: SolveTensors) -> bool:
    """Groups the native tier can't express: positive pod-affinity (modes
    A/B/C live on the device / oracle) and capacity-type spread (routes the
    whole batch to the oracle — scheduler.batch_needs_oracle).  Zone/hostname
    spread and anti-affinity ARE handled natively (ffd.cpp place_constrained)
    — the binding marshals ex_zone/ex_selcnt/zc0 so the constrained path sees
    real existing-cluster topology state."""
    import numpy as _np

    return bool(
        _np.any(st.g_zone_paff >= 0)
        or _np.any(st.g_host_paff >= 0)
        or st.has_ct_spread
    )


def existing_compat(
    st: SolveTensors, existing_nodes: Sequence[SimNode]
) -> np.ndarray:
    """[G, NE] uint8 — may pods of group g run on existing node n
    (tolerations vs taints + merged requirements vs labels)?

    Two-level memo, the same scheme as consolidation.compat_matrix: a
    group's side of the answer is its merged requirements + tolerations; a
    node's side is its taints plus only the label keys any group's
    requirements reference — a per-node hostname label must not split a
    uniform fleet into NE classes when nothing selects on hostname.  The
    naive O(G x NE) requirement-algebra walk was ~15 s per consolidation
    what-if at 4k groups x 1k nodes; the memo answers once per
    (signature, class) pair."""
    G, NE = st.G, len(existing_nodes)
    g_sig_idx = np.empty(G, dtype=np.int64)
    sig_rep: List[int] = []  # representative group index per signature
    sig_of: Dict[tuple, int] = {}
    relevant_keys: set = set()
    for gi, g in enumerate(st.groups):
        key = (g.requirements.signature(), tuple(g.pods[0].tolerations))
        si = sig_of.get(key)
        if si is None:
            si = sig_of[key] = len(sig_rep)
            sig_rep.append(gi)
            relevant_keys.update(g.requirements)
        g_sig_idx[gi] = si
    cls_idx, cls_rep = node_classes(existing_nodes, relevant_keys)
    n_cls_idx = np.asarray(cls_idx, dtype=np.int64)
    table = np.zeros((len(sig_rep), len(cls_rep)), dtype=np.uint8)
    for si, gi in enumerate(sig_rep):
        g = st.groups[gi]
        rep = g.pods[0]
        for ci, node in enumerate(cls_rep):
            table[si, ci] = (
                not any(t.blocks(rep.tolerations) for t in node.taints)
                and g.requirements.compatible(node.labels) is None
            )
    return table[g_sig_idx[:, None], n_cls_idx[None, :]]


# ---------------------------------------------------------------------------
# solve
# ---------------------------------------------------------------------------


def solve_tensors_native(
    st: SolveTensors,
    existing_nodes: Sequence[SimNode] = (),
    max_nodes: Optional[int] = None,
) -> SolveResult:
    import time

    lib = _load()
    t0 = time.perf_counter()
    G, C, D, R = st.G, max(1, st.C), st.D, st.R
    S = st.S
    Z = max(1, st.n_zones)
    P = st.prov_limits.shape[0]
    NE = len(existing_nodes)
    NR = max(1, NE, (max_nodes if max_nodes is not None else NE + int(st.counts.sum())))

    F, dom_ok = feasibility_numpy(st)
    F = np.ascontiguousarray(F, dtype=np.uint8)
    dom_ok = np.ascontiguousarray(dom_ok, dtype=np.uint8)

    # ---- existing-node state (same semantics as TpuSolver.prepare) ------
    zone_index = {z: i for i, z in enumerate(st.zone_names)}
    prov_index = {n: i for i, n in enumerate(st.prov_names)}
    ex_res = np.zeros((max(1, NE), R), dtype=np.float32)
    ex_zone = np.zeros(max(1, NE), dtype=np.int32)
    ex_selcnt = np.zeros((max(1, NE), S), dtype=np.int32)
    ex_ok = np.zeros((G, max(1, NE)), dtype=np.uint8)
    zc0 = np.zeros((S, Z), dtype=np.int32)
    prov_used0 = np.zeros((P, R), dtype=np.float32)
    # limits bind on raw machine CAPACITY (st.capacity_row) — same accounting
    # as the device solver and the oracle (fuzz seed 23)
    for ni, node in enumerate(existing_nodes):
        ex_res[ni] = st.vocab.resources_to_row(node.remaining()).astype(np.float32)
        ex_zone[ni] = zone_index.get(node.zone, 0)
        pi = prov_index.get(node.provisioner)
        if pi is not None:
            prov_used0[pi] += st.capacity_row(node.instance_type,
                                              node.allocatable)
    if NE and G:
        ex_ok[:, :] = existing_compat(st, existing_nodes)
    for si, (sel, _topo, _kind) in enumerate(st.selector_defs):
        for ni, node in enumerate(existing_nodes):
            n_match = sum(1 for p in node.pods if sel.matches(p.labels))
            ex_selcnt[ni, si] = n_match
            zc0[si, zone_index.get(node.zone, 0)] += n_match

    price = np.where(np.isinf(st.cand_price), np.float32(3.0e38), st.cand_price)
    price = np.ascontiguousarray(price, dtype=np.float32)
    avail = np.ascontiguousarray(st.cand_avail, dtype=np.uint8)
    req = np.ascontiguousarray(st.requests, dtype=np.float32)
    counts = np.ascontiguousarray(st.counts, dtype=np.int32)
    alloc = np.ascontiguousarray(st.cand_alloc, dtype=np.float32)
    g_zone_spread = np.ascontiguousarray(st.g_zone_spread, dtype=np.int32)
    g_zone_skew = np.ascontiguousarray(st.g_zone_skew, dtype=np.int32)
    g_host_spread = np.ascontiguousarray(st.g_host_spread, dtype=np.int32)
    g_host_cap = np.ascontiguousarray(st.g_host_cap, dtype=np.int32)
    g_zone_anti = np.ascontiguousarray(st.g_zone_anti, dtype=np.int32)
    sel_match = np.ascontiguousarray(st.g_sel_match, dtype=np.uint8)
    dom_zone = np.ascontiguousarray(st.dom_zone, dtype=np.int32)
    cand_prov = np.ascontiguousarray(st.cand_prov, dtype=np.int32)
    cand_cap = np.ascontiguousarray(st.cand_cap, dtype=np.float32)
    prov_limits = np.ascontiguousarray(st.prov_limits, dtype=np.float32)

    slot_res = np.zeros((NR, R), dtype=np.float32)
    slot_cand = np.zeros(NR, dtype=np.int32)
    slot_dom = np.zeros(NR, dtype=np.int32)
    slot_price = np.zeros(NR, dtype=np.float32)
    takes = np.zeros((G, NR), dtype=np.int32)
    n_used = np.zeros(1, dtype=np.int32)
    infeasible = np.zeros(G, dtype=np.int32)

    c = lambda a: a.ctypes.data_as(ctypes.c_void_p)
    lib.kt_ffd_solve(
        G, C, D, R, NE, NR, S, Z, P,
        c(req), c(counts), c(F), c(dom_ok), c(alloc), c(price), c(avail),
        c(ex_res), c(ex_ok), c(ex_zone), c(ex_selcnt),
        c(g_zone_spread), c(g_zone_skew), c(g_host_spread), c(g_host_cap),
        c(g_zone_anti), c(sel_match), c(dom_zone), c(zc0),
        c(cand_prov), c(cand_cap), c(prov_limits), c(prov_used0),
        c(slot_res), c(slot_cand), c(slot_dom), c(slot_price), c(takes),
        c(n_used), c(infeasible),
    )

    # ---- extraction (same shape as TpuSolver._extract) -----------------
    nused = int(n_used[0])
    nodes: List[SimNode] = []
    slot_to_node: Dict[int, SimNode] = {}
    # snapshots: placements must not leak into the caller's node objects;
    # the placed snapshots are returned (existing_nodes) so retry waves can
    # chain on them without double-booking capacity
    snap_existing = [n.snapshot() for n in existing_nodes]
    for ni, node in enumerate(snap_existing):
        slot_to_node[ni] = node
    n_ct = max(1, len(st.ct_names))
    for s in range(NE, nused):
        ci = int(slot_cand[s])
        if ci < 0:
            continue
        prov_name, type_name = st.cand_names[ci]
        di = int(slot_dom[s])
        node = SimNode(
            instance_type=type_name,
            provisioner=prov_name,
            zone=st.zone_names[di // n_ct] if st.zone_names else "",
            capacity_type=st.ct_names[di % n_ct] if st.ct_names else "",
            price=float(slot_price[s]),
            allocatable={
                st.vocab.resources[r]: float(st.cand_alloc[ci, r]) for r in range(R)
            },
        )
        node.stamp_labels()
        nodes.append(node)
        slot_to_node[s] = node

    assignments: Dict[str, str] = {}
    infeasible_map: Dict[str, str] = {}
    node_groups: Dict[int, set] = {}
    for gi, g in enumerate(st.groups):
        gp = g.pods
        base = 0
        for s in np.nonzero(takes[gi])[0]:
            take = int(takes[gi, s])
            chunk = gp[base:base + take]
            base += len(chunk)
            node = slot_to_node.get(int(s))
            if node is not None:
                node_groups.setdefault(id(node), set()).add(gi)
                node.pods.extend(chunk)
                nn = node.name
                for pod in chunk:
                    assignments[pod.name] = nn
            else:
                for pod in chunk:
                    assignments[pod.name] = f"slot-{int(s)}"
        for pod in gp[base:]:
            infeasible_map[pod.name] = "native solver: no feasible placement"

    # cost-neutral coalescing, same pass as the device tier (the cold-start
    # answer should match the warm tier's node-count quality — before this
    # the native tier served 20 nodes where the device tier served 16 on
    # bench config 1)
    from .coalesce import apply_coalesce

    used_rows = {}
    for s, node in slot_to_node.items():
        if s >= NE:  # slots >= NE are exactly the new nodes
            ci = int(slot_cand[s])
            used_rows[id(node)] = (
                np.asarray(st.cand_alloc[ci], dtype=np.float64)
                - np.asarray(slot_res[s], dtype=np.float64)
            )
    nodes = apply_coalesce(st, nodes, used_rows, node_groups, assignments)

    return SolveResult(
        nodes=nodes,
        assignments=assignments,
        infeasible=infeasible_map,
        existing_nodes=snap_existing,
        solve_ms=(time.perf_counter() - t0) * 1000.0,
    )
